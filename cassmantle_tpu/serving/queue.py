"""Continuous-batching coalescer: async requests -> fixed-shape device batches.

The reference scores each guess synchronously on the request path
(backend.py:303-317) and could not batch across players. Here concurrent
requests (guess scorings, image generations) land in an asyncio queue; a
collector drains up to the largest configured bucket or until
``max_delay_ms`` passes, then hands the batch to a single dispatch thread —
one thread per process so device dispatches serialize (one compiled graph
in flight per step) while the event loop stays free (SURVEY.md §7 stage 6,
hard part (d)). Bucketed batch sizes keep shapes static: a batch of 37
guesses pads to the 64 bucket, reusing the compiled graph.

Failure containment (the supervisor subsystem, ISSUE 2):

- **Backpressure**: a bounded queue; when full, ``submit`` fails fast and
  the caller degrades (skip-don't-crash, reference error semantics §5.3).
  While the supervisor reports degraded, the bound tightens to
  ``degraded_max_pending`` — a sick device gets a short queue, not a
  4096-deep pile of doomed work.
- **Per-request deadlines**: ``submit`` fails its future with
  :class:`DeadlineExceeded` when the deadline passes, whether the item is
  still queued or stuck inside a hung handler — awaiting callers never
  hang on a wedged XLA call.
- **Dispatch watchdog**: a handler that exceeds ``hang_timeout_s`` has
  wedged the dispatch thread (device calls hang rather than raise —
  utils/health.py). The batch's futures fail with
  :class:`DispatchTimeout`, the supervisor is flipped degraded, and the
  wedged thread is *disowned* (daemon) and replaced so later batches
  still dispatch.

Overload control (ISSUE 13; serving/overload.py):

- **Adaptive admission**: with an :class:`AdaptiveLimiter` wired
  (``admission=``), the effective pending bound is the AIMD limit
  driven by measured queue-wait + batch-service latency against a
  target, not the static ``max_pending``. Rejections raise
  :class:`OverloadShed` carrying a *computed* Retry-After (predicted
  wait = depth × observed per-item service time), and a submission
  whose predicted wait already exceeds its ``deadline_s`` is rejected
  immediately instead of expiring in the queue.
- **Priority tiers**: ``submit(priority=)`` with two classes.
  Interactive (player scoring, the default) dispatches ahead of
  background (round generation, reserve refill, bench); background is
  the first shed under pressure; and a starvation bound guarantees a
  background item still heads a batch after ``background_every``
  consecutive batches dispatched with background work pending — rounds
  keep rotating under sustained interactive load.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import queue as _thread_queue
import threading
import time
from typing import Callable, Generic, List, Optional, Sequence, TypeVar

from cassmantle_tpu.chaos import ChaosInjected, fault_point
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import current_ctx, run_with_ctx, tracer
from cassmantle_tpu.serving.overload import (
    PRIORITY_BACKGROUND,
    PRIORITY_INTERACTIVE,
    note_shed,
)
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

T = TypeVar("T")
R = TypeVar("R")

log = get_logger("queue")

# batch-size histogram bounds: the configured bucket ladder's shape
# (powers of two through the largest score bucket)
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048)


class QueueFull(Exception):
    pass


class QueueStopped(QueueFull):
    """The queue shut down with this item still pending."""


class OverloadShed(QueueFull):
    """Rejected by the adaptive admission controller — not a hard
    capacity wall but a *decision*, carrying the computed Retry-After
    the HTTP layer serves and the reason (overload / background /
    predicted_late / loop_lag / chaos). Subclasses QueueFull so legacy
    call sites that degrade on backpressure keep degrading."""

    def __init__(self, name: str, *, reason: str = "overload",
                 retry_after_s: float = 1.0) -> None:
        super().__init__(f"{name} ({reason}; retry in "
                         f"{retry_after_s:.1f}s)")
        self.queue_name = name
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(Exception):
    """A submitted item missed its per-request deadline."""


class DispatchTimeout(Exception):
    """The batch handler wedged the dispatch thread past the watchdog."""


class _HandlerWedged(Exception):
    """Internal watchdog signal: the RUNNING handler overran its hang
    deadline (distinct from a handler-raised TimeoutError, which must
    propagate per-item like any other handler exception)."""


class _DispatchWorker:
    """One DAEMON dispatch thread per process: device work serializes
    here. Daemon because a wedged XLA call cannot be cancelled, only
    disowned — ``replace()`` retires the stuck thread (it exits if its
    call ever returns), re-queues any jobs it hadn't started, and starts
    a fresh thread, without ever pinning process exit."""

    def __init__(self, name: str = "queue.dispatch_worker",
                 rank: int = 20) -> None:
        # docs/STATIC_ANALYSIS.md hierarchy: worker bookkeeping nests
        # inside nothing and may (in principle) precede supervisor state.
        # Stage-disaggregated serving (serving/stages.py) builds extra
        # workers under their own names/ranks (stage.encode_dispatch 21,
        # stage.decode_dispatch 22) so each stage dispatches devicework
        # independently instead of serializing on the process-global
        # worker.
        self.name = name
        self._lock = OrderedLock(name, rank=rank)
        self._jobs: Optional[_thread_queue.Queue] = None
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _loop(jobs: "_thread_queue.Queue") -> None:
        while True:
            job = jobs.get()
            if job is None:  # retired by replace()
                return
            fn, args, cf, started = job
            if not cf.set_running_or_notify_cancel():
                continue
            started.set()
            try:
                result = fn(*args)
            except BaseException as exc:  # noqa: BLE001 — carried to waiter
                cf.set_exception(exc)
            else:
                cf.set_result(result)

    def _ensure(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._jobs = _thread_queue.Queue()
            self._thread = threading.Thread(
                target=self._loop, args=(self._jobs,),
                daemon=True, name=f"cassmantle-{self.name}",
            )
            self._thread.start()

    def submit(self, fn: Callable, *args):
        """Returns (future, started_event). ``started`` distinguishes a
        handler that is actually RUNNING from one merely queued behind
        another queue's dispatch — the watchdog must only declare a wedge
        for the former."""
        with self._lock:
            self._ensure()
            cf: concurrent.futures.Future = concurrent.futures.Future()
            started = threading.Event()
            self._jobs.put((fn, args, cf, started))
            return cf, started

    def stop(self, timeout_s: float = 5.0) -> None:
        """Retire a DEDICATED worker's thread when its owning queue
        shuts down: send the retire sentinel, then join with a bounded
        timeout. Unbounded join would trade a thread leak for a
        shutdown hang when a handler is wedged in XLA; on overrun the
        thread is disowned exactly like ``replace()`` does (daemon, so
        it cannot pin process exit) — but counted and flight-recorded,
        because a teardown that abandons a live dispatch thread is the
        flaky-test / slow-drain shape the leak sentinel exists to
        catch. The process-global worker is never stopped: it is a
        process-lifetime singleton by contract."""
        with self._lock:
            jobs, thread = self._jobs, self._thread
            self._jobs = None
            self._thread = None
        if jobs is not None:
            jobs.put(None)  # retire when the current call returns
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout_s)
            if thread.is_alive():
                metrics.inc("dispatch.stop_overruns")
                flight_recorder.record("dispatch.stop_overrun",
                                       worker=self.name,
                                       timeout_s=timeout_s)
                log.warning(
                    "%s dispatch thread still running %.1fs after "
                    "stop; disowning it (wedged handler?)",
                    self.name, timeout_s)

    def replace(self) -> None:
        """Disown a wedged thread and start a fresh one. Jobs the old
        thread had not started move to the new thread; the in-flight call
        keeps its (already-failed) future and its eventual result is
        dropped."""
        with self._lock:
            old_jobs = self._jobs
            self._jobs = _thread_queue.Queue()
            if old_jobs is not None:
                while True:
                    try:
                        job = old_jobs.get_nowait()
                    except _thread_queue.Empty:
                        break
                    if job is not None:
                        self._jobs.put(job)
                old_jobs.put(None)  # retire the old thread when it unwedges
            self._thread = threading.Thread(
                target=self._loop, args=(self._jobs,),
                daemon=True, name=f"cassmantle-{self.name}",
            )
            self._thread.start()
            metrics.inc("dispatch.thread_replacements")


_dispatcher = _DispatchWorker()


class BatchingQueue(Generic[T, R]):
    """Coalesces ``submit`` calls into batched ``handler`` invocations.

    ``handler(items) -> results`` runs on the dispatch thread and must
    return one result per item (it pads internally to its bucket shapes).

    ``default_deadline_s`` bounds each submission end to end;
    ``hang_timeout_s`` arms the dispatch watchdog; ``supervisor`` (a
    :class:`~cassmantle_tpu.serving.supervisor.ServingSupervisor`)
    receives overrun notifications and drives the degraded admission
    bound ``degraded_max_pending``.
    """

    def __init__(
        self,
        handler: Callable[[List[T]], Sequence[R]],
        max_batch: int = 1024,
        max_delay_ms: float = 25.0,
        max_pending: int = 4096,
        name: str = "queue",
        default_deadline_s: Optional[float] = None,
        hang_timeout_s: Optional[float] = None,
        supervisor=None,
        degraded_max_pending: Optional[int] = None,
        dispatcher: Optional[_DispatchWorker] = None,
        admission=None,
        background_every: int = 8,
        on_dispatch_error: Optional[Callable[[BaseException], None]]
        = None,
    ) -> None:
        # ``dispatcher``: a dedicated _DispatchWorker for this queue.
        # Default is the process-global worker (device work serializes
        # there); the stage-disaggregated image path hands each stage
        # its own so encode/decode batches dispatch concurrently with
        # everything else (serving/stages.py).
        self._dispatcher = dispatcher if dispatcher is not None \
            else _dispatcher
        self.handler = handler
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1000.0
        self.max_pending = max_pending
        self.name = name
        self.default_deadline_s = default_deadline_s
        self.hang_timeout_s = hang_timeout_s
        self.supervisor = supervisor
        self.degraded_max_pending = (
            degraded_max_pending if degraded_max_pending is not None
            else max(1, max_pending // 8)
        )
        # adaptive admission (serving/overload.py AdaptiveLimiter):
        # None keeps the legacy static max_pending bound exactly
        self.admission = admission
        # called with the exception when a dispatched batch fails —
        # the device-loss classification seam (device_recovery.py)
        self.on_dispatch_error = on_dispatch_error
        # starvation bound: after this many consecutive batches
        # dispatched while background work sat pending, the oldest
        # background item heads the next batch
        self.background_every = max(1, int(background_every))
        self._batches_since_bg = 0
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_pending)
        # background tier rides its own queue so dispatch order can
        # prefer interactive without scanning
        self._bg_queue: asyncio.Queue = asyncio.Queue(maxsize=max_pending)
        # items a racing get() returned after its cancellation was
        # requested (priority-pop bookkeeping); consulted first by the
        # collector and drained by stop()
        self._spill: List = []
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # fail anything still queued: a pending future left to dangle
        # hangs its awaiting caller forever (ISSUE 2 satellite)
        stopped = 0
        pending = list(self._spill)
        self._spill.clear()
        for q in (self._queue, self._bg_queue):
            while True:
                try:
                    pending.append(q.get_nowait())
                except asyncio.QueueEmpty:
                    break
        for _, fut in pending:
            if not fut.done():
                fut.set_exception(QueueStopped(self.name))
            stopped += 1
        if stopped:
            metrics.inc(f"{self.name}.stopped_pending", stopped)
        # a DEDICATED dispatch worker dies with its queue (bounded
        # join; see _DispatchWorker.stop) — before this, every staged
        # server start/stop cycle abandoned a live stage.*_dispatch
        # thread. The shared process-global worker outlives any one
        # queue on purpose and is never stopped here.
        if self._dispatcher is not _dispatcher:
            self._dispatcher.stop()

    def _expire(self, fut: asyncio.Future) -> None:
        if not fut.done():
            metrics.inc(f"{self.name}.deadline_expired")
            flight_recorder.record("queue.deadline_expired",
                                   queue=self.name)
            # the wait histogram must include the waits that EXPIRED —
            # they are the tail that matters during degradation; only
            # counting survivors would report healthy p99s while users
            # time out
            t_submit = getattr(fut, "_obs_t", None)
            if t_submit is not None:
                metrics.observe(f"{self.name}.queue_wait_s",
                                time.perf_counter() - t_submit)
                # consumed: if the batch was already in flight when the
                # deadline hit, _record_batch_obs must not observe this
                # future a second time
                fut._obs_t = None          # type: ignore[attr-defined]
            fut.set_exception(DeadlineExceeded(self.name))

    def depth(self) -> int:
        """Pending submissions across both priority tiers."""
        return (self._queue.qsize() + self._bg_queue.qsize()
                + len(self._spill))

    async def submit(self, item: T, *,
                     deadline_s: Optional[float] = None,
                     priority: str = PRIORITY_INTERACTIVE) -> R:
        self.start()
        loop = asyncio.get_running_loop()
        depth = self.depth()
        deadline_s = (deadline_s if deadline_s is not None
                      else self.default_deadline_s)
        if self.supervisor is not None:
            lost = getattr(self.supervisor, "device_lost", None)
            if lost is not None:
                # the accelerator runtime is GONE: queuing work behind
                # it only manufactures deadline misses — fail fast with
                # a retriable error while the rebuild runs
                metrics.inc(f"{self.name}.rejected_device_lost")
                raise QueueFull(f"{self.name} (device_lost: {lost})")
        if self.supervisor is not None and self.supervisor.degraded and \
                depth >= self.degraded_max_pending:
            # degraded: admit only a short queue — deep backlogs behind a
            # sick device are all going to miss their deadlines anyway
            metrics.inc(f"{self.name}.rejected_degraded")
            raise QueueFull(f"{self.name} (degraded)")
        try:
            # drill lever (docs/CHAOS.md): a fired ``server.admit``
            # rule forces a mis-admission — the request is shed as if
            # the limiter had rejected it
            fault_point("server.admit", peer=self.name)
        except ChaosInjected:
            metrics.inc(f"{self.name}.rejected_overload")
            note_shed()
            raise OverloadShed(
                self.name, reason="chaos",
                retry_after_s=(self.admission.retry_after_s(depth)
                               if self.admission is not None else 1.0))
        # canary-probe exemption (ISSUE 18): a probe-marked request
        # (server/app.py _resolve_probe_game stamps the trace context)
        # bypasses adaptive admission and never feeds the limiter's
        # latency/capacity estimator — the probe measures the system,
        # it must not steer it. The static max_pending wall and the
        # degraded/device-lost fail-fasts still apply: a probe that
        # can't be served should FAIL (that is its job), not queue-jump
        # a dead device.
        ctx = current_ctx()
        probe = bool(ctx is not None and ctx.marks.get("probe"))
        if self.admission is not None and not probe:
            verdict = self.admission.admit(depth, priority, deadline_s)
            if verdict is not None:
                if verdict.reason == "predicted_late":
                    # doomed work rejected at submit, not at deadline
                    metrics.inc(f"{self.name}.rejected_predicted_late")
                elif verdict.reason == "background":
                    metrics.inc(f"{self.name}.rejected_background")
                else:
                    metrics.inc(f"{self.name}.rejected_overload")
                metrics.gauge(f"{self.name}.predicted_wait_s",
                              self.admission.predicted_wait_s(depth))
                note_shed()
                raise OverloadShed(self.name, reason=verdict.reason,
                                   retry_after_s=verdict.retry_after_s)
        if depth >= self.max_pending:
            # the static wall applies to the COMBINED depth: two
            # priority tiers must not quietly double the legacy
            # max_pending bound (each tier queue's own maxsize still
            # backstops the single-tier case identically)
            metrics.inc(f"{self.name}.rejected")
            raise QueueFull(self.name)
        fut: asyncio.Future = loop.create_future()
        # trace propagation rides the future, not the queue tuple: the
        # (item, fut) shape is a stable seam (tests poke it directly),
        # and a future without these attributes simply goes untraced
        fut._obs_ctx = ctx                  # type: ignore[attr-defined]
        fut._obs_t = time.perf_counter()    # type: ignore[attr-defined]
        fut._obs_priority = priority        # type: ignore[attr-defined]
        fut._obs_probe = probe              # type: ignore[attr-defined]
        q = (self._bg_queue if priority == PRIORITY_BACKGROUND
             else self._queue)
        try:
            q.put_nowait((item, fut))
        except asyncio.QueueFull:
            metrics.inc(f"{self.name}.rejected")
            raise QueueFull(self.name)
        metrics.gauge(f"{self.name}.depth", self.depth())
        if deadline_s is not None:
            handle = loop.call_later(deadline_s, self._expire, fut)
            fut.add_done_callback(lambda _f: handle.cancel())
        return await fut

    async def _pop_one(self, timeout: Optional[float]):
        """One pending item honoring priority: spilled items first,
        then interactive ahead of background — UNLESS background has
        sat out ``background_every`` consecutive batches (the
        starvation bound: its oldest item heads this batch). Both
        empty: await whichever tier produces first. Returns None on
        timeout. An item a racing get() returns after losing the
        FIRST_COMPLETED race (or after cancellation was requested)
        lands in ``self._spill`` — never lost, consumed next pop."""
        if self._spill:
            return self._spill.pop(0)
        starving = (self._bg_queue.qsize() > 0
                    and self._batches_since_bg >= self.background_every)
        order = ((self._bg_queue, self._queue) if starving
                 else (self._queue, self._bg_queue))
        for q in order:
            try:
                return q.get_nowait()
            except asyncio.QueueEmpty:
                pass
        getters = (
            # asyncio.Queue.get() is a COROUTINE here, not the blocking
            # queue.Queue.get — it runs as a task and is awaited below
            # lint: ignore[async-blocking-call] — asyncio.Queue.get coroutine under ensure_future
            asyncio.ensure_future(self._queue.get()),
            # lint: ignore[async-blocking-call] — asyncio.Queue.get coroutine under ensure_future
            asyncio.ensure_future(self._bg_queue.get()),
        )
        try:
            done, pending = await asyncio.wait(
                set(getters), timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
        except asyncio.CancelledError:
            for t in getters:
                t.cancel()
            for t in getters:
                try:
                    self._spill.append(await t)
                except asyncio.CancelledError:
                    pass
            raise
        for t in pending:
            t.cancel()

            def _salvage(task) -> None:
                # the cancel can lose the race with an arriving item:
                # keep it for the next pop instead of dropping it
                if not task.cancelled() and task.exception() is None:
                    self._spill.append(task.result())

            t.add_done_callback(_salvage)
        # lint: ignore[async-blocking-call] — every t here is in done; result() returns immediately
        items = [t.result() for t in getters
                 if t in done and not t.cancelled()
                 and t.exception() is None]
        if not items:
            return None
        self._spill.extend(items[1:])   # both tiers produced at once
        return items[0]

    async def _collect(self) -> List:
        """One entry (blocking) + everything arriving within the window.
        Cancellation-safe: items already popped off the queue when the
        collector is cancelled (queue stopping mid-window) have their
        futures failed here — stop()'s drain can no longer see them."""
        batch: List = []
        try:
            first = await self._pop_one(None)
            if first is not None:
                batch.append(first)
            loop = asyncio.get_running_loop()
            opened = loop.time()
            deadline = opened + self.max_delay_s
            while len(batch) < self.max_batch:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                nxt = await self._pop_one(timeout)
                if nxt is None:
                    break
                batch.append(nxt)
            # how long the window actually held the first item before
            # dispatch: ~0 under load (bucket fills instantly), ~the
            # full max_delay under trickle traffic — the knob's cost
            metrics.gauge(f"{self.name}.coalesce_wait_s",
                          loop.time() - opened)
        except asyncio.CancelledError:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(QueueStopped(self.name))
            raise
        return batch

    async def _run(self) -> None:
        while True:
            batch = await self._collect()
            # deadline-expired entries are already failed; don't spend a
            # device dispatch on items nobody is waiting for
            batch = [(item, fut) for item, fut in batch if not fut.done()]
            if not batch:
                continue
            items = [item for item, _ in batch]
            futures = [fut for _, fut in batch]
            # starvation-bound bookkeeping: a batch that carried any
            # background member resets the counter; one dispatched while
            # background sat pending ages it toward background_every
            if any(getattr(f, "_obs_priority", None) == PRIORITY_BACKGROUND
                   for f in futures):
                self._batches_since_bg = 0
            elif self._bg_queue.qsize() > 0:
                self._batches_since_bg += 1
            metrics.inc(f"{self.name}.batches")
            metrics.inc(f"{self.name}.items", len(items))
            metrics.observe(f"{self.name}.batch_size", len(items),
                            buckets=BATCH_SIZE_BUCKETS)
            # the batch span JOINS the first traced member's trace (a
            # single-request batch — the interactive case — reads as one
            # contiguous trace); every traced member additionally gets
            # queue_wait/batch_service spans in its OWN trace, linked to
            # the batch by id (_record_batch_obs)
            ctxs = [c for c in (getattr(f, "_obs_ctx", None)
                                for f in futures) if c is not None]
            # prefer a SAMPLED member as the batch span's parent: joining
            # an unsampled member's trace would silently drop the batch
            # and device-stage spans for every sampled member behind it.
            # No traced member at all -> a DETACHED (unsampled) ctx, so
            # the batch records nothing rather than minting an orphan
            # root trace per batch that would flush the ring
            parent = next((c for c in ctxs if c.sampled),
                          ctxs[0] if ctxs else None)
            batch_ctx = (tracer.child_ctx(parent) if parent is not None
                         else tracer.detached_ctx())
            start_wall = time.time()
            t_dispatch = time.perf_counter()
            status = "ok"
            # the handler runs on the dispatch thread under the batch
            # span's context, so its block_timer stage spans land in the
            # batch's trace (contextvars don't cross threads on their own)
            dispatch, started = self._dispatcher.submit(
                run_with_ctx, batch_ctx, self._handle_batch, items)
            wrapped = asyncio.wrap_future(dispatch)
            try:
                with metrics.timer(f"{self.name}.batch_s"):
                    results = await self._await_dispatch(wrapped, started)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"handler returned {len(results)} results for "
                        f"{len(items)} items"
                    )
                for fut, res in zip(futures, results):
                    if not fut.done():
                        if isinstance(res, Exception):
                            # per-member failure (integrity sentinels:
                            # one poisoned batch row fails one request,
                            # not the batch)
                            fut.set_exception(res)
                        else:
                            fut.set_result(res)
            except asyncio.CancelledError:
                # queue stopping mid-batch: the in-flight futures must
                # fail, not dangle (their handler result is dropped)
                status = "error"
                self._disown(wrapped)
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(QueueStopped(self.name))
                raise
            except _HandlerWedged:
                # OUR handler is running and wedged (hung XLA call): fail
                # the batch, flip the supervisor degraded, and hand
                # future batches a fresh dispatch thread
                status = "error"
                log.error(
                    "%s handler exceeded %.1fs hang deadline; replacing "
                    "dispatch thread", self.name, self.hang_timeout_s)
                metrics.inc(f"{self.name}.dispatch_hangs")
                flight_recorder.record(
                    "queue.dispatch_hang", queue=self.name,
                    hang_timeout_s=self.hang_timeout_s,
                    batch_size=len(items))
                if self.supervisor is not None:
                    self.supervisor.note_dispatch_overrun(self.name)
                self._dispatcher.replace()
                self._disown(wrapped)
                exc = DispatchTimeout(
                    f"{self.name} dispatch exceeded {self.hang_timeout_s}s")
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
            except Exception as exc:  # noqa: BLE001 — propagate per-item
                status = "error"
                log.exception("%s batch failed", self.name)
                metrics.inc(f"{self.name}.failures")
                if self.on_dispatch_error is not None:
                    # device-loss classification seam (serving/
                    # device_recovery.py); advisory — a hook failure
                    # must not change the per-item failure contract
                    try:
                        self.on_dispatch_error(exc)
                    # lint: ignore[swallowed-error] — advisory classification hook: the batch failure itself is counted and carried to every waiter below
                    except Exception:
                        log.exception("%s on_dispatch_error hook "
                                      "failed", self.name)
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
            finally:
                self._record_batch_obs(
                    batch_ctx, parent, futures, start_wall, t_dispatch,
                    status)

    def _handle_batch(self, items: List[T]):
        """The dispatched body: the ``queue.dispatch`` fault point runs
        ON the dispatch thread, peer-scoped by queue name — a ``wedge``
        rule wedges the real thread and exercises the real watchdog
        (deadline expiry, thread disown + replace), not a mock of it."""
        fault_point("queue.dispatch", peer=self.name)
        return self.handler(items)

    def _record_batch_obs(self, batch_ctx, parent, futures,
                          start_wall: float, t_dispatch: float,
                          status: str) -> None:
        """Sink the batch span plus, per traced member, the queue-wait /
        batch-service split: wait is submit -> dispatch handoff, service
        is handoff -> batch completion (shared by all members — the
        device ran them as one computation). Also fills the request's
        marks blackboard so the HTTP layer can answer with
        ``X-Queue-Wait`` / ``X-Service-Time`` headers."""
        service_s = time.perf_counter() - t_dispatch
        tracer.record_span(
            f"{self.name}.batch", batch_ctx,
            parent_id=parent.span_id if parent is not None else None,
            start_wall=start_wall, duration_s=service_s, status=status,
            attrs={"queue": self.name, "batch_size": len(futures)})
        # probe members are invisible to the limiter's estimator AND
        # the queue-wait histogram (ISSUE 18): the canary's timings
        # belong to probe.e2e_s, never to the series that size
        # admission or alarm players' latency
        player = [f for f in futures
                  if not getattr(f, "_obs_probe", False)]
        if self.admission is not None and status == "ok" and player:
            # the AIMD signal: the batch's end-to-end latency is its
            # service time plus its slowest member's queue wait (error
            # batches excluded — a handler bug is not a latency signal)
            waits = [t_dispatch - t
                     for t in (getattr(f, "_obs_t", None)
                               for f in player) if t is not None]
            self.admission.observe_batch(
                max(waits) if waits else 0.0, service_s, len(player))
        for fut in futures:
            t_submit = getattr(fut, "_obs_t", None)
            if t_submit is None:
                continue
            wait_s = t_dispatch - t_submit
            if not getattr(fut, "_obs_probe", False):
                metrics.observe(f"{self.name}.queue_wait_s", wait_s)
            ctx = getattr(fut, "_obs_ctx", None)
            if ctx is None:
                continue
            # a request that rode several batches (gathered submits)
            # reports its slowest leg — the one that bounded its latency
            ctx.marks["queue_wait_s"] = max(
                wait_s, ctx.marks.get("queue_wait_s", 0.0))
            ctx.marks["service_s"] = max(
                service_s, ctx.marks.get("service_s", 0.0))
            if not ctx.sampled:
                continue
            link = {"queue": self.name,
                    "batch_trace": batch_ctx.trace_id,
                    "batch_span": batch_ctx.span_id}
            tracer.record_span(
                f"{self.name}.queue_wait", tracer.child_ctx(ctx),
                parent_id=ctx.span_id, start_wall=start_wall - wait_s,
                duration_s=wait_s, attrs=link)
            tracer.record_span(
                f"{self.name}.batch_service", tracer.child_ctx(ctx),
                parent_id=ctx.span_id, start_wall=start_wall,
                duration_s=service_s, status=status, attrs=link)

    async def _await_dispatch(self, wrapped: asyncio.Future,
                              started: "threading.Event"):
        """Await the dispatched batch, raising _HandlerWedged only when
        THIS handler has been RUNNING past the hang deadline. Time spent
        merely queued behind another queue's dispatch on the shared
        thread never counts: the hang clock arms only once ``started``
        is observed set, so a handler that began late (behind a slow but
        healthy neighbor) gets its full budget — declaring it wedged at
        the first window expiry would fail the batch, flip the
        supervisor degraded, and disown a healthy in-flight device call.
        (A genuinely queued-forever job is bounded elsewhere: the
        neighbor's own watchdog replaces the wedged thread and
        replace() moves unstarted jobs onto the fresh one, and every
        submission carries its per-request deadline.)"""
        if self.hang_timeout_s is None:
            return await wrapped
        loop = asyncio.get_running_loop()
        hang_deadline = None   # armed when the handler is seen running
        while True:
            if hang_deadline is None and started.is_set():
                hang_deadline = loop.time() + self.hang_timeout_s
            if hang_deadline is not None and \
                    loop.time() >= hang_deadline:
                raise _HandlerWedged()
            timeout = (self.hang_timeout_s if hang_deadline is None
                       else hang_deadline - loop.time())
            done, _ = await asyncio.wait({wrapped}, timeout=timeout)
            if done:
                # asyncio.wait just completed this future, so .result()
                # returns immediately (re-raising handler exceptions)
                # lint: ignore[async-blocking-call] — future already done
                return wrapped.result()

    @staticmethod
    def _disown(wrapped: asyncio.Future) -> None:
        """Abandon a dispatch future we will never await again; mark its
        eventual exception retrieved so asyncio doesn't log it."""
        if not wrapped.done():
            wrapped.add_done_callback(
                lambda f: None if f.cancelled() else f.exception())
