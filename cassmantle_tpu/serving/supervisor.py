"""Serving supervisor: one readiness signal for the whole device path.

Fuses the three independent degradation detectors into the state the
operator (and the load balancer) actually needs:

- the **content breaker** around ``ContentBackend.generate`` (a dark TPU
  stops costing retry backoff and flips the engine onto the round
  reserve, engine/rounds.py);
- the **score breaker** around the guess-scorer dispatch
  (serving/service.py degrades to floor scores, the API sheds with 503);
- the **dispatch watchdog** in serving/queue.py (a hung handler — a
  wedged XLA call that blocks the dispatch thread — trips
  ``note_dispatch_overrun`` when a batch overruns its hang deadline);
- optionally ``utils.health.DeviceHealth`` (the jitted liveness probe).

``/readyz`` (server/app.py) serves ``status()`` with a 503 + Retry-After
while degraded: readiness is "can this worker produce fresh content and
real scores right now", distinct from `/healthz` liveness ("is the
process/store/device up at all") — a degraded worker still serves the
game from the store and must NOT be killed by a liveness probe.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, Optional

from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.circuit import OPEN, CircuitBreaker
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("supervisor")


class ServingSupervisor:
    def __init__(
        self,
        *,
        content_breaker: Optional[CircuitBreaker] = None,
        score_breaker: Optional[CircuitBreaker] = None,
        device_health=None,
        degraded_cooldown_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.clock = clock
        self.content_breaker = content_breaker or CircuitBreaker(
            "content", clock=clock)
        self.score_breaker = score_breaker or CircuitBreaker(
            "score", clock=clock)
        # set by server/app.py when real-device serving wires DeviceHealth
        self.device_health = device_health
        # set by server/app.py when a room fabric is serving: a sync
        # callable returning the cluster block `/readyz` embeds — worker
        # identity, room placement, live membership, replication
        # leader + lag (fabric/rooms.py RoomFabric.status)
        self.fabric_status: Optional[Callable[[], Dict[str, object]]] = None
        self.degraded_cooldown_s = degraded_cooldown_s
        # rank per the docs/STATIC_ANALYSIS.md lock hierarchy: supervisor
        # state is leaf-ward of the dispatch locks, outward of breakers
        self._lock = OrderedLock("supervisor", rank=30)
        self._degraded_until = 0.0
        self._overruns = 0
        # the DeviceRecoveryManager when an InferenceService owns this
        # supervisor (serving/service.py publishes it): the server
        # layer wires DeviceHealth probe raises into its classifier
        self.recovery = None
        # device-loss state (serving/device_recovery.py): reason string
        # while the accelerator runtime is gone and the recovery manager
        # is rebuilding serving state; None when healthy
        self._device_lost: Optional[str] = None
        # per-stage dispatch health (stage-disaggregated serving,
        # serving/stages.py): last time each stage made observable
        # progress (a batch completed / a slot retired). status()
        # surfaces seconds-since-progress so a /readyz reader sees
        # WHICH stage went dark; a wedged stage still flips degraded
        # through note_dispatch_overrun like any other dispatch path.
        self._stage_progress: Dict[str, float] = {}

    # -- watchdog ---------------------------------------------------------
    def note_dispatch_overrun(self, queue_name: str) -> None:
        """A batch handler blew through its hang deadline: the dispatch
        thread was wedged (and has been replaced). Hold the worker in
        degraded state for a cooldown — one overrun means in-flight
        device work is unreliable right now, not just that one batch."""
        with self._lock:
            self._overruns += 1
            self._degraded_until = max(
                self._degraded_until,
                self.clock() + self.degraded_cooldown_s,
            )
        metrics.inc("supervisor.dispatch_overruns")
        flight_recorder.record("supervisor.overrun", queue=queue_name,
                               cooldown_s=self.degraded_cooldown_s)
        log.error("dispatch overrun on %r: degraded for %.0fs",
                  queue_name, self.degraded_cooldown_s)

    @property
    def watchdog_degraded(self) -> bool:
        with self._lock:
            return self.clock() < self._degraded_until

    # -- per-stage health (serving/stages.py) ------------------------------
    def note_stage_progress(self, stage: str) -> None:
        """A serving stage (encode / denoise / decode) made observable
        progress: a batch completed or a slot retired. Cheap enough for
        every completion; feeds the ``stages`` block of status()."""
        with self._lock:
            self._stage_progress[stage] = self.clock()

    def stage_health(self) -> Dict[str, float]:
        """Seconds since each registered stage last made progress
        (empty until staged serving has run)."""
        with self._lock:
            now = self.clock()
            return {s: round(now - t, 3)
                    for s, t in self._stage_progress.items()}

    # -- device loss (serving/device_recovery.py) --------------------------
    def note_device_lost(self, reason: str) -> None:
        """The recovery manager classified a dispatch failure / probe
        pattern as accelerator-runtime loss: hold `/readyz` 503 (state
        ``device_lost``) until :meth:`note_device_recovered`."""
        with self._lock:
            self._device_lost = reason or "device lost"
        metrics.gauge("supervisor.device_lost", 1.0)
        flight_recorder.record("device.lost", reason=reason)
        log.error("device lost (%s): serving degraded until the "
                  "recovery manager rebuilds device state", reason)

    def note_device_recovered(self) -> None:
        with self._lock:
            self._device_lost = None
        metrics.gauge("supervisor.device_lost", 0.0)
        flight_recorder.record("device.recovered")
        log.warning("device recovered: serving state rebuilt")

    @property
    def device_lost(self) -> Optional[str]:
        """The loss reason while in the ``device_lost`` state, else
        None. Read by `/readyz` (names the state) and the queues (fail
        fast instead of batching work for a dead device)."""
        with self._lock:
            return self._device_lost

    def device_unhealthy(self) -> bool:
        """True only when the cached device verdict is a hard False —
        a sync read with NO probe dial, cheap enough for the request
        path (the scorer-hedge decision, server/app.py). None/unknown
        reads healthy: hedging is for provably dark devices."""
        dh = self.device_health
        return dh is not None and dh.last_verdict() is False

    # -- device -----------------------------------------------------------
    async def probe_device(self) -> Optional[bool]:
        """DeviceHealth verdict for status(); None = nothing to probe
        (fake backend). Runs off the event loop — the probe blocks up to
        its timeout when the device is wedged."""
        if self.device_health is None:
            return None
        loop = asyncio.get_running_loop()
        ok, _ = await loop.run_in_executor(None, self.device_health.check)
        return ok

    # -- fused signal -----------------------------------------------------
    @property
    def degraded(self) -> bool:
        """True while ANY detector is unhappy: open/half-open breaker or
        a recent dispatch overrun. Queues tighten rejection thresholds on
        this; `/readyz` flips 503."""
        return (
            self.watchdog_degraded
            or self.device_lost is not None
            or self.content_breaker.state != "closed"
            or self.score_breaker.state != "closed"
        )

    def shed_scores(self) -> bool:
        """Should the API refuse scoring work outright (503) instead of
        returning floor scores? Only when the breaker KNOWS the scorer is
        dark — half-open still lets the probe traffic through."""
        return self.score_breaker.state == OPEN

    def retry_after_s(self) -> float:
        """Seconds a shed client should wait: the longest of the open
        breakers' cooldown remainders and the watchdog window (floor 1)."""
        with self._lock:
            watchdog = max(0.0, self._degraded_until - self.clock())
        return max(
            1.0,
            watchdog,
            # a rebuild (re-upload + re-warm) takes seconds at best:
            # don't invite shed clients back mid-recovery
            5.0 if self.device_lost is not None else 0.0,
            self.content_breaker.seconds_until_half_open(),
            self.score_breaker.seconds_until_half_open(),
        )

    def status(self, device_ok: Optional[bool] = None,
               include_events: bool = False) -> Dict[str, object]:
        """The `/readyz` body. ``device_ok`` is the (executor-run)
        DeviceHealth verdict when the caller has one; None = no device to
        probe (fake backend). ``include_events`` embeds the flight-
        recorder tail in a degraded verdict — the HTTP layer sets it
        only for loopback callers (the same internal-state boundary
        `/debugz` enforces; remote probes get the verdict, not the
        event history)."""
        degraded = self.degraded
        lost = self.device_lost
        ready = not degraded and device_ok is not False
        with self._lock:
            watchdog = {
                "degraded": self.clock() < self._degraded_until,
                "overruns": self._overruns,
                "degraded_for_s": max(
                    0.0, self._degraded_until - self.clock()),
            }
        deaths = metrics.counter_total("server.worker_deaths")
        if deaths:
            # dead sibling workers (the parent's watcher counts them):
            # capacity this /readyz verdict silently lost (ISSUE 12)
            watchdog["worker_deaths"] = int(deaths)
        metrics.gauge("supervisor.degraded", 0.0 if ready else 1.0)
        status: Dict[str, object] = {
            "ready": ready,
            # device_lost is its own named state: the operator runbook
            # (docs/DEPLOY.md §7b) keys off it
            "state": ("device_lost" if lost is not None
                      else "ok" if ready else "degraded"),
            "breakers": {
                b.name: b.snapshot()
                for b in (self.content_breaker, self.score_breaker)
            },
            "watchdog": watchdog,
            "device": device_ok,
        }
        if lost is not None:
            status["device_lost"] = {"reason": lost}
        stages = self.stage_health()
        if stages:
            status["stages"] = stages
        from cassmantle_tpu import chaos

        if chaos.armed():
            # a drill must never read as an incident: whenever a fault
            # plan is armed, BOTH probe surfaces say so (healthz embeds
            # this same status block)
            status["chaos"] = chaos.status()
        if self.fabric_status is not None:
            try:
                status["fabric"] = self.fabric_status()
            # lint: ignore[swallowed-error] — the failure is carried into the status payload itself ({"error": "unavailable"}), which every probe consumer sees
            except Exception:
                # the cluster block is advisory: a torn membership
                # snapshot must never break the readiness verdict
                log.exception("fabric status failed")
                status["fabric"] = {"error": "unavailable"}
        if not ready and include_events:
            # a degraded verdict carries the recent event history that
            # explains it — the flight-recorder tail (trip order,
            # watchdog fires, reserve rotations), not just end states
            status["events"] = flight_recorder.tail(25)
        return status
