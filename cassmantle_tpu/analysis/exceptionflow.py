"""Exception-flow pass: swallowed errors and overbroad catches.

The failure-containment plane (breakers, integrity verdicts, recovery
manager) only works when failures actually REACH it — and the repo's
worst silent bugs were all exception-flow bugs: the PR 8 replication
pump whose swallowed cancellation left ``close()`` awaiting a loop that
would never exit (gh-86296), and log-only broad catches that turned
dispatch failures into invisible log lines no alert ever read. Two
rules over the serving/engine/fabric/server/native layers:

``swallowed-error`` — an ``except`` handler that catches broadly
(``Exception``, ``BaseException``, bare) and then neither

- re-raises,
- counts a metric (``metrics.inc/observe/gauge/timer``),
- flight-records / traces (``*.record``, ``*.record_span``,
  ``*.mark_retain``),
- classifies through the recovery plane (``note_*``, ``*classify*``,
  ``on_dispatch_error``), nor
- carries the error to a waiter (``*.set_exception``, ``*fail*``)

is a black hole: the failure happened, nothing counted it, no
dashboard or drill can see it. Log-only handlers count as swallowed on
purpose — a log line is not a signal the SLO engine or an alert reads.
The same rule flags the PR 8 cancel-swallow shape directly: a handler
catching ``asyncio.CancelledError`` inside a loop of an ``async def``
that neither re-raises nor breaks/returns makes the task UNCANCELLABLE
— ``stop()``/``close()`` then awaits it forever.

``overbroad-except`` — ``except BaseException`` or a bare ``except:``
outside documented shutdown paths (``stop``/``close``/``shutdown``/
``__exit__``-shaped functions) and not re-raising or carrying the
exception to a future: these catch ``KeyboardInterrupt``/``SystemExit``
and cancellation, hiding even the intent to die.

Exempt by construction: the cancelled-task reap idiom (``t.cancel()``
then ``try: await t except ...: pass`` — the error already reached its
owner when the task was cancelled), and narrow typed catches
(``except KeyError`` is control flow, not swallowing).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    dotted_name,
)

RULE_SWALLOW = "swallowed-error"
RULE_OVERBROAD = "overbroad-except"

#: the async handler/engine/fabric layers whose exceptions must reach
#: the containment plane (ops/models raise to their callers normally)
REPO_DIRS = ("cassmantle_tpu/serving/", "cassmantle_tpu/engine/",
             "cassmantle_tpu/fabric/", "cassmantle_tpu/server/",
             "cassmantle_tpu/native/")

_BROAD = {"Exception", "BaseException"}
_METRIC_METHODS = {"inc", "observe", "gauge", "timer"}
_RECORD_METHODS = {"record", "record_span", "mark_retain"}
#: functions whose job is teardown: a broadest-possible catch there is
#: the documented shutdown-path exemption for overbroad-except
_SHUTDOWN_PREFIXES = ("stop", "close", "shutdown", "drain", "retire",
                      "terminate", "aclose")
_SHUTDOWN_NAMES = {"__exit__", "__aexit__", "__del__", "join"}


def _handler_names(handler: ast.ExceptHandler) -> Set[str]:
    """Dotted names of the caught types; empty set = bare ``except:``."""
    t = handler.type
    if t is None:
        return set()
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = set()
    for e in elts:
        name = dotted_name(e)
        if name is not None:
            names.add(name)
    return names


def _is_shutdown_path(func_name: Optional[str]) -> bool:
    if func_name is None:
        return False
    bare = func_name.lstrip("_")
    return func_name in _SHUTDOWN_NAMES or \
        bare.startswith(_SHUTDOWN_PREFIXES)


def _walk_body(nodes: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested defs (their
    bodies execute elsewhere, under their own handlers)."""
    stack: List[ast.AST] = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _accounts_for_error(handler: ast.ExceptHandler) -> bool:
    """True if the handler body re-raises or routes the failure into
    something the containment plane can see."""
    for node in _walk_body(handler.body):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None:
            continue
        segments = name.split(".")
        last = segments[-1]
        if last in _METRIC_METHODS and "metrics" in segments:
            return True
        if last in _RECORD_METHODS:
            return True
        if last == "set_exception" or "fail" in last:
            return True
        if last.startswith("note_") or "classify" in last or \
                last == "on_dispatch_error":
            return True
    return False


def _terminates(handler: ast.ExceptHandler) -> bool:
    """Raise/Return/Break anywhere in the handler body: the loop (and
    so the task) actually ends on this path."""
    return any(isinstance(n, (ast.Raise, ast.Return, ast.Break))
               for n in _walk_body(handler.body))


def _cancelled_receivers(fn: ast.AST) -> Set[str]:
    """Dotted receivers of every ``X.cancel()`` call in the function."""
    receivers: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "cancel":
            recv = dotted_name(node.func.value)
            if recv is not None:
                receivers.add(recv)
    return receivers


def _is_reap_idiom(try_node: ast.Try, cancelled: Set[str]) -> bool:
    """``try: await X`` (alone) where the function cancels ``X``
    somewhere: awaiting a task you just cancelled raises its
    CancelledError at you — suppressing THAT is teardown, not
    swallowing (the owner initiated the death it is now observing)."""
    if len(try_node.body) != 1:
        return False
    for node in ast.walk(try_node.body[0]):
        if not isinstance(node, ast.Await):
            continue
        awaited = node.value
        if isinstance(awaited, ast.Call):  # await wait_for(X, ...)
            if not awaited.args:
                continue
            awaited = awaited.args[0]
        recv = dotted_name(awaited)
        if recv is not None and recv in cancelled:
            return True
    return False


class ExceptionFlowPass(LintPass):
    name = "exceptionflow"
    description = ("broad except bodies that swallow errors invisibly; "
                   "BaseException/bare catches outside shutdown paths")

    def __init__(self, dirs: Optional[Sequence[str]] = None) -> None:
        # None = lint every module handed in (fixtures); the repo run
        # scopes to the layers whose failures feed the containment plane
        self.dirs = tuple(dirs) if dirs else None

    @classmethod
    def for_repo(cls) -> "ExceptionFlowPass":
        return cls(dirs=REPO_DIRS)

    def run(self, module: Module) -> Iterator[Finding]:
        if self.dirs and not any(module.rel.startswith(d)
                                 for d in self.dirs):
            return
        yield from self._scan(module.tree.body, module,
                              func=None, is_async=False, in_loop=False,
                              cancelled=set())

    def _scan(self, nodes, module: Module, *, func: Optional[str],
              is_async: bool, in_loop: bool,
              cancelled: Set[str]) -> Iterator[Finding]:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(
                    node.body, module, func=node.name,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                    in_loop=False, cancelled=_cancelled_receivers(node))
                continue
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._scan(
                    node.body + node.orelse, module, func=func,
                    is_async=is_async, in_loop=True, cancelled=cancelled)
                continue
            if isinstance(node, ast.Try):
                yield from self._check_try(node, module, func=func,
                                           is_async=is_async,
                                           in_loop=in_loop,
                                           cancelled=cancelled)
                yield from self._scan(
                    node.body + node.orelse + node.finalbody, module,
                    func=func, is_async=is_async, in_loop=in_loop,
                    cancelled=cancelled)
                for handler in node.handlers:
                    yield from self._scan(handler.body, module, func=func,
                                          is_async=is_async,
                                          in_loop=in_loop,
                                          cancelled=cancelled)
                continue
            if isinstance(node, ast.ClassDef):
                yield from self._scan(node.body, module, func=func,
                                      is_async=is_async, in_loop=in_loop,
                                      cancelled=cancelled)
                continue
            # other compound statements (With, If, ...): recurse into
            # their statement bodies via child iteration
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    # handled via the generic field walk below
                    pass
            yield from self._scan(
                [c for c in ast.iter_child_nodes(node)
                 if isinstance(c, ast.stmt)],
                module, func=func, is_async=is_async, in_loop=in_loop,
                cancelled=cancelled)

    def _check_try(self, node: ast.Try, module: Module, *,
                   func: Optional[str], is_async: bool, in_loop: bool,
                   cancelled: Set[str]) -> Iterator[Finding]:
        reap = _is_reap_idiom(node, cancelled)
        for handler in node.handlers:
            names = _handler_names(handler)
            bare = handler.type is None
            end = handler.body[0].lineno if handler.body else None
            # -- overbroad-except -----------------------------------------
            if (bare or "BaseException" in names) and not reap and \
                    not _is_shutdown_path(func) and \
                    not self._carries(handler):
                what = "bare except:" if bare else "except BaseException"
                yield Finding(
                    RULE_OVERBROAD, module.rel, handler.lineno,
                    f"{what} outside a shutdown path catches "
                    f"KeyboardInterrupt/SystemExit and cancellation "
                    f"without re-raising — catch Exception, or re-raise "
                    f"after cleanup", end)
                continue  # the stronger claim; don't double-report
            # -- cancel-swallow (the PR 8 close-hang shape) ---------------
            catches_cancel = bare or \
                any(n.rsplit(".", 1)[-1] == "CancelledError"
                    for n in names) or "BaseException" in names
            if catches_cancel and is_async and in_loop and not reap and \
                    not _terminates(handler):
                yield Finding(
                    RULE_SWALLOW, module.rel, handler.lineno,
                    f"cancellation swallowed in a loop of async "
                    f"{func or '<module>'!r}: the task becomes "
                    f"uncancellable and stop()/close() awaits it "
                    f"forever (the gh-86296 pump shape) — re-raise "
                    f"CancelledError", end)
                continue
            # -- swallowed-error ------------------------------------------
            broad = bare or bool(names & _BROAD)
            if broad and not reap and not _accounts_for_error(handler):
                yield Finding(
                    RULE_SWALLOW, module.rel, handler.lineno,
                    f"broad except in {func or '<module>'!r} swallows "
                    f"the error invisibly (no re-raise, metric, "
                    f"flight-record, classification, or set_exception) "
                    f"— a failure here is unobservable; count it or "
                    f"let it propagate", end)

    @staticmethod
    def _carries(handler: ast.ExceptHandler) -> bool:
        """Re-raises or hands the exception to a waiter — the two
        legitimate broadest-catch shapes (the dispatch-thread carrier
        in serving/queue.py is the canonical one)."""
        for node in _walk_body(handler.body):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name is not None and \
                        name.rsplit(".", 1)[-1] == "set_exception":
                    return True
        return False
