"""Resource-lifecycle pass: leaked tasks, threads, and OS resources.

The static half of the leak story (the runtime half is
``utils/leak_sentinel.py``, armed per-test by conftest). Three rules:

``task-leak`` — fire-and-forget ``asyncio.create_task`` /
``ensure_future`` as a bare expression statement: nothing retains the
task, so (a) the event loop holds only a weak reference and the task
can be garbage-collected MID-FLIGHT (the documented asyncio footgun),
and (b) its exception is silently dropped at GC time. Store the task,
gather it, or attach a done-callback.

``thread-leak`` — threads whose shutdown story is missing:

- a ``self._x = Thread(...)`` started in a class that HAS a
  ``stop``/``close``/``shutdown``/``join`` method, none of which
  ever joins it — ``stop()`` returns while the thread still runs,
  the PR 2 disowned-watchdog shape and the flaky-teardown shape the
  leak sentinel catches at runtime;
- a non-daemon ``self._x`` thread in a class with NO stop-ish method
  at all — nothing can ever end it, so process exit hangs;
- an anonymous non-daemon ``Thread(...).start()`` — unjoinable by
  construction;
- a function-local non-daemon thread never joined in that function.

Anonymous DAEMON threads are exempt by design (the health prober's
device-probe and the server's worker-death watcher are deliberate
fire-and-forget daemons) — a documented blind spot the runtime
sentinel's allowlist mirrors.

``resource-leak`` — ``open()``, ``socket.socket()``,
``ThreadPoolExecutor``/``ProcessPoolExecutor``, ``subprocess.Popen``
bound to a name with neither a ``with`` block, a close-ish call on a
close path (same function for locals; any ``stop``/``close``-shaped
method for ``self._x``), nor an ownership transfer (returned or passed
onward). Each leaked fd/executor is invisible until the process hits
EMFILE under load.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    dotted_name,
)

RULE_TASK = "task-leak"
RULE_THREAD = "thread-leak"
RULE_RESOURCE = "resource-leak"

_SPAWN_METHODS = {"create_task", "ensure_future"}
_STOP_PREFIXES = ("stop", "close", "shutdown", "join", "terminate",
                  "aclose", "retire", "drain")
_STOP_DUNDERS = {"__exit__", "__aexit__", "__del__"}
#: ctor dotted-name suffixes -> what leaks
_RESOURCE_CTORS = {
    "open": "file",
    "socket.socket": "socket",
    "ThreadPoolExecutor": "executor",
    "ProcessPoolExecutor": "executor",
    "subprocess.Popen": "subprocess",
    "Popen": "subprocess",
}
_CLOSE_METHODS = {"close", "shutdown", "terminate", "kill", "wait",
                  "communicate", "release"}


def _is_stop_like(name: str) -> bool:
    return name in _STOP_DUNDERS or \
        name.lstrip("_").startswith(_STOP_PREFIXES)


def _self_aliases(fn: ast.AST) -> Dict[str, str]:
    """Local name -> ``self.attr`` for plain and tuple-unpacking
    assigns — the grab-under-lock-then-join-outside idiom
    (``t = self._thread`` / ``jobs, t = self._jobs, self._thread``)
    must count as join evidence for the aliased attribute."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                and len(tgt.elts) == len(val.elts):
            pairs = zip(tgt.elts, val.elts)
        else:
            pairs = [(tgt, val)]
        for t, v in pairs:
            src = dotted_name(v)
            if isinstance(t, ast.Name) and src and \
                    src.startswith("self."):
                aliases[t.id] = src
    return aliases


def _resource_kind(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if name is None:
        return None
    for ctor, kind in _RESOURCE_CTORS.items():
        if name == ctor or name.endswith("." + ctor):
            return kind
    return None


def _is_thread_ctor(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    name = call_name(call)
    return name is not None and (name == "Thread" or
                                 name.endswith(".Thread"))


def _daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _has_explicit_daemon(call: ast.Call) -> bool:
    return any(kw.arg == "daemon" for kw in call.keywords)


class LifecyclePass(LintPass):
    name = "lifecycle"
    description = ("fire-and-forget tasks, threads stop() never joins, "
                   "resources opened without close-on-stop")

    def __init__(self, dirs: Optional[Sequence[str]] = None) -> None:
        self.dirs = tuple(dirs) if dirs else None

    @classmethod
    def for_repo(cls) -> "LifecyclePass":
        # whole package: leaks matter everywhere, not just serving
        return cls(dirs=("cassmantle_tpu/",))

    def run(self, module: Module) -> Iterator[Finding]:
        if self.dirs and not any(module.rel.startswith(d)
                                 for d in self.dirs):
            return
        yield from self._check_task_leaks(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class_threads(node, module)
                yield from self._check_class_resources(node, module)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_local_threads(node, module)
                yield from self._check_local_resources(node, module)
        yield from self._check_anonymous_threads(module)

    # -- task-leak -----------------------------------------------------------

    def _check_task_leaks(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr) and
                    isinstance(node.value, ast.Call)):
                continue
            call = node.value
            spawn = self._spawn_call(call)
            if spawn is not None:
                yield Finding(
                    RULE_TASK, module.rel, call.lineno,
                    f"fire-and-forget {spawn}: the loop keeps only a "
                    f"weak reference, so the task can be GC'd mid-"
                    f"flight and its exception is dropped silently — "
                    f"store the task, await/gather it, or attach a "
                    f"done-callback")

    @staticmethod
    def _spawn_call(call: ast.Call) -> Optional[str]:
        """The spawn call's display name if this expression statement is
        a bare create_task/ensure_future — including the chained
        ``<spawn>(...).add_done_callback(...)`` form, which is FINE
        (the callback retains and observes the task)."""
        func = call.func
        # chained .add_done_callback on the spawn result: not a leak
        if isinstance(func, ast.Attribute) and \
                func.attr == "add_done_callback":
            return None
        name = call_name(call)
        if name is None:
            return None
        last = name.rsplit(".", 1)[-1]
        if last in _SPAWN_METHODS:
            return name
        return None

    # -- thread-leak: class-owned threads ------------------------------------

    def _check_class_threads(self, cls: ast.ClassDef,
                             module: Module) -> Iterator[Finding]:
        methods: Dict[str, ast.AST] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt
        stop_methods = {n: m for n, m in methods.items()
                        if _is_stop_like(n)}
        # self._x = Thread(...) assignments, with daemon-ness
        threads: Dict[str, Tuple[int, bool]] = {}  # attr -> (line, daemon)
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and \
                        _is_thread_ctor(node.value):
                    for tgt in node.targets:
                        attr = dotted_name(tgt)
                        if attr and attr.startswith("self."):
                            threads[attr] = (node.lineno,
                                             _daemon_true(node.value))
        if not threads:
            return
        # which of those attrs are actually .start()ed?
        started: Dict[str, int] = {}
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "start":
                    recv = dotted_name(node.func.value)
                    if recv in threads:
                        started[recv] = node.lineno
        if not started:
            return
        joined = self._joined_attrs(stop_methods, methods)
        for attr, start_line in sorted(started.items()):
            _, daemon = threads[attr]
            if attr in joined:
                continue
            if stop_methods:
                yield Finding(
                    RULE_THREAD, module.rel, start_line,
                    f"{cls.name} starts {attr} but "
                    f"{'/'.join(sorted(stop_methods))}() never joins "
                    f"it: stop returns while the thread still runs — "
                    f"join with a bounded timeout (and flight-record "
                    f"on overrun)")
            elif not daemon:
                yield Finding(
                    RULE_THREAD, module.rel, start_line,
                    f"{cls.name} starts non-daemon {attr} and has no "
                    f"stop()/close() at all: nothing can end the "
                    f"thread, so process exit hangs on it — add a "
                    f"stop path that joins, or make it daemon with a "
                    f"documented reason")

    @staticmethod
    def _joined_attrs(stop_methods: Dict[str, ast.AST],
                      methods: Dict[str, ast.AST]) -> Set[str]:
        """``self._x`` receivers of ``.join()`` reachable from the stop
        methods (one transitive level of same-class callees, the same
        budget lockorder uses for release-path evidence)."""
        joined: Set[str] = set()
        frontier = list(stop_methods.values())
        seen = set(stop_methods)
        for _ in range(2):
            nxt: List[ast.AST] = []
            for fn in frontier:
                aliases = _self_aliases(fn)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "join":
                        recv = dotted_name(node.func.value)
                        if recv:
                            joined.add(aliases.get(recv, recv))
                    name = call_name(node)
                    if name and name.startswith("self."):
                        callee = name.rsplit(".", 1)[-1]
                        if callee in methods and callee not in seen:
                            seen.add(callee)
                            nxt.append(methods[callee])
            frontier = nxt
            if not frontier:
                break
        return joined

    # -- thread-leak: anonymous + function-local threads ---------------------

    def _check_anonymous_threads(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "start" and
                    _is_thread_ctor(node.func.value)):
                continue
            ctor = node.func.value
            assert isinstance(ctor, ast.Call)
            if _daemon_true(ctor):
                continue  # documented blind spot: deliberate daemons
            yield Finding(
                RULE_THREAD, module.rel, node.lineno,
                "anonymous non-daemon Thread(...).start(): no name "
                "ever references it, so it can never be joined and "
                "blocks process exit — keep a reference and join it, "
                "or pass daemon=True with a comment saying why "
                "fire-and-forget is safe here")

    def _check_local_threads(self, fn: ast.AST,
                             module: Module) -> Iterator[Finding]:
        locals_: Dict[str, Tuple[int, bool, bool]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name and "." not in name:
                        locals_[name] = (node.lineno,
                                         _daemon_true(node.value),
                                         _has_explicit_daemon(node.value))
        if not locals_:
            return
        started: Dict[str, int] = {}
        joined: Set[str] = set()
        escaped: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                recv = dotted_name(node.func.value)
                if recv in locals_:
                    if node.func.attr == "start":
                        started[recv] = node.lineno
                    elif node.func.attr == "join":
                        joined.add(recv)
            # x.daemon = True after construction counts
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon" and \
                            dotted_name(tgt.value) in locals_ and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value:
                        nm = dotted_name(tgt.value)
                        ln, _, _ = locals_[nm]
                        locals_[nm] = (ln, True, True)
                # escapes: returned, stored on self, appended, passed on
                src = dotted_name(node.value)
                if src in locals_ and node.targets and \
                        any(isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in node.targets):
                    escaped.add(src)
            if isinstance(node, ast.Return) and node.value is not None:
                src = dotted_name(node.value)
                if src in locals_:
                    escaped.add(src)
            if isinstance(node, ast.Call):
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    src = dotted_name(arg)
                    if src in locals_ and not (
                            isinstance(node.func, ast.Attribute) and
                            node.func.attr in ("start", "join")):
                        escaped.add(src)
        for name, start_line in sorted(started.items()):
            _, daemon, _ = locals_[name]
            if daemon or name in joined or name in escaped:
                continue
            yield Finding(
                RULE_THREAD, module.rel, start_line,
                f"local non-daemon thread {name!r} started but never "
                f"joined in {getattr(fn, 'name', '<fn>')!r} and never "
                f"handed to an owner — it outlives the function with "
                f"no shutdown story; join it, store it on an owner "
                f"with a stop path, or make it daemon")

    # -- resource-leak -------------------------------------------------------

    def _check_class_resources(self, cls: ast.ClassDef,
                               module: Module) -> Iterator[Finding]:
        methods: Dict[str, ast.AST] = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        close_methods = {n: m for n, m in methods.items()
                         if _is_stop_like(n)}
        closed = self._closed_attrs(close_methods, methods)
        for m in methods.values():
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                kind = _resource_kind(node.value)
                if kind is None:
                    continue
                for tgt in node.targets:
                    attr = dotted_name(tgt)
                    if not attr or not attr.startswith("self."):
                        continue
                    if attr in closed:
                        continue
                    yield Finding(
                        RULE_RESOURCE, module.rel, node.lineno,
                        f"{cls.name} opens a {kind} into {attr} but no "
                        f"stop()/close() path ever closes it — each "
                        f"instance leaks an fd/worker pool until the "
                        f"process hits EMFILE; close it on the stop "
                        f"path or use a context manager")

    @staticmethod
    def _closed_attrs(close_methods: Dict[str, ast.AST],
                      methods: Dict[str, ast.AST]) -> Set[str]:
        closed: Set[str] = set()
        frontier = list(close_methods.values())
        seen = set(close_methods)
        for _ in range(2):
            nxt: List[ast.AST] = []
            for fn in frontier:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _CLOSE_METHODS:
                        recv = dotted_name(node.func.value)
                        if recv:
                            closed.add(recv)
                    name = call_name(node)
                    if name and name.startswith("self."):
                        callee = name.rsplit(".", 1)[-1]
                        if callee in methods and callee not in seen:
                            seen.add(callee)
                            nxt.append(methods[callee])
            frontier = nxt
            if not frontier:
                break
        return closed

    def _check_local_resources(self, fn: ast.AST,
                               module: Module) -> Iterator[Finding]:
        opened: Dict[str, Tuple[int, str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                kind = _resource_kind(node.value)
                if kind is None:
                    continue
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name and "." not in name:
                        opened[name] = (node.lineno, kind)
        if not opened:
            return
        closed: Set[str] = set()
        escaped: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    recv = dotted_name(node.func.value)
                    if recv in opened and \
                            node.func.attr in _CLOSE_METHODS:
                        closed.add(recv)
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    src = dotted_name(arg)
                    if src in opened:
                        escaped.add(src)  # ownership transfer
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    src = dotted_name(sub)
                    if src in opened:
                        escaped.add(src)
            if isinstance(node, ast.Assign):
                src = dotted_name(node.value)
                if src in opened and \
                        any(isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in node.targets):
                    escaped.add(src)
        for name, (lineno, kind) in sorted(opened.items()):
            if name in closed or name in escaped:
                continue
            yield Finding(
                RULE_RESOURCE, module.rel, lineno,
                f"local {kind} {name!r} opened in "
                f"{getattr(fn, 'name', '<fn>')!r} without with-block, "
                f"close(), or ownership transfer — the fd leaks on "
                f"every call (and on every exception path)")
