"""Future-discipline pass: futures that can strand their waiters.

A future is a contract: someone awaits it, so SOME code path must
resolve it — success, error, or cancellation. The repo's PR 6 outage
shape was exactly this contract broken at shutdown: ``BatchingQueue``
handed callers loop-bound futures, ``stop()`` killed the loop, and the
queued futures were never resolved — callers blocked in
``cf.result()`` forever with no timeout. The fix (drain every queue
and ``set_exception(QueueStopped(...))`` on each pending future) is an
idiom this pass now enforces structurally. One rule,
``future-discipline``, with three sub-shapes:

1. **error-path stranding** — a ``try`` whose body (or ``else``)
   resolves a future with ``set_result`` while a broad ``except``
   neither re-raises nor ``set_exception``s the same future: on the
   error path the waiter waits forever.
2. **unguarded set** — ``set_result``/``set_exception`` on a future
   the function did NOT just create, without a ``done()``/
   ``cancelled()`` guard, ``set_running_or_notify_cancel()``, or
   ``contextlib.suppress(InvalidStateError)``: in racy contexts
   (timeouts, cancellation, duplicate completion) the second setter
   raises ``InvalidStateError`` from an arbitrary thread.
3. **stop-strand** (the PR 6 shape) — a class whose methods enqueue
   locally-created futures (``put_nowait``/``put``/``append`` of a
   fresh future, alone or in a tuple) and whose ``stop``/``close``/
   ``shutdown`` path shows NO evidence of failing them
   (``set_exception``, or a ``*fail*``/``*drain*`` same-class callee,
   directly or one call level deep). Cancelling the consumer task is
   deliberately NOT evidence: that is precisely what the broken PR 6
   ``stop()`` did — the task died, the queued futures stayed pending.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    dotted_name,
)
from cassmantle_tpu.analysis.exceptionflow import (
    REPO_DIRS,
    _handler_names,
    _walk_body,
)

RULE = "future-discipline"

_BROAD = {"Exception", "BaseException"}
#: calls that mint a fresh, still-pending future
_FUTURE_CTORS = {"loop.create_future", "create_future", "asyncio.Future",
                 "Future", "concurrent.futures.Future", "futures.Future"}
_ENQUEUE_METHODS = {"put_nowait", "put", "append", "appendleft"}
_STOP_NAMES = ("stop", "close", "shutdown", "aclose")


def _is_future_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name is None:
        return False
    return name in _FUTURE_CTORS or name.endswith(".create_future") or \
        name.endswith(".Future")


def _stop_like(name: str) -> bool:
    return name.lstrip("_").startswith(_STOP_NAMES)


@dataclass
class _ClassInfo:
    name: str
    lineno: int
    #: stop-ish method name -> node
    stop_methods: Dict[str, ast.AST] = field(default_factory=dict)
    #: every method, for the one-level transitive callee walk
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    #: (method name, lineno) of each enqueue-of-fresh-future site
    enqueue_sites: List[tuple] = field(default_factory=list)


class FutureDisciplinePass(LintPass):
    name = "futuredisc"
    description = ("futures that can escape unresolved: error-path "
                   "stranding, unguarded set_result/set_exception, "
                   "enqueued futures no stop() path ever fails")

    def __init__(self, dirs: Optional[Sequence[str]] = None) -> None:
        self.dirs = tuple(dirs) if dirs else None

    @classmethod
    def for_repo(cls) -> "FutureDisciplinePass":
        # same layers as exceptionflow: where futures cross threads/loops
        return cls(dirs=REPO_DIRS)

    def run(self, module: Module) -> Iterator[Finding]:
        if self.dirs and not any(module.rel.startswith(d)
                                 for d in self.dirs):
            return
        for fn in self._outermost_functions(module.tree):
            yield from self._check_function(fn, module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, module)

    @classmethod
    def _outermost_functions(cls, node: ast.AST) -> Iterator[ast.AST]:
        """Module-level functions and methods, but NOT nested defs: a
        closure that resolves a future created by its enclosing
        function must be checked in that enclosing scope (the
        created/guard sets cover the whole lexical body)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
            elif isinstance(child, (ast.ClassDef, ast.If, ast.Try,
                                    ast.With, ast.For, ast.While)):
                yield from cls._outermost_functions(child)

    # -- sub-shapes 1 & 2: per-function --------------------------------------

    def _check_function(self, fn: ast.AST,
                        module: Module) -> Iterator[Finding]:
        created = self._created_futures(fn)
        guarded = self._guard_receivers(fn)
        notified = self._notify_receivers(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Try):
                yield from self._check_error_path(node, fn, module)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("set_result", "set_exception"):
                recv = dotted_name(node.func.value)
                if recv is None or recv in created or recv in guarded or \
                        recv in notified:
                    continue
                if self._under_done_guard(node, fn, recv) or \
                        self._under_suppress(node, fn):
                    continue
                yield Finding(
                    RULE, module.rel, node.lineno,
                    f"{node.func.attr} on {recv!r} (not created in "
                    f"{fn.name!r}) without a done()/cancelled() guard — "
                    f"a racing completer (timeout, cancellation, "
                    f"duplicate resolve) raises InvalidStateError; "
                    f"guard with `if not {recv}.done():`")

    @staticmethod
    def _created_futures(fn: ast.AST) -> Set[str]:
        """Names bound to a fresh future inside this function: the
        creator is the sole resolver, so no race guard is needed."""
        names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_future_ctor(node.value):
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name is not None:
                        names.add(name)
        return names

    @staticmethod
    def _guard_receivers(fn: ast.AST) -> Set[str]:
        """Receivers tested with ``X.done()``/``X.cancelled()`` anywhere
        in the function — coarse, but a visible guard shows the author
        thought about the race (the precise path check is sub-shape 1's
        job)."""
        receivers: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("done", "cancelled"):
                recv = dotted_name(node.func.value)
                if recv is not None:
                    receivers.add(recv)
        return receivers

    @staticmethod
    def _notify_receivers(fn: ast.AST) -> Set[str]:
        """Receivers of ``set_running_or_notify_cancel()`` — the
        concurrent.futures handshake that makes a later set safe."""
        receivers: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "set_running_or_notify_cancel":
                recv = dotted_name(node.func.value)
                if recv is not None:
                    receivers.add(recv)
        return receivers

    @staticmethod
    def _under_done_guard(call: ast.Call, fn: ast.AST,
                          recv: str) -> bool:
        """The call sits under an ``if`` whose test mentions
        ``recv.done()`` / ``recv.cancelled()``."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            test_calls = [n for n in ast.walk(node.test)
                          if isinstance(n, ast.Call) and
                          isinstance(n.func, ast.Attribute) and
                          n.func.attr in ("done", "cancelled") and
                          dotted_name(n.func.value) == recv]
            if test_calls and any(n is call for n in ast.walk(node)):
                return True
        return False

    @staticmethod
    def _under_suppress(call: ast.Call, fn: ast.AST) -> bool:
        """``with contextlib.suppress(...InvalidStateError...)`` around
        the call, or a try/except catching InvalidStateError."""
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call) and \
                            (call_name(ctx) or "").endswith("suppress") and \
                            any("InvalidStateError" in (dotted_name(a) or "")
                                for a in ctx.args):
                        if any(n is call for n in ast.walk(node)):
                            return True
            if isinstance(node, ast.Try):
                caught = set()
                for h in node.handlers:
                    caught |= _handler_names(h)
                if any(n.rsplit(".", 1)[-1] == "InvalidStateError"
                       for n in caught):
                    if any(n is call for n in
                           ast.walk(ast.Module(body=node.body,
                                               type_ignores=[]))):
                        return True
        return False

    def _check_error_path(self, try_node: ast.Try, fn: ast.AST,
                          module: Module) -> Iterator[Finding]:
        """Sub-shape 1: set_result in try body/else, broad except that
        neither re-raises nor set_exceptions the same receiver."""
        resolved: Set[str] = set()
        for node in _walk_body(try_node.body + try_node.orelse):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "set_result":
                recv = dotted_name(node.func.value)
                if recv is not None:
                    resolved.add(recv)
        if not resolved:
            return
        for handler in try_node.handlers:
            names = _handler_names(handler)
            if handler.type is not None and not (names & _BROAD):
                continue
            failed: Set[str] = set()
            raises = False
            for node in _walk_body(handler.body):
                if isinstance(node, ast.Raise):
                    raises = True
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "set_exception":
                    recv = dotted_name(node.func.value)
                    if recv is not None:
                        failed.add(recv)
            if raises:
                continue
            stranded = resolved - failed
            if stranded:
                who = ", ".join(sorted(stranded))
                end = handler.body[0].lineno if handler.body else None
                yield Finding(
                    RULE, module.rel, handler.lineno,
                    f"error path strands waiter(s) of {who}: the try "
                    f"body set_result()s but this broad except neither "
                    f"re-raises nor set_exception()s — on failure the "
                    f"future never resolves and its awaiter blocks "
                    f"forever", end)

    # -- sub-shape 3: per-class stop-strand (the PR 6 pin) -------------------

    def _check_class(self, cls: ast.ClassDef,
                     module: Module) -> Iterator[Finding]:
        info = self._collect(cls)
        if not info.enqueue_sites or not info.stop_methods:
            return
        if self._stop_fails_futures(info):
            return
        sites = ", ".join(f"{m}:{ln}" for m, ln in info.enqueue_sites[:3])
        for stop_name, stop_node in sorted(info.stop_methods.items()):
            yield Finding(
                RULE, module.rel, stop_node.lineno,
                f"{cls.name}.{stop_name}() never fails the futures "
                f"enqueued at {sites}: after stop the consumer is gone "
                f"and queued futures stay pending forever (the PR 6 "
                f"stranding shape) — drain the queue and "
                f"set_exception() each pending future; cancelling the "
                f"consumer task is not enough")

    @staticmethod
    def _collect(cls: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(cls.name, cls.lineno)
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            info.methods[stmt.name] = stmt
            if _stop_like(stmt.name):
                info.stop_methods[stmt.name] = stmt
            # find locally-created futures enqueued onto queues/deques
            local_futs: Set[str] = set()
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and \
                        _is_future_ctor(node.value):
                    for tgt in node.targets:
                        name = dotted_name(tgt)
                        if name is not None:
                            local_futs.add(name)
            if not local_futs:
                continue
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in _ENQUEUE_METHODS):
                    continue
                for arg in node.args:
                    elts = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                    if any((dotted_name(e) or "") in local_futs
                           for e in elts):
                        info.enqueue_sites.append((stmt.name, node.lineno))
                        break
        return info

    @staticmethod
    def _stop_fails_futures(info: _ClassInfo) -> bool:
        """Evidence that the stop path resolves pending futures: a
        ``set_exception`` call, or a same-class ``self._x()`` callee
        whose name says fail/drain — checked in the stop methods and
        one transitive level of same-class callees."""
        frontier = list(info.stop_methods.values())
        seen: Set[str] = set(info.stop_methods)
        for _ in range(2):  # stop methods, then their direct callees
            next_frontier: List[ast.AST] = []
            for fn in frontier:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    if name is None:
                        continue
                    last = name.rsplit(".", 1)[-1]
                    if last == "set_exception" or "fail" in last or \
                            "drain" in last:
                        return True
                    if name.startswith("self.") and "." not in last and \
                            last in info.methods and last not in seen:
                        seen.add(last)
                        next_frontier.append(info.methods[last])
            frontier = next_frontier
            if not frontier:
                break
        return False
