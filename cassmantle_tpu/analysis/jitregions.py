"""Shared jit-region discovery for the JAX-discipline passes.

One module owns the question "which functions in this file run traced
under ``jax.jit``, and with what static arguments?" — extracted from
``hostsync.py`` (which found jit regions but threw the static-argument
information away) so ``recompile.py`` and ``tracerleak.py`` can reason
about *which parameters are traced* and *where jitted callables are
invoked* without re-implementing the discovery.

Recognized jit shapes (the ones the repo actually uses):

- decorated: ``@jax.jit``, ``@jax.jit(...)``,
  ``@partial(jax.jit, static_argnums=..., static_argnames=...)``;
- passed: ``jax.jit(f, ...)``, ``jax.jit(self.m, ...)``,
  ``jax.jit(partial(self.m, k), ...)`` — partial-bound leading
  positionals are treated as static (they key the jit cache);
- wrappers: ``dp_sharded_sampler(self._sample_impl, mesh)`` — the
  serving pipelines' sharded-jit helper.

The **closure** of an entry (same-module functions it transitively
calls through bare names or ``self.X``/``cls.X``) runs traced too —
identical to hostsync's fixpoint, now shared.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from cassmantle_tpu.analysis.core import call_name, dotted_name

JIT_NAMES = {"jax.jit", "jit"}
JIT_WRAPPERS = {"dp_sharded_sampler"}
PARTIAL_NAMES = {"partial", "functools.partial"}


@dataclasses.dataclass
class JitEntry:
    """One function that enters a jit region as the traced entry point.

    ``params`` are the positional parameter names with a leading
    ``self``/``cls`` dropped; ``static_params`` the subset that is NOT
    traced (declared via static_argnums/static_argnames, or bound by a
    ``partial`` before jit saw the function). ``traced_params`` is the
    rest. ``explicit_statics`` records whether any static declaration
    was visible — passes that need to reason about "the author marked
    this static" can distinguish "no statics" from "unknown"."""

    fn: ast.AST
    params: List[str] = dataclasses.field(default_factory=list)
    static_params: Set[str] = dataclasses.field(default_factory=set)
    explicit_statics: bool = False

    @property
    def traced_params(self) -> List[str]:
        return [p for p in self.params if p not in self.static_params]


@dataclasses.dataclass
class JitAlias:
    """A name a jitted callable is reachable through at call sites:
    ``g = jax.jit(f, ...)`` (key ``g``), ``self._x = jax.jit(...)``
    (key ``_x``), or a directly-decorated function (key ``f``).

    ``bound`` is the number of leading positionals a wrapping
    ``partial`` consumed: call-site argument ``i`` maps to
    ``entry.params[bound + i]``, and ``static_argnums`` (from the jit
    call itself) index the partial-reduced signature — i.e. call-site
    positions directly."""

    key: str
    entry: Optional[JitEntry]        # resolved same-module target
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    bound: int = 0
    #: this alias's OWN jit site declared statics — callers should then
    #: trust these over the (possibly multi-site-merged) entry's
    explicit: bool = False


def function_table(tree: ast.Module) -> Dict[str, ast.AST]:
    """qual -> node for top-level functions and methods; bare method
    names are also keyed (for ``self.X`` / ``jax.jit(self.X)``
    resolution) when unambiguous enough — first definition wins."""
    fns: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fns.setdefault(f"{node.name}.{sub.name}", sub)
                    fns.setdefault(sub.name, sub)
    return fns


def positional_params(fn: ast.AST) -> List[str]:
    """Positional parameter names, leading ``self``/``cls`` dropped
    (jit always sees the bound method)."""
    params = [a.arg for a in fn.args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return params


def _int_constants(expr: Optional[ast.expr]) -> Tuple[int, ...]:
    """static_argnums as a tuple of ints (``0`` or ``(0, 5)``);
    anything dynamic resolves to ()."""
    if expr is None:
        return ()
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_constants(expr: Optional[ast.expr]) -> Tuple[str, ...]:
    if expr is None:
        return ()
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(e.value for e in expr.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _static_kwargs(call: ast.Call) -> Tuple[Tuple[int, ...],
                                            Tuple[str, ...], bool]:
    nums = names = None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = kw.value
        elif kw.arg == "static_argnames":
            names = kw.value
    explicit = nums is not None or names is not None
    return _int_constants(nums), _str_constants(names), explicit


def _target_names(expr: ast.expr) -> Tuple[List[str], int]:
    """(function names referenced by a jit(...) argument, number of
    positionals a wrapping ``partial`` binds): a bare name, a
    ``self.X`` attribute, or either inside ``partial``."""
    if isinstance(expr, ast.Name):
        return [expr.id], 0
    if isinstance(expr, ast.Attribute):
        return [expr.attr], 0
    if isinstance(expr, ast.Call) and \
            call_name(expr) in PARTIAL_NAMES and expr.args:
        names, _ = _target_names(expr.args[0])
        return names, len(expr.args) - 1
    return [], 0


def _make_entry(fn: ast.AST, bound_n: int,
                static_argnums: Tuple[int, ...],
                static_argnames: Tuple[str, ...],
                explicit: bool,
                argnums_include_self: bool = False) -> JitEntry:
    all_params = [a.arg for a in fn.args.args]
    has_self = bool(all_params) and all_params[0] in ("self", "cls")
    params = all_params[1:] if has_self else all_params
    if argnums_include_self and has_self:
        # a DECORATED method is jitted unbound: jax counts ``self`` as
        # position 0, so the declared indices shift down by one over
        # the self-dropped list (index 0 names self itself — skip it)
        static_argnums = tuple(i - 1 for i in static_argnums if i >= 1)
    static: Set[str] = set(params[:bound_n])
    rest = params[bound_n:]
    for i in static_argnums:
        if 0 <= i < len(rest):
            static.add(rest[i])
    static |= set(static_argnames) & set(params)
    return JitEntry(fn=fn, params=params, static_params=static,
                    explicit_statics=explicit)


def jit_entries(tree: ast.Module,
                fns: Dict[str, ast.AST]) -> Dict[ast.AST, JitEntry]:
    """fn node -> JitEntry for every function that is jit-compiled as
    an entry point (decorated, passed to jit, or wrapper-jitted)."""
    entries: Dict[ast.AST, JitEntry] = {}

    def add(fn, bound_n, nums, names, explicit, include_self=False):
        made = _make_entry(fn, bound_n, nums, names, explicit,
                           argnums_include_self=include_self)
        if fn in entries:
            # a SECOND jit site for the same function: keep only the
            # statics every site agrees on (intersection) — a union
            # would let one alias's static declarations misclassify
            # another alias's traced call positions
            entries[fn].static_params &= made.static_params
            entries[fn].explicit_statics |= explicit
        else:
            entries[fn] = made

    # decorated: @jax.jit / @jax.jit(...) / @partial(jax.jit, ...) —
    # jitted UNBOUND, so static_argnums count self (include_self)
    for fn in set(fns.values()):
        for dec in getattr(fn, "decorator_list", ()):
            if isinstance(dec, ast.Call):
                dec_name = call_name(dec)
                if dec_name in JIT_NAMES:
                    nums, names, explicit = _static_kwargs(dec)
                    add(fn, 0, nums, names, explicit, include_self=True)
                elif dec_name in PARTIAL_NAMES and dec.args and \
                        dotted_name(dec.args[0]) in JIT_NAMES:
                    nums, names, explicit = _static_kwargs(dec)
                    add(fn, 0, nums, names, explicit, include_self=True)
            elif dotted_name(dec) in JIT_NAMES:
                add(fn, 0, (), (), False, include_self=True)
    # passed: jax.jit(f) / jax.jit(partial(f, k)) /
    # dp_sharded_sampler(self._sample_impl, ...)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        is_jit = name in JIT_NAMES
        is_wrapper = (name or "").rsplit(".", 1)[-1] in JIT_WRAPPERS
        if not (is_jit or is_wrapper) or not node.args:
            continue
        targets, bound_n = _target_names(node.args[0])
        nums, names_, explicit = (_static_kwargs(node) if is_jit
                                  else ((), (), False))
        for target in targets:
            if target in fns:
                add(fns[target], bound_n, nums, names_, explicit)
    return entries


def jit_closure(tree: ast.Module, fns: Dict[str, ast.AST],
                entries: Optional[Set[ast.AST]] = None) -> Set[ast.AST]:
    """Entries plus same-module functions they (transitively) call
    — a helper called from a jit body runs traced too."""
    if entries is None:
        entries = set(jit_entries(tree, fns))
    closure = set(entries)
    queue = list(closure)
    while queue:
        fn = queue.pop()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            target = None
            if isinstance(f, ast.Name) and f.id in fns:
                target = fns[f.id]
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id in ("self", "cls")
                  and f.attr in fns):
                target = fns[f.attr]
            if target is not None and target not in closure:
                closure.add(target)
                queue.append(target)
    return closure


def jit_aliases(tree: ast.Module, fns: Dict[str, ast.AST],
                entries: Optional[Dict[ast.AST, JitEntry]] = None
                ) -> Dict[str, JitAlias]:
    """Call-site names resolving to jitted callables: assignments of a
    jit/wrapper call to a bare name or a ``self.X`` attribute, plus
    directly-decorated functions (callable by their own name). Keys are
    the bare name / attribute name — call sites look up ``g(...)`` and
    ``self._x(...)`` by that key. Pass precomputed ``entries`` to avoid
    re-running discovery."""
    if entries is None:
        entries = jit_entries(tree, fns)
    # None marks a key two different jit signatures fought over —
    # ambiguous, filtered out of the returned map
    aliases: Dict[str, Optional[JitAlias]] = {}
    for fn, entry in entries.items():
        for dec in getattr(fn, "decorator_list", ()):
            is_jit = (dotted_name(dec) in JIT_NAMES
                      or (isinstance(dec, ast.Call)
                          and (call_name(dec) in JIT_NAMES
                               or (call_name(dec) in PARTIAL_NAMES
                                   and dec.args
                                   and dotted_name(dec.args[0])
                                   in JIT_NAMES))))
            if is_jit:
                aliases[getattr(fn, "name", "")] = JitAlias(
                    key=getattr(fn, "name", ""), entry=entry)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        name = call_name(value)
        if name not in JIT_NAMES and \
                (name or "").rsplit(".", 1)[-1] not in JIT_WRAPPERS:
            continue
        nums, argnames, explicit = (_static_kwargs(value)
                                    if name in JIT_NAMES
                                    else ((), (), False))
        entry = None
        bound = 0
        if value.args:
            targets, bound = _target_names(value.args[0])
            for t in targets:
                if t in fns:
                    entry = entries.get(fns[t])
                    break
        for target in node.targets:
            key = None
            if isinstance(target, ast.Name):
                key = target.id
            elif isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                key = target.attr
            if key is not None:
                alias = JitAlias(key=key, entry=entry,
                                 static_argnums=nums,
                                 static_argnames=argnames,
                                 bound=bound, explicit=explicit)
                prior = aliases.get(key)
                if prior is not None and (
                        prior.entry is not alias.entry
                        or prior.static_argnums != alias.static_argnums
                        or prior.static_argnames != alias.static_argnames
                        or prior.bound != alias.bound):
                    # two classes (or rebinding paths) share the key
                    # with different jit signatures: call sites can't
                    # be attributed safely — drop the alias rather
                    # than check calls against the wrong statics
                    aliases[key] = None
                else:
                    aliases[key] = alias
    return {k: v for k, v in aliases.items() if v is not None}
