"""Blocking-call-in-async pass: the event-loop stall detector.

A blocking call lexically inside an ``async def`` body freezes the
whole worker — the 1 Hz clock pushes, every WS connection, every
in-flight request — for its full duration (the PR 2 wedge class, seen
from the other side). This pass flags, inside ``async def`` bodies:

- ``time.sleep`` (use ``await asyncio.sleep``);
- unbounded waits — zero-arg ``.result()`` / ``.get()`` / ``.wait()`` /
  ``.join()`` (await the async counterpart or add a timeout + executor);
- device syncs — ``block_until_ready`` / ``jax.device_get`` (route
  through ``loop.run_in_executor`` like the pipelines do);
- synchronous I/O — ``open()``, ``requests.*`` / ``urllib.request.*``
  HTTP, ``subprocess.run/call/check_*`` and ``os.system``.

Executor-routed work passes by construction: ``await
loop.run_in_executor(None, fn, ...)`` passes ``fn`` as a *reference*,
not a call, and directly-awaited calls are exempt (awaiting yields).
Nested sync ``def``/``lambda`` bodies are skipped — they run wherever
they are called, typically on an executor thread.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence

from cassmantle_tpu.analysis.core import Finding, LintPass, Module, call_name
from cassmantle_tpu.analysis.lockorder import blocking_wait_reason

RULE = "async-blocking-call"

_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output"}

# awaited wrappers whose call-arguments are coroutine/future factories
# (`await asyncio.wait_for(cond.wait(), ...)`): the inner call is
# awaited machinery, not a blocking call on the loop
_ASYNC_WRAPPERS = {
    "asyncio.wait_for", "asyncio.wait", "asyncio.shield",
    "asyncio.gather", "asyncio.wrap_future", "asyncio.ensure_future",
    "asyncio.create_task", "asyncio.as_completed",
}

# async handler/pipeline/engine layers — the dirs whose async defs feed
# the serving event loop (ops/models are sync-only by construction)
REPO_DIRS = ("cassmantle_tpu/server/", "cassmantle_tpu/serving/",
             "cassmantle_tpu/engine/", "cassmantle_tpu/fabric/")


def _blocking_reason(node: ast.Call) -> Optional[str]:
    reason = blocking_wait_reason(node)
    if reason is not None:
        return reason
    name = call_name(node)
    if name is None:
        return None
    if name == "open":
        return "synchronous file I/O"
    root = name.split(".", 1)[0]
    if root == "requests" or name.startswith("urllib.request."):
        return "synchronous HTTP request"
    if name == "os.system":
        return "os.system() blocks on the child process"
    if root == "subprocess" and \
            name.rsplit(".", 1)[-1] in _SUBPROCESS_BLOCKING:
        return "synchronous subprocess wait"
    return None


class AsyncBlockingPass(LintPass):
    name = "async-blocking"
    description = "blocking calls lexically inside async def bodies"

    def __init__(self, dirs: Optional[Sequence[str]] = None) -> None:
        # None = lint every module handed in (fixtures); the repo run
        # scopes to the event-loop layers via for_repo()
        self.dirs = tuple(dirs) if dirs else None

    @classmethod
    def for_repo(cls) -> "AsyncBlockingPass":
        return cls(dirs=REPO_DIRS)

    def run(self, module: Module) -> Iterator[Finding]:
        if self.dirs and not any(module.rel.startswith(d)
                                 for d in self.dirs):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._scan_async(node, module)

    def _scan_async(self, fn: ast.AsyncFunctionDef,
                    module: Module) -> Iterator[Finding]:
        findings: List[Finding] = []

        def scan(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested defs run elsewhere (nested async defs
                # are visited by the outer walk in run())
            if isinstance(node, ast.Await):
                value = node.value
                if isinstance(value, ast.Call):
                    # the awaited call yields; only its arguments can
                    # still hide a blocking call — and when the awaited
                    # call is asyncio machinery, its call-arguments are
                    # coroutine factories, exempt one level down too
                    wrapper = call_name(value) in _ASYNC_WRAPPERS
                    for child in ast.iter_child_nodes(value):
                        if wrapper and isinstance(child, ast.Call):
                            for sub in ast.iter_child_nodes(child):
                                scan(sub)
                        else:
                            scan(child)
                else:
                    scan(value)
                return
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node)
                if reason is not None:
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"{reason} inside async def {fn.name!r} — the "
                        f"event loop stalls for its full duration; "
                        f"await the async form or route through "
                        f"loop.run_in_executor",
                        getattr(node, "end_lineno", None)))
            for child in ast.iter_child_nodes(node):
                scan(child)

        for stmt in fn.body:
            scan(stmt)
        yield from findings
