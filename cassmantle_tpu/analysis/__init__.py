"""Static analysis: the lint framework behind ``tools/check_*.py``.

The two worst bugs this repo has shipped were concurrency bugs (the
PR 1 device-dispatch deadlock, the PR 2 wedged dispatch thread) — the
class of hazard an AST pass catches before it reaches a serving fleet.
This package is the shared machinery: ``core`` (module parsing,
``# lint: ignore[rule]`` suppressions, JSON/human reporters, the
runner), plus one module per pass. ``docs/STATIC_ANALYSIS.md`` is the
rule catalog and the how-to-add-a-pass guide.

Entry points: ``tools/check_concurrency.py`` (lock discipline,
blocking-in-async, host-sync), ``tools/check_metrics.py`` (metric
naming/catalog), ``tools/lint_all.py`` (everything, one exit code) —
all gated as fast-tier tests.
"""

from cassmantle_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintPass,
    Module,
    iter_modules,
    parse_source,
    run_passes,
)
