"""Static analysis: the lint framework behind ``tools/check_*.py``.

The two worst bugs this repo has shipped were concurrency bugs (the
PR 1 device-dispatch deadlock, the PR 2 wedged dispatch thread) — the
class of hazard an AST pass catches before it reaches a serving fleet.
This package is the shared machinery: ``core`` (module parsing,
``# lint: ignore[rule]`` suppressions, JSON/human reporters, the
runner), plus one module per pass. ``docs/STATIC_ANALYSIS.md`` is the
rule catalog and the how-to-add-a-pass guide.

Entry points: ``tools/check_concurrency.py`` (lock discipline,
blocking-in-async, host-sync), ``tools/check_metrics.py`` (metric
naming/catalog), ``tools/check_jax.py`` (recompile hazards, tracer
leaks, host-buffer escapes, env-flag registry — jit-region discovery
shared via ``jitregions``), ``tools/lint_all.py`` (everything, one
exit code) — all gated as fast-tier tests. Runtime counterparts:
``utils/locks.OrderedLock`` (lock discipline) and
``utils/jit_sentinel`` (compile counts), both armed per test.
"""

from cassmantle_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintPass,
    Module,
    iter_modules,
    parse_source,
    run_passes,
)
