"""Shared AST lint infrastructure: modules, suppressions, runner, reporters.

Every pass (lock discipline, blocking-in-async, host-sync, metric
names) plugs into the same three pieces:

- :func:`iter_modules` / :func:`parse_source` build :class:`Module`
  objects — source + AST + parsed suppression comments — once per file,
  shared by all passes in a run;
- :class:`LintPass` subclasses yield :class:`Finding`s from a module;
- :func:`run_passes` filters findings through the suppressions and
  sorts them; :func:`format_human` / :func:`to_json` render them; and
  :func:`main_for` is the shared CLI (``<tool> [root] [--json]``,
  exit 1 on findings) every ``tools/check_*.py`` entry point wraps.

Suppression syntax (see docs/STATIC_ANALYSIS.md):

- ``# lint: ignore[rule]`` on (any line of) the offending statement —
  or on its own line directly above it — suppresses that rule there;
  always follow with ``— reason``;
- ``# lint: ignore-file[rule]`` anywhere in a file suppresses the rule
  for the whole file.

This package is stdlib-only on purpose: the lint tools must run (and
fail CI) in a few hundred milliseconds, with no jax import.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import pathlib
import re
import sys
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

REPO = pathlib.Path(__file__).resolve().parents[2]
PACKAGE = REPO / "cassmantle_tpu"

_IGNORE = re.compile(
    r"#\s*lint:\s*ignore(?P<scope>-file)?\[(?P<rules>[a-z0-9_\-, ]+)\]"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: ``rule`` names the check (the suppression key),
    ``lineno``/``end_lineno`` anchor it (suppression comments anywhere
    in that statement span apply)."""

    rule: str
    path: str
    lineno: int
    message: str
    end_lineno: Optional[int] = None

    def __str__(self) -> str:
        return f"{self.path}:{self.lineno}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path,
                "lineno": self.lineno, "message": self.message}


class Suppressions:
    """``# lint: ignore[rule]`` comments, parsed from the token stream
    (comments never reach the AST)."""

    def __init__(self) -> None:
        self.file_rules: Set[str] = set()
        self.line_rules: Dict[int, Set[str]] = {}

    @classmethod
    def parse(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _IGNORE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group("rules").split(",")
                         if r.strip()}
                if m.group("scope"):
                    sup.file_rules |= rules
                else:
                    row = tok.start[0]
                    sup.line_rules.setdefault(row, set()).update(rules)
                    # a comment standing on its own line covers the next
                    # line too (the statement it annotates)
                    if tok.line[:tok.start[1]].strip() == "":
                        sup.line_rules.setdefault(
                            row + 1, set()).update(rules)
        except tokenize.TokenError:
            pass  # half-written file: lint what parsed, suppress nothing
        return sup

    def allows(self, rule: str, lineno: int,
               end_lineno: Optional[int] = None) -> bool:
        if rule in self.file_rules:
            return True
        for line in range(lineno, (end_lineno or lineno) + 1):
            if rule in self.line_rules.get(line, ()):
                return True
        return False


@dataclasses.dataclass
class Module:
    """One parsed source file, shared by every pass in a run."""

    rel: str                      # repo-relative path (or fixture name)
    source: str
    tree: ast.Module
    suppressions: Suppressions


def parse_source(source: str, rel: str = "<fixture>") -> Module:
    return Module(rel=rel, source=source,
                  tree=ast.parse(source, filename=rel),
                  suppressions=Suppressions.parse(source))


def iter_modules(root: pathlib.Path,
                 repo_root: pathlib.Path = REPO) -> List[Module]:
    modules = []
    for path in sorted(pathlib.Path(root).rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            rel = str(path.relative_to(repo_root))
        except ValueError:
            rel = str(path)
        modules.append(parse_source(path.read_text(), rel))
    return modules


class LintPass:
    """One named check. ``run`` yields raw findings; the runner applies
    suppressions, so passes never need to know about them.

    A pass that needs the WHOLE module set before it can judge (e.g.
    the env-flag registry's "documented but never read" direction) may
    override ``finalize``: it runs once after every module has been
    ``run``, and its findings bypass per-line suppressions (they
    usually anchor to a docs file, not a linted module)."""

    name = "base"
    description = ""

    def run(self, module: Module) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self) -> Iterator[Finding]:
        return iter(())


def run_passes(modules: Iterable[Module],
               passes: Sequence[LintPass]) -> List[Finding]:
    findings = []
    for module in modules:
        for p in passes:
            for f in p.run(module):
                if not module.suppressions.allows(
                        f.rule, f.lineno, f.end_lineno):
                    findings.append(f)
    for p in passes:
        findings.extend(p.finalize())
    findings.sort(key=lambda f: (f.path, f.lineno, f.rule, f.message))
    return findings


# -- shared AST helpers ----------------------------------------------------

def dotted_name(expr: ast.expr) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains; None for anything dynamic."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def self_attr(expr: ast.expr) -> Optional[str]:
    """``X`` for a ``self.X`` attribute expression; None otherwise."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


# -- reporting -------------------------------------------------------------

def format_human(findings: Sequence[Finding]) -> str:
    lines = [str(f) for f in findings]
    lines.append(f"{len(findings)} violation(s)" if findings else "clean")
    return "\n".join(lines)


def to_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {"violations": [f.as_dict() for f in findings],
         "count": len(findings)},
        indent=2, sort_keys=True)


def main_for(passes, argv: Optional[Sequence[str]],
             default_root: pathlib.Path = PACKAGE,
             prog: str = "lint") -> int:
    """Shared CLI: ``<tool> [root] [--json]``; exit 1 on findings.
    ``passes`` is a sequence, or a callable ``root -> sequence`` for
    pass sets whose behavior depends on the walked root (the env-flag
    registry only checks stale doc rows on a full-package walk)."""
    import argparse

    parser = argparse.ArgumentParser(prog=prog)
    parser.add_argument("root", nargs="?", default=str(default_root))
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    args = parser.parse_args(argv)
    if callable(passes):
        passes = passes(pathlib.Path(args.root))
    findings = run_passes(iter_modules(pathlib.Path(args.root)), passes)
    if args.json:
        print(to_json(findings))
    else:
        print(format_human(findings),
              file=sys.stderr if findings else sys.stdout)
    return 1 if findings else 0
