"""Fault-point registry pass: every ``fault_point("name")`` documented.

The same contract the env-flag and metric-name passes enforce, applied
to the chaos subsystem (``cassmantle_tpu/chaos/``, docs/CHAOS.md):
every ``fault_point(...)`` / ``afault_point(...)`` call in the package
must name a registered fault point — a row in the docs/CHAOS.md
fault-point registry table — and every row there must correspond to a
real call site. An unregistered point is a drill lever the operator
cannot find; a stale row is a drill that silently injects nothing.
Rule ``fault-point``, three directions:

- per module: calls whose literal name has no registry row;
- per module: calls whose name is NOT a literal (the registry contract
  needs greppable names, exactly like metric names);
- finalize(): registry rows whose point is never hit anywhere in the
  walked module set (anchored at the docs line) — skipped on scoped
  runs like the env-flag orphan check.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cassmantle_tpu.analysis.core import (
    REPO,
    Finding,
    LintPass,
    Module,
    call_name,
)

RULE = "fault-point"

REGISTRY_DOC = REPO / "docs" / "CHAOS.md"
_SECTION = "## Fault-point registry"
_ROW = re.compile(r"^\|\s*`([a-z0-9_.]+)`")
_CALLS = ("fault_point", "afault_point")


def load_registry(doc: pathlib.Path = REGISTRY_DOC) -> Dict[str, int]:
    """point -> line number for every first-column backticked name in
    the docs/CHAOS.md fault-point registry table."""
    if not doc.exists():
        return {}
    registry: Dict[str, int] = {}
    in_section = False
    for lineno, line in enumerate(doc.read_text().splitlines(), 1):
        if line.startswith("## "):
            in_section = line.startswith(_SECTION)
            continue
        if in_section:
            m = _ROW.match(line.strip())
            if m:
                registry.setdefault(m.group(1), lineno)
    return registry


def extract_calls(tree: ast.Module
                  ) -> List[Tuple[Optional[str], int]]:
    """(point-or-None, lineno) for every ``fault_point``/``afault_point``
    call; None = the name argument is not a string literal."""
    calls: List[Tuple[Optional[str], int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None or name.rsplit(".", 1)[-1] not in _CALLS:
            continue
        if not node.args:
            calls.append((None, node.lineno))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            calls.append((arg.value, node.lineno))
        else:
            calls.append((None, node.lineno))
    return calls


class FaultPointPass(LintPass):
    name = "faultpoints"
    description = ("fault_point()/afault_point() names registered in "
                   "the docs/CHAOS.md fault-point table, and vice "
                   "versa")

    def __init__(self, registry: Optional[Dict[str, int]] = None,
                 check_orphans: bool = True) -> None:
        self._registry = registry
        self._check_orphans = check_orphans
        self._seen: Set[str] = set()
        self._warned_empty = False

    @property
    def registry(self) -> Dict[str, int]:
        if self._registry is None:
            self._registry = load_registry()
        return self._registry

    def run(self, module: Module) -> Iterator[Finding]:
        registry = self.registry
        calls = extract_calls(module.tree)
        if calls and not registry and not self._warned_empty:
            self._warned_empty = True
            yield Finding(RULE, str(REGISTRY_DOC), 1,
                          "fault-point registry (docs/CHAOS.md table) "
                          "missing or empty")
        for point, lineno in calls:
            if point is None:
                yield Finding(
                    RULE, module.rel, lineno,
                    "fault point name must be a string literal — the "
                    "docs/CHAOS.md registry contract needs greppable "
                    "names")
                continue
            self._seen.add(point)
            if registry and point not in registry:
                yield Finding(
                    RULE, module.rel, lineno,
                    f"fault point {point!r} has no row in the "
                    f"docs/CHAOS.md registry table — document the "
                    f"drill lever")

    def finalize(self) -> Iterator[Finding]:
        if not self._check_orphans:
            return
        for point, lineno in sorted(self.registry.items()):
            if point not in self._seen:
                yield Finding(
                    RULE, "docs/CHAOS.md", lineno,
                    f"{point} has a registry row but no "
                    f"fault_point()/afault_point() call site in the "
                    f"package — stale drill lever (remove the row or "
                    f"wire the point)")
