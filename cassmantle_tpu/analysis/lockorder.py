"""Lock-discipline pass: acquisition-order cycles, locks held across
``await``, locks held across known-blocking calls.

This is the static half of the defense against the PR 1 deadlock class
(two call paths acquiring the same pair of locks in opposite order hung
the backend under 3 concurrent round generations; the runtime half is
``utils/locks.OrderedLock``). Per module it:

1. extracts every lock attribute — ``self.X = threading.Lock() /
   RLock() / Condition()`` or ``OrderedLock(...)`` inside a class, and
   the same at module level;
2. walks each top-level function / method tracking the *statically
   nested* ``with <lock>:`` stack, recording a directed edge
   ``held -> acquired`` for every nested acquisition — including
   **inter-procedural** nesting through same-module calls (``self.m()``
   and bare-name calls) via a transitive acquires fixpoint;
3. fails on cycles in that graph (``lock-order-cycle``), on ``await``
   under a held lock (``lock-across-await`` — the event loop stalls
   every other coroutine needing the lock), and on known-blocking calls
   under a held lock (``lock-blocking-call`` — ``time.sleep``, unbounded
   ``.result()/.get()/.wait()/.join()``, ``block_until_ready`` /
   ``jax.device_get`` device syncs).

Known limits (documented in docs/STATIC_ANALYSIS.md): analysis is
per-module; calls through non-``self`` receivers and property reads are
not resolved; ``.acquire()``/``.release()`` outside ``with`` are not
tracked. Reentrant kinds (RLock/Condition) do not self-deadlock, so
self-edges on them are ignored.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
    "OrderedLock", "locks.OrderedLock",
}
_REENTRANT_CTORS = {
    "threading.RLock", "RLock", "threading.Condition", "Condition",
}

RULE_CYCLE = "lock-order-cycle"
RULE_AWAIT = "lock-across-await"
RULE_BLOCKING = "lock-blocking-call"


def blocking_wait_reason(node: ast.Call) -> Optional[str]:
    """Why this call is a known-blocking wait, or None. Shared with the
    blocking-in-async pass. Zero-arg ``.result()/.get()/.wait()/.join()``
    are unbounded waits (dict.get etc. always take arguments)."""
    name = call_name(node)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if name == "time.sleep":
        return "time.sleep() blocks the thread"
    if last == "block_until_ready":
        return "block_until_ready() waits on in-flight device work"
    if last == "device_get":
        return "device_get() forces a device->host sync"
    if last in ("result", "get", "wait", "join") \
            and not node.args and not node.keywords:
        return f".{last}() with no timeout is an unbounded blocking wait"
    return None


@dataclasses.dataclass
class _FnInfo:
    qual: str
    acquires: Set[str] = dataclasses.field(default_factory=set)
    # (callee_qual, locks held at the call site, lineno)
    calls: List[Tuple[str, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)
    # direct nested acquisitions: (held, acquired, lineno)
    edges: List[Tuple[str, str, int]] = dataclasses.field(default_factory=list)
    findings: List[Finding] = dataclasses.field(default_factory=list)


class LockOrderPass(LintPass):
    name = "lock-order"
    description = ("lock acquisition-order cycles, locks held across "
                   "await, locks held across blocking calls")

    def run(self, module: Module) -> Iterator[Finding]:
        locks = self._collect_locks(module.tree)
        if not locks:
            return
        infos = self._analyze_functions(module, locks)
        for info in infos.values():
            yield from info.findings
        yield from self._cycle_findings(module, locks, infos)

    # -- lock + function discovery ----------------------------------------

    @staticmethod
    def _lock_kind(value: ast.expr) -> Optional[str]:
        if isinstance(value, ast.Call):
            ctor = call_name(value)
            if ctor in _LOCK_CTORS:
                return ("reentrant" if ctor in _REENTRANT_CTORS
                        else "exclusive")
        return None

    def _collect_locks(self, tree: ast.Module) -> Dict[Tuple[Optional[str],
                                                             str], str]:
        """(class or None, attr) -> kind, for every lock-typed attribute
        assignment anywhere in the module."""
        locks: Dict[Tuple[Optional[str], str], str] = {}

        def visit_assign(node: ast.Assign, cls: Optional[str]) -> None:
            kind = self._lock_kind(node.value)
            if kind is None:
                return
            for target in node.targets:
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self" and cls is not None):
                    locks[(cls, target.attr)] = kind
                elif isinstance(target, ast.Name) and cls is None:
                    locks[(None, target.id)] = kind

        for node in tree.body:
            if isinstance(node, ast.Assign):
                visit_assign(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        visit_assign(sub, node.name)
        return locks

    @staticmethod
    def _functions(tree: ast.Module):
        """Yield (class_name or None, function node) for top-level
        functions and methods."""
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield None, node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        yield node.name, sub

    # -- per-function scan -------------------------------------------------

    def _analyze_functions(self, module: Module,
                           locks) -> Dict[str, _FnInfo]:
        fn_names: Set[str] = set()
        fns = list(self._functions(module.tree))
        for cls, fn in fns:
            fn_names.add(f"{cls}.{fn.name}" if cls else fn.name)
        infos: Dict[str, _FnInfo] = {}
        for cls, fn in fns:
            qual = f"{cls}.{fn.name}" if cls else fn.name
            info = _FnInfo(qual=qual)
            self._scan(fn.body, [], module, cls, locks, fn_names, info)
            infos[qual] = info
        return infos

    def _resolve_lock(self, expr: ast.expr, cls: Optional[str],
                      locks) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None
                and (cls, expr.attr) in locks):
            return f"{cls}.{expr.attr}"
        if isinstance(expr, ast.Name) and (None, expr.id) in locks:
            return expr.id
        return None

    @staticmethod
    def _resolve_callee(node: ast.Call, cls: Optional[str],
                        fn_names: Set[str]) -> Optional[str]:
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls") and cls is not None):
            qual = f"{cls}.{f.attr}"
            return qual if qual in fn_names else None
        if isinstance(f, ast.Name) and f.id in fn_names:
            return f.id
        return None

    def _scan(self, nodes, held: List[str], module: Module,
              cls: Optional[str], locks, fn_names: Set[str],
              info: _FnInfo) -> None:
        for node in nodes if isinstance(nodes, list) else [nodes]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested definitions execute elsewhere
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    lock = self._resolve_lock(item.context_expr, cls, locks)
                    if lock is not None:
                        for h in held:
                            info.edges.append((h, lock,
                                               item.context_expr.lineno))
                        info.acquires.add(lock)
                        held.append(lock)
                        pushed += 1
                    else:
                        self._scan(item.context_expr, held, module, cls,
                                   locks, fn_names, info)
                self._scan(node.body, held, module, cls, locks, fn_names,
                           info)
                for _ in range(pushed):
                    held.pop()
                continue
            if isinstance(node, ast.Await):
                if held:
                    info.findings.append(Finding(
                        RULE_AWAIT, module.rel, node.lineno,
                        f"await while holding lock {held[-1]!r} in "
                        f"{info.qual}: every coroutine needing the lock "
                        f"stalls until this resumes",
                        getattr(node, "end_lineno", None)))
                value = node.value
                if isinstance(value, ast.Call):
                    # the awaited call itself yields; its arguments may
                    # still hide blocking calls
                    self._scan(list(ast.iter_child_nodes(value)), held,
                               module, cls, locks, fn_names, info)
                else:
                    self._scan(value, held, module, cls, locks, fn_names,
                               info)
                continue
            if isinstance(node, ast.Call):
                if held:
                    reason = blocking_wait_reason(node)
                    if reason is not None:
                        info.findings.append(Finding(
                            RULE_BLOCKING, module.rel, node.lineno,
                            f"{reason} while holding lock {held[-1]!r} "
                            f"in {info.qual}",
                            getattr(node, "end_lineno", None)))
                callee = self._resolve_callee(node, cls, fn_names)
                if callee is not None:
                    info.calls.append((callee, tuple(held), node.lineno))
                self._scan(list(ast.iter_child_nodes(node)), held, module,
                           cls, locks, fn_names, info)
                continue
            self._scan(list(ast.iter_child_nodes(node)), held, module, cls,
                       locks, fn_names, info)

    # -- inter-procedural graph + cycles ----------------------------------

    def _cycle_findings(self, module: Module, locks,
                        infos: Dict[str, _FnInfo]) -> Iterator[Finding]:
        # transitive acquires fixpoint over same-module calls
        acq = {q: set(i.acquires) for q, i in infos.items()}
        changed = True
        while changed:
            changed = False
            for q, info in infos.items():
                for callee, _, _ in info.calls:
                    extra = acq.get(callee, ())
                    if not set(extra) <= acq[q]:
                        acq[q] |= set(extra)
                        changed = True
        # edge set: direct nesting + held-at-call -> callee's acquires
        edges: Dict[Tuple[str, str], str] = {}
        kinds = {(f"{c}.{a}" if c else a): k for (c, a), k in locks.items()}
        for q, info in infos.items():
            for a, b, lineno in info.edges:
                edges.setdefault((a, b), f"{module.rel}:{lineno} ({q})")
            for callee, held, lineno in info.calls:
                for b in acq.get(callee, ()):
                    for a in held:
                        edges.setdefault(
                            (a, b),
                            f"{module.rel}:{lineno} ({q} -> {callee})")
        lines = {}
        for (a, b), site in edges.items():
            lines[(a, b)] = int(site.split(":")[1].split(" ")[0])
        yield from self._emit_cycles(module, edges, lines, kinds)

    def _emit_cycles(self, module: Module, edges, lines,
                     kinds) -> Iterator[Finding]:
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        reported: Set[frozenset] = set()
        for (a, b) in sorted(edges):
            if a == b:
                if kinds.get(a) == "reentrant":
                    continue
                key = frozenset((a,))
                if key in reported:
                    continue
                reported.add(key)
                yield Finding(
                    RULE_CYCLE, module.rel, lines[(a, b)],
                    f"lock {a!r} re-acquired while already held "
                    f"(self-deadlock for a non-reentrant lock) at "
                    f"{edges[(a, b)]}")
                continue
            path = self._find_path(adj, b, a)
            if path is None:
                continue
            cycle = [a] + path  # a, b, ..., a — already closed
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            hops = []
            for x, y in zip(cycle, cycle[1:]):
                hops.append(f"{x} -> {y} at {edges.get((x, y), '?')}")
            yield Finding(
                RULE_CYCLE, module.rel, lines[(a, b)],
                "lock acquisition-order cycle (deadlock under "
                "concurrency): " + "; ".join(hops))

    @staticmethod
    def _find_path(adj, src: str, dst: str) -> Optional[List[str]]:
        """BFS path src..dst (inclusive) or None."""
        parents: Dict[str, Optional[str]] = {src: None}
        queue = [src]
        while queue:
            node = queue.pop(0)
            if node == dst:
                path = []
                cur: Optional[str] = node
                while cur is not None:
                    path.append(cur)
                    cur = parents[cur]
                return list(reversed(path))
            for nxt in adj.get(node, ()):
                if nxt not in parents:
                    parents[nxt] = node
                    queue.append(nxt)
        return None


def default_passes() -> Sequence[LintPass]:
    """The concurrency pass set ``tools/check_concurrency.py`` runs."""
    from cassmantle_tpu.analysis.asyncblock import AsyncBlockingPass
    from cassmantle_tpu.analysis.hostsync import HostSyncPass

    return (LockOrderPass(), AsyncBlockingPass.for_repo(),
            HostSyncPass.for_repo())
