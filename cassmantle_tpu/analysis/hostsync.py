"""Host-sync pass: stray device→host synchronization in the hot path.

Two contexts, one rule (``host-sync``):

1. **Inside jit regions** — functions compiled by ``jax.jit`` (directly
   decorated, passed to ``jax.jit(...)`` / ``partial(jax.jit, ...)`` /
   ``dp_sharded_sampler(...)``, or reachable from one through
   same-module calls). ``float()``/``int()`` on arrays, ``.item()``,
   ``np.asarray``, ``jax.device_get`` and ``block_until_ready`` there
   are at best silent constant-folds and at worst trace errors.

2. **Inside loops of host-side serving/ops code** — the serialization
   hazard the DDIM/decode paths live or die by: one sync per loop
   iteration (``int(gen_len[i])`` per row, ``np.asarray(x)`` per chunk)
   turns a single batched device round-trip into N sequential ones.
   Syncs *outside* loops are the normal "collect the result once"
   boundary and stay unflagged.

``float()``/``int()`` are only flagged on bare-name / subscript
arguments (``float(x)``, ``int(lens[i])``) — attribute chains and call
results (``float(self.cfg...)``, ``int(os.environ.get(...))``) are
config/host reads, not array syncs.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
)
from cassmantle_tpu.analysis.jitregions import (
    function_table,
    jit_closure,
)

RULE = "host-sync"

# the serving pipelines + device ops — where a stray sync serializes
# the DDIM loop (engine/server host code syncs at will)
REPO_DIRS = ("cassmantle_tpu/ops/", "cassmantle_tpu/serving/")

_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)


def _sync_reason(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last == "item" and not node.args:
        return ".item() forces a device->host sync"
    if name in ("np.asarray", "numpy.asarray", "np.array", "numpy.array"):
        return f"{name}() on a device value forces a device->host sync"
    if last == "device_get":
        return "device_get() forces a device->host sync"
    if last == "block_until_ready":
        return "block_until_ready() waits on in-flight device work"
    if name in ("float", "int") and len(node.args) == 1 \
            and not node.keywords \
            and isinstance(node.args[0], (ast.Name, ast.Subscript)):
        return f"{name}() on an array value forces a device->host sync"
    return None


class HostSyncPass(LintPass):
    name = "host-sync"
    description = ("device->host syncs inside jit regions and inside "
                   "loops of serving/ops hot paths")

    def __init__(self, dirs: Optional[Sequence[str]] = None) -> None:
        self.dirs = tuple(dirs) if dirs else None

    @classmethod
    def for_repo(cls) -> "HostSyncPass":
        return cls(dirs=REPO_DIRS)

    def run(self, module: Module) -> Iterator[Finding]:
        if self.dirs and not any(module.rel.startswith(d)
                                 for d in self.dirs):
            return
        fns = function_table(module.tree)
        jit_fns = jit_closure(module.tree, fns)
        seen: Set[int] = set()
        for qual, fn in fns.items():
            if id(fn) in seen:  # bare-name alias of a method entry
                continue
            seen.add(id(fn))
            if fn in jit_fns:
                yield from self._scan(fn, module,
                                      f"inside jit-compiled {qual!r}",
                                      loops_only=False)
            else:
                yield from self._scan(fn, module,
                                      f"inside a loop in {qual!r} (one "
                                      f"sync per iteration serializes "
                                      f"the device pipeline — hoist it "
                                      f"out of the loop)",
                                      loops_only=True)

    # jit-region discovery lives in analysis/jitregions.py (shared with
    # the recompile/tracer-leak passes).

    # -- scanning ----------------------------------------------------------

    def _scan(self, fn: ast.AST, module: Module, context: str,
              loops_only: bool) -> Iterator[Finding]:
        findings: List[Finding] = []

        def scan(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                if loops_only:
                    return  # nested defs get their own host-side scan
                # inside a jit region, nested closures run traced
            if isinstance(node, _LOOPS):
                in_loop = True
            if isinstance(node, ast.Call):
                reason = _sync_reason(node)
                if reason is not None and (in_loop or not loops_only):
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"{reason} {context}",
                        getattr(node, "end_lineno", None)))
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        for stmt in fn.body:
            scan(stmt, in_loop=False)
        yield from findings
