"""Env-flag registry pass: every ``CASSMANTLE_*`` read documented.

The same contract the metric-name pass enforces against the
``docs/OBSERVABILITY.md`` catalog, applied to operator kill switches:
every ``CASSMANTLE_*`` environment variable the package reads must
have a row in the docs/DEPLOY.md §6 lever table, and every row there
must correspond to a real read — an undocumented flag is a lever the
operator cannot find at 3 a.m., and a stale row is a lever that
silently does nothing. Rule ``env-flag``, both directions:

- per module: ``os.environ.get("CASSMANTLE_X")`` / ``os.getenv`` /
  ``os.environ["CASSMANTLE_X"]`` reads whose flag has no §6 row;
- finalize(): §6 rows whose flag is never read anywhere in the walked
  module set (anchored at the docs line).
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cassmantle_tpu.analysis.core import (
    REPO,
    Finding,
    LintPass,
    Module,
    call_name,
)

RULE = "env-flag"

REGISTRY_DOC = REPO / "docs" / "DEPLOY.md"
_SECTION = "## 6."
_FLAG = re.compile(r"CASSMANTLE_[A-Z0-9_]+")


def load_registry(doc: pathlib.Path = REGISTRY_DOC
                  ) -> Dict[str, int]:
    """flag -> line number for every ``CASSMANTLE_*`` token in the §6
    lever table of docs/DEPLOY.md."""
    if not doc.exists():
        return {}
    registry: Dict[str, int] = {}
    in_section = False
    for lineno, line in enumerate(doc.read_text().splitlines(), 1):
        if line.startswith("## "):
            in_section = line.startswith(_SECTION)
            continue
        if in_section:
            for flag in _FLAG.findall(line):
                registry.setdefault(flag, lineno)
    return registry


def _flag_const(expr: ast.expr, consts: Dict[str, str]) -> Optional[str]:
    """A CASSMANTLE_* flag name from a string literal or a module-level
    constant name (``_PROBE_ENV = "CASSMANTLE_..."``)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and expr.value.startswith("CASSMANTLE_"):
        return expr.value
    if isinstance(expr, ast.Name):
        return consts.get(expr.id)
    return None


def _module_consts(tree: ast.Module) -> Dict[str, str]:
    consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str) and \
                node.value.value.startswith("CASSMANTLE_"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = node.value.value
    return consts


def _env_read(node: ast.Call, consts: Dict[str, str]) -> Optional[str]:
    """The flag name of an env read call, or None. Besides
    ``os.environ.get``/``os.getenv``, any helper whose name mentions
    ``env`` taking the flag as its first argument counts (the repo's
    ``_block_env(...)`` pattern)."""
    name = call_name(node)
    if name is None or not node.args:
        return None
    last = name.rsplit(".", 1)[-1].lower()
    if not (name.endswith("environ.get") or "env" in last):
        return None
    return _flag_const(node.args[0], consts)


def extract_reads(tree: ast.Module) -> List[Tuple[str, int]]:
    """(flag, lineno) for every CASSMANTLE_* env read in a module:
    ``os.environ.get(...)``, ``os.getenv(...)``, ``os.environ[...]``
    subscripts, and ``*env*``-named helpers taking the flag literally —
    with flag names resolvable through module-level string constants."""
    consts = _module_consts(tree)
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            flag = _env_read(node, consts)
            if flag is not None:
                reads.append((flag, node.lineno))
        elif isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Attribute) and \
                    base.attr == "environ" and \
                    isinstance(node.ctx, ast.Load):
                # Load only: a write (os.environ[FLAG] = ...) exports
                # state and must not satisfy the registry's "some code
                # actually reads this lever" direction
                flag = _flag_const(node.slice, consts)
                if flag is not None:
                    reads.append((flag, node.lineno))
    return reads


class EnvFlagPass(LintPass):
    name = "envflags"
    description = ("CASSMANTLE_* env reads documented in the "
                   "docs/DEPLOY.md §6 lever table, and vice versa")

    def __init__(self, registry: Optional[Dict[str, int]] = None,
                 check_orphans: bool = True) -> None:
        self._registry = registry
        self._check_orphans = check_orphans
        self._seen: Set[str] = set()
        self._warned_empty = False

    @property
    def registry(self) -> Dict[str, int]:
        if self._registry is None:
            self._registry = load_registry()
        return self._registry

    def run(self, module: Module) -> Iterator[Finding]:
        registry = self.registry
        if not registry and not self._warned_empty:
            self._warned_empty = True
            yield Finding(RULE, str(REGISTRY_DOC), 1,
                          "env-flag registry (§6 lever table) missing "
                          "or empty")
        for flag, lineno in extract_reads(module.tree):
            self._seen.add(flag)
            if registry and flag not in registry:
                yield Finding(
                    RULE, module.rel, lineno,
                    f"{flag} is read here but has no row in the "
                    f"docs/DEPLOY.md §6 lever table — document the "
                    f"switch")

    def finalize(self) -> Iterator[Finding]:
        if not self._check_orphans:
            return
        for flag, lineno in sorted(self.registry.items()):
            if flag not in self._seen:
                yield Finding(
                    RULE, "docs/DEPLOY.md", lineno,
                    f"{flag} has a §6 lever-table row but is never "
                    f"read in the package — stale switch (remove the "
                    f"row or wire the flag)")
