"""Host-buffer-escape pass: mutable numpy mirrors aliased into async
device dispatch.

The PR 6 silently-wrong-images bug, generalized: ``jnp.asarray`` (and
``device_put``) may ZERO-COPY alias a numpy buffer on some backends,
and dispatch is asynchronous — so a host mirror that is (a) mutated in
place by its owning class and (b) handed without ``.copy()`` into an
async dispatch sink (``jnp.asarray``/``jax.device_put``, an executor /
``BatchingQueue`` ``submit``, a queue ``put``) can be rewritten by the
next tick *while the in-flight computation is still reading it* —
wrong schedule coefficients, silently wrong images; only e2e parity
tests catch it. Rule ``buffer-escape`` flags the triple:

1. the attribute is a numpy-allocated mirror
   (``self.X = np.zeros/ones/empty/full/array/arange(...)``);
2. the class mutates it in place somewhere (``self.X[...] = ...``,
   ``self.X += ...``, ``self.X.fill(...)``);
3. ``self.X`` is passed *directly* (no ``.copy()``) into a dispatch
   sink.

A ``.copy()`` at the sink (the shipped `_steps` fix) breaks the alias
and is clean; host→host reads (``np.flatnonzero(self.X)``) are not
sinks.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    self_attr,
)

RULE = "buffer-escape"

_NP_ALLOCATORS = {"zeros", "ones", "empty", "full", "array", "arange",
                  "zeros_like", "ones_like", "empty_like", "full_like"}
_NP_ROOTS = {"np", "numpy"}

# async dispatch sinks: device placement (may zero-copy alias the host
# buffer while dispatch is in flight) and cross-thread handoffs
# (executor/queue submit — the receiving thread reads the buffer later)
_SINK_NAMES = {"jnp.asarray", "jnp.array", "jax.device_put",
               "device_put", "jax.numpy.asarray", "jax.numpy.array"}
_SINK_METHODS = {"submit", "put", "put_nowait"}


_is_self_attr = self_attr  # shared AST helper (analysis/core.py)


def _np_allocation(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = call_name(value)
    if name is None or "." not in name:
        return False
    root, last = name.split(".", 1)[0], name.rsplit(".", 1)[-1]
    return root in _NP_ROOTS and last in _NP_ALLOCATORS


def _sink_call(node: ast.Call) -> Optional[str]:
    """A description of why this call is an async dispatch sink, or
    None."""
    name = call_name(node)
    if name in _SINK_NAMES:
        return f"{name}() (device placement may zero-copy alias it)"
    if name is not None:
        last = name.rsplit(".", 1)[-1]
        if last in _SINK_METHODS and "." in name:
            return (f"{name}() (cross-thread handoff reads it after "
                    f"this method returns)")
    return None


class BufferEscapePass(LintPass):
    name = "bufferescape"
    description = ("mutable numpy host mirrors passed uncopied into "
                   "async dispatch / device placement")

    def run(self, module: Module) -> Iterator[Finding]:
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._scan_class(module, node)

    def _scan_class(self, module: Module,
                    cls: ast.ClassDef) -> Iterator[Finding]:
        mirrors: Set[str] = set()
        mutated: Dict[str, int] = {}
        for node in ast.walk(cls):
            # (1) numpy-allocated mirror attributes
            if isinstance(node, ast.Assign) and \
                    _np_allocation(node.value):
                for t in node.targets:
                    attr = _is_self_attr(t)
                    if attr is not None:
                        mirrors.add(attr)
            # (2) in-place mutation
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        attr = _is_self_attr(t.value)
                    elif isinstance(node, ast.AugAssign):
                        attr = _is_self_attr(t)
                    else:
                        attr = None
                    if attr is not None:
                        mutated.setdefault(attr, node.lineno)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("fill", "sort", "partition"):
                attr = _is_self_attr(node.func.value)
                if attr is not None:
                    mutated.setdefault(attr, node.lineno)
        hot = mirrors & set(mutated)
        if not hot:
            return
        # (3) the uncopied escape into a sink
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            sink = _sink_call(node)
            if sink is None:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                attr = _is_self_attr(arg)
                if attr in hot:
                    yield Finding(
                        RULE, module.rel, arg.lineno,
                        f"mutable host mirror self.{attr} (mutated in "
                        f"place at line {mutated[attr]}) passed "
                        f"uncopied into {sink}: an in-flight dispatch "
                        f"can read the NEXT mutation's values — pass "
                        f"self.{attr}.copy()",
                        getattr(arg, "end_lineno", None))
