"""Metric-name pass: convention + docs-catalog coverage.

The ``tools/check_metrics.py`` lint (PR 3), ported onto the shared
``analysis`` framework — same rules, same CLI, the bespoke file-walking
/ reporting code replaced by :mod:`cassmantle_tpu.analysis.core`.

Walks every module for literal ``metrics.inc/gauge/observe/timer``
names (plain strings and f-strings — interpolated segments become
wildcards) plus ``block_timer(...)`` stage names, and checks:

1. **Convention** — dotted lowercase ``subsystem.metric`` names, at
   least two segments, each ``[a-z0-9_]`` (or a dynamic wildcard);
   histogram names (``observe``/``timer``/``block_timer``) end ``_s``
   (seconds) or ``_size``.
2. **Catalog coverage** — every name matches an entry in the metric
   catalog in ``docs/OBSERVABILITY.md`` (entries use ``<x>``
   placeholders for dynamic segments), so a new metric cannot ship
   without operator documentation. Drift fails tier-1
   (``tests/test_check_metrics.py``).
3. **Type agreement** (ISSUE 9) — the call kind at the emission site
   must match the catalog row's declared type column: ``inc`` is a
   counter, ``gauge`` a gauge, ``observe``/``timer``/``block_timer`` a
   histogram. A site that drifts (a counter quietly becoming a gauge,
   an ``observe`` on a cataloged counter) changes the Prometheus
   exposition shape (``_total`` vs ``_bucket``) and silently breaks
   every recording rule built on it — now a lint error instead of a
   dashboard surprise. Catalog entries whose row has no recognizable
   type column (prose mentions) don't constrain.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, Iterator, List, Optional, Tuple

from cassmantle_tpu.analysis.core import (
    PACKAGE,
    REPO,
    Finding,
    LintPass,
    Module,
    iter_modules,
    run_passes,
)

CATALOG_DOC = REPO / "docs" / "OBSERVABILITY.md"

RULE = "metric-name"

_METHODS = {"inc", "gauge", "observe", "timer"}
_SEGMENT = re.compile(r"^[a-z0-9_*]+$")
_CATALOG_NAME = re.compile(r"`([a-z0-9_.<>*]+\.[a-z0-9_.<>*]+)`")


def _literal_name(node: ast.expr) -> Optional[str]:
    """The metric name as a pattern: f-string holes become ``*``.
    None = not a literal (dynamic whole-name pass-through like
    profiling.block_timer's ``name`` arg — its callers are linted)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _is_registry_receiver(expr: ast.expr) -> bool:
    """Does this call receiver look like a Metrics registry? The plain
    ``metrics`` global, any ``*metrics*``/``*registry*``-named variable
    or attribute (``self._registry``, an injected ``registry=``) — so
    modules that take the registry by injection (obs/slo.py,
    obs/process.py) lint like direct emitters instead of escaping the
    catalog."""
    if isinstance(expr, ast.Name):
        tail = expr.id
    elif isinstance(expr, ast.Attribute):
        tail = expr.attr
    else:
        return False
    tail = tail.lower()
    return "metrics" in tail or "registry" in tail


def extract_sites(source: str, path: str) -> List[Tuple[str, str, int]]:
    """(name_pattern, method, lineno) for every literal metrics call —
    ``<registry>.inc/gauge/observe/timer(...)`` on any registry-shaped
    receiver (the ``metrics`` global, ``self._registry``, …) plus
    ``block_timer(...)`` (utils/profiling.py's metric-emitting stage
    timer, linted as an ``observe`` so device-stage names can't drift
    off the catalog)."""
    sites = []
    tree = ast.parse(source, filename=path)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS
                and _is_registry_receiver(node.func.value)):
            method = node.func.attr
        elif (isinstance(node.func, ast.Name)
                and node.func.id == "block_timer"):
            method = "observe"
        else:
            continue
        name = _literal_name(node.args[0])
        if name is not None:
            sites.append((name, method, node.lineno))
    return sites


_WILD = "\x00"


def _segments_match(code_seg: str, cat_seg: str) -> bool:
    """Mutual-wildcard segment match: ``*`` in code (an interpolated
    chunk) and ``<x>`` in the catalog both stand for any value. Both
    sides normalize their wildcard to one token, then each side's
    pattern is tried against the other's text."""
    code_norm = code_seg.replace("*", _WILD)
    cat_norm = re.sub(r"<[a-z0-9_]+>", _WILD, cat_seg)
    cat_re = re.escape(cat_norm).replace(_WILD, ".+")
    code_re = re.escape(code_norm).replace(_WILD, ".+")
    return bool(re.fullmatch(cat_re, code_norm)
                or re.fullmatch(code_re, cat_norm))


def _name_matches(code_name: str, cat_name: str) -> bool:
    code_segs = code_name.split(".")
    cat_segs = cat_name.split(".")
    if len(code_segs) != len(cat_segs):
        return False
    return all(_segments_match(c, k)
               for c, k in zip(code_segs, cat_segs))


def load_catalog() -> List[str]:
    if not CATALOG_DOC.exists():
        return []
    return sorted(set(_CATALOG_NAME.findall(CATALOG_DOC.read_text())))


_TYPES = ("counter", "gauge", "histogram")
# the method -> declared-type contract the type-agreement rule enforces
_TYPE_FOR_METHOD = {"inc": "counter", "gauge": "gauge",
                    "observe": "histogram", "timer": "histogram"}


def load_catalog_types() -> Dict[str, str]:
    """``{entry: declared_type}`` from the catalog's markdown tables:
    a row whose second cell is exactly counter/gauge/histogram types
    every backticked name in its first cell. Names appearing only in
    prose carry no type and don't constrain."""
    if not CATALOG_DOC.exists():
        return {}
    types: Dict[str, str] = {}
    for line in CATALOG_DOC.read_text().splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if len(cells) >= 2 and cells[1] in _TYPES:
            for name in _CATALOG_NAME.findall(cells[0]):
                types[name] = cells[1]
    return types


class MetricNamePass(LintPass):
    name = "metric-name"
    description = ("metric naming convention + docs/OBSERVABILITY.md "
                   "catalog coverage")

    def __init__(self, catalog: Optional[List[str]] = None,
                 catalog_types: Optional[Dict[str, str]] = None) -> None:
        self._catalog = catalog
        self._catalog_types = catalog_types
        self._warned_empty = False

    @property
    def catalog(self) -> List[str]:
        if self._catalog is None:
            self._catalog = load_catalog()
        return self._catalog

    @property
    def catalog_types(self) -> Dict[str, str]:
        if self._catalog_types is None:
            self._catalog_types = load_catalog_types()
        return self._catalog_types

    def run(self, module: Module) -> Iterator[Finding]:
        catalog = self.catalog
        if not catalog and not self._warned_empty:
            self._warned_empty = True
            yield Finding(RULE, str(CATALOG_DOC), 1,
                          "metric catalog missing or empty")
        for name, method, lineno in extract_sites(module.source,
                                                  module.rel):
            segs = name.split(".")
            if len(segs) < 2:
                yield Finding(
                    RULE, module.rel, lineno,
                    f"{name!r} needs >=2 dotted segments "
                    f"(subsystem.metric)")
                continue
            bad = [s for s in segs if not _SEGMENT.match(s)]
            if bad:
                yield Finding(
                    RULE, module.rel, lineno,
                    f"{name!r} has non-[a-z0-9_] segment(s) {bad}")
                continue
            if method in ("observe", "timer") and \
                    not (segs[-1].endswith("_s")
                         or segs[-1].endswith("_size")):
                yield Finding(
                    RULE, module.rel, lineno,
                    f"histogram {name!r} must end _s (seconds) or _size")
                continue
            if catalog:
                matched = [entry for entry in catalog
                           if _name_matches(name, entry)]
                if not matched:
                    yield Finding(
                        RULE, module.rel, lineno,
                        f"{name!r} not in the docs/OBSERVABILITY.md "
                        f"metric catalog")
                    continue
                # type agreement: the site's call kind must match the
                # declared type of at least one matching typed row —
                # a wildcard site matching several rows is fine as long
                # as one of them is the right kind
                expected = _TYPE_FOR_METHOD[method]
                declared = [self.catalog_types[e] for e in matched
                            if e in self.catalog_types]
                if declared and expected not in declared:
                    yield Finding(
                        RULE, module.rel, lineno,
                        f"{name!r} emitted as a {expected} "
                        f"(metrics.{method}) but cataloged as "
                        f"{'/'.join(sorted(set(declared)))} — type "
                        f"drift; fix the site or the catalog row")


def check(root: pathlib.Path = PACKAGE) -> List[str]:
    """All violations as human-readable strings; empty = clean."""
    return [str(f) for f in
            run_passes(iter_modules(root), [MetricNamePass()])]


def main(argv=None) -> int:
    from cassmantle_tpu.analysis.core import main_for

    return main_for([MetricNamePass()], argv, default_root=PACKAGE,
                    prog="check_metrics")
