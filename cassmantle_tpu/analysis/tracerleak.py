"""Tracer-leak pass: traced values escaping (or steering) a jit region.

Inside a jit trace every intermediate is a tracer. Two escape classes,
one rule (``tracer-leak``):

1. **Stores that outlive the trace** — assignments to ``self.*``,
   module globals (``global``/``nonlocal`` writes), or mutations of
   containers created *outside* the function (``outer.append(x)``,
   ``outer[k] = x`` on a non-local name). The stored tracer is dead
   the moment tracing finishes: later reads raise
   ``UnexpectedTracerError`` — or worse, silently hold the value of
   the FIRST trace forever (a stale-constant bug, the mirror of the
   recompile pass's capture hazard).

2. **Host control flow on traced values** — ``if``/``while`` whose
   test involves a traced parameter or a ``jnp.*`` result:
   ``TracerBoolConversionError`` at trace time. Caught statically so
   the author reaches for ``lax.cond``/``jnp.where`` before the trace
   explodes. Tests on statics, ``x is None`` guards, ``isinstance``,
   and shape/dtype/ndim reads are concrete at trace time and exempt.

Scope: functions in the module's jit closure (entries + same-module
transitive callees, via ``analysis/jitregions.py``). The traced-branch
check runs only on *entry* functions, where static/partial-bound
parameters are known — helpers routinely take host config scalars, and
flagging those would be noise.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    self_attr,
)
from cassmantle_tpu.analysis.jitregions import (
    function_table,
    jit_closure,
    jit_entries,
)

RULE = "tracer-leak"

_MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault",
             "appendleft"}


_is_self_attr = self_attr  # shared AST helper (analysis/core.py)


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound inside the function: params + assignment/loop/with
    targets + comprehension targets + nested def/lambda names."""
    names: Set[str] = set()
    args = fn.args
    for a in (args.args + args.kwonlyargs + args.posonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    declared: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            names.add(node.name)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            declared |= set(node.names)
    # subtract AFTER the walk: ast.walk is breadth-first, so a Store
    # nested under a later-visited Assign would re-add a name the
    # Global statement already excluded
    return names - declared


def _concrete_test(test: ast.expr, traced: Set[str]) -> bool:
    """True when a test is concrete at trace time even though it
    mentions a traced name: ``x is None`` guards, ``isinstance``,
    ``len()``/``.shape``/``.ndim``/``.dtype`` reads, or no traced name
    at all."""
    involved = {n.id for n in ast.walk(test)
                if isinstance(n, ast.Name)} & traced
    if not involved:
        return True
    # every traced-name occurrence must sit under a concrete extractor
    concrete_spans: List[ast.expr] = []
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
            concrete_spans.append(node)
        elif isinstance(node, ast.Call) and \
                call_name(node) in ("len", "isinstance", "getattr",
                                    "hasattr"):
            concrete_spans.append(node)
        elif isinstance(node, ast.Attribute) and \
                node.attr in ("shape", "ndim", "dtype", "size"):
            concrete_spans.append(node)

    def covered(name_node: ast.Name) -> bool:
        return any(name_node in ast.walk(span)
                   for span in concrete_spans)

    return all(covered(n) for n in ast.walk(test)
               if isinstance(n, ast.Name) and n.id in traced)


class TracerLeakPass(LintPass):
    name = "tracerleak"
    description = ("traced values stored outside jit regions; host "
                   "control flow on traced values")

    def run(self, module: Module) -> Iterator[Finding]:
        fns = function_table(module.tree)
        entries = jit_entries(module.tree, fns)
        closure = jit_closure(module.tree, fns, set(entries))
        seen: Set[int] = set()
        for fn in closure:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._scan_stores(module, fn)
            entry = entries.get(fn)
            if entry is not None:
                yield from self._scan_branches(module, fn,
                                               set(entry.traced_params))

    # -- (1) escaping stores ----------------------------------------------

    def _scan_stores(self, module: Module, fn: ast.AST
                     ) -> Iterator[Finding]:
        local = _local_names(fn)
        declared_nonlocal: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared_nonlocal |= set(node.names)
        # nested defs are NOT skipped: a closure built inside a jit
        # body (a scan body, a denoiser fn) runs traced too — the same
        # stance hostsync takes. Host-side callbacks nested in jit
        # code (jax.debug.callback targets) are rare enough to carry a
        # suppression with their reason.
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _is_self_attr(t)
                    if attr is not None:
                        yield Finding(
                            RULE, module.rel, node.lineno,
                            f"store to self.{attr} inside jit-traced "
                            f"{fn.name!r}: the tracer escapes the "
                            f"trace (UnexpectedTracerError on later "
                            f"use, or a stale first-trace constant) — "
                            f"return the value instead",
                            getattr(node, "end_lineno", None))
                    elif isinstance(t, ast.Name) and \
                            t.id in declared_nonlocal:
                        yield Finding(
                            RULE, module.rel, node.lineno,
                            f"store to global/nonlocal {t.id!r} inside "
                            f"jit-traced {fn.name!r}: the tracer "
                            f"escapes the trace — return the value "
                            f"instead",
                            getattr(node, "end_lineno", None))
                    elif isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id not in local:
                        yield Finding(
                            RULE, module.rel, node.lineno,
                            f"subscript store into outer container "
                            f"{t.value.id!r} inside jit-traced "
                            f"{fn.name!r}: the tracer escapes the "
                            f"trace — return the value instead",
                            getattr(node, "end_lineno", None))
            elif isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr in _MUTATORS and node.value.args:
                # only bare-statement calls: a used result
                # (``updates, s = opt.update(...)``) is a pure
                # functional API, not a container mutation
                node = node.value
                recv = node.func.value
                escapes = (_is_self_attr(recv) is not None
                           or (isinstance(recv, ast.Name)
                               and recv.id not in local))
                if escapes:
                    where = (f"self.{_is_self_attr(recv)}"
                             if _is_self_attr(recv) is not None
                             else recv.id)
                    yield Finding(
                        RULE, module.rel, node.lineno,
                        f".{node.func.attr}() into outer container "
                        f"{where!r} inside jit-traced {fn.name!r}: "
                        f"the tracer escapes the trace — return the "
                        f"value instead",
                        getattr(node, "end_lineno", None))

    # -- (2) host control flow on traced values ----------------------------

    def _scan_branches(self, module: Module, fn: ast.AST,
                       traced: Set[str]) -> Iterator[Finding]:
        for node in ast.walk(fn):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            if test is None:
                continue
            # a jnp.* ARRAY result in a test is traced regardless of
            # params; host-concrete jax APIs (jax.default_backend(),
            # jax.devices()) are fine, so only the numpy namespace —
            # the one producing arrays — trips this
            jnp_call = next(
                (n for n in ast.walk(test)
                 if isinstance(n, ast.Call)
                 and ((call_name(n) or "").startswith("jnp.")
                      or (call_name(n) or "").startswith("jax.numpy."))),
                None)
            if jnp_call is not None:
                yield Finding(
                    RULE, module.rel, test.lineno,
                    f"jnp/jax result used as a host "
                    f"{'if' if not isinstance(node, ast.While) else 'while'} "
                    f"condition inside jit-traced {fn.name!r}: "
                    f"TracerBoolConversionError at trace time — use "
                    f"lax.cond / jnp.where",
                    getattr(test, "end_lineno", None))
                continue
            if traced and not _concrete_test(test, traced):
                names = sorted({n.id for n in ast.walk(test)
                                if isinstance(n, ast.Name)
                                and n.id in traced})
                yield Finding(
                    RULE, module.rel, test.lineno,
                    f"traced parameter(s) {names} drive a host "
                    f"{'while' if isinstance(node, ast.While) else 'if'} "
                    f"inside jit-traced {fn.name!r}: "
                    f"TracerBoolConversionError at trace time — use "
                    f"lax.cond / jnp.where, or declare the arg static "
                    f"and bucket its values",
                    getattr(test, "end_lineno", None))
