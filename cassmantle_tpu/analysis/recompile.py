"""Recompile-hazard pass: jit sites that silently defeat the cache.

The serving stack's latency story depends on every jit site compiling
ONCE per bucket (spec-decode batch buckets, staged-denoise width
buckets, the per-strength img2img cache) — a hazard here doesn't crash,
it ships as a 100x latency cliff that only shows up under real traffic.
One rule (``recompile-hazard``), four statically-checkable shapes:

1. **jit built in a loop** — ``jax.jit(f)`` evaluated inside a
   ``for``/``while``/comprehension builds a fresh wrapper (and a fresh
   empty cache) every iteration: every call compiles. Hoist the jit.
2. **per-call / unhashable static arguments** — a call through a known
   jitted callable passing a list/dict/set literal in a static
   position (``TypeError: unhashable`` at dispatch) or an f-string
   (hashable but unique per call → one compile per call).
3. **mutable attribute captured at trace time** — a jitted function
   reads ``self.X`` where ``self.X`` is *reassigned* outside
   ``__init__``: the trace baked the old value in, so the mutation is
   silently ignored until an unrelated retrace picks it up —
   value-dependent behavior must enter as an argument. (Attributes
   assigned once, lazily, outside ``__init__`` are exempt: lazy init
   is a construction pattern, not mutation.)
4. **unbucketed shapes fed to a jit inside a loop** — calling a jitted
   function in a loop with a ``x[i:j]``-style slice whose bounds are
   loop data: every distinct length is a fresh compile. Pad to a
   bucket ladder like the serving paths do. Same hazard for ``len(x)``
   / ``x.shape[i]`` scalars passed as *traced* args that the callee
   branches on (``if``/``while``/``range``): that branch either fails
   to trace or forces the author to mark it static — one compile per
   distinct value.

A sibling rule (``quant-in-dispatch``, ISSUE 20) pins the
quantize-once-at-load contract of ops/quant.py: the weight-tree
quantizers (``quantize_tree_host`` / ``w8a8_tree_host`` /
``w8a8_tree`` / ``quantize_tree``) are LOAD-TIME transforms. Called
inside a loop they re-quantize the whole param tree per iteration — a
host-side bandwidth cliff that also defeats the donor/param caches
(every call materializes a fresh tree, so every dispatch sees new
buffer ids). Called inside a jit-traced closure the quantize is baked
into the traced graph and re-executes per dispatch, throwing away the
entire point of serving int8 trees. Both shapes are flagged; the fix
is always the same — quantize once in the loader transform
(serving/pipeline.py ``w8a8_unet_tools``) and pass the quantized tree
in.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cassmantle_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    self_attr,
)
from cassmantle_tpu.analysis.jitregions import (
    JIT_NAMES,
    JitAlias,
    JitEntry,
    function_table,
    jit_aliases,
    jit_closure,
    jit_entries,
)

RULE = "recompile-hazard"
QUANT_RULE = "quant-in-dispatch"

#: the ops/quant.py load-time tree transforms (quantize-once-at-load
#: contract — see module docstring). Matched by trailing call name, so
#: ``quant.w8a8_tree_host(...)`` and a bare imported name both hit.
QUANT_TREE_TRANSFORMS = frozenset({
    "quantize_tree", "quantize_tree_host",
    "w8a8_tree", "w8a8_tree_host",
})

_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


_is_self_attr = self_attr  # shared AST helper (analysis/core.py)


def _branched_params(fn: ast.AST) -> Set[str]:
    """Parameter names the function branches host control flow on:
    used (directly or in a comparison/boolop) as an ``if``/``while``
    test, or as an argument to ``range()``."""
    params = {a.arg for a in fn.args.args}
    hits: Set[str] = set()

    def names_in(expr: ast.expr) -> Set[str]:
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            hits |= names_in(node.test) & params
        elif isinstance(node, ast.Call) and call_name(node) == "range":
            for arg in node.args:
                hits |= names_in(arg) & params
    return hits


def _shape_derived(expr: ast.expr) -> Optional[str]:
    """'len(x)' / 'x.shape[0]'-style host scalars, described; else
    None."""
    if isinstance(expr, ast.Call) and call_name(expr) == "len":
        return "len(...)"
    node = expr
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr == "shape":
        return ".shape"
    return None


def _loose_slice(expr: ast.expr) -> bool:
    """A subscript slice whose LENGTH can vary per iteration — the
    per-iteration-shape hazard (``x[i:j]``, ``x[:n]``). A sliding
    window of constant width (``x[off:off + 128]``) has one shape and
    is exempt."""
    if not (isinstance(expr, ast.Subscript)
            and isinstance(expr.slice, ast.Slice)):
        return False
    lower, upper = expr.slice.lower, expr.slice.upper
    if all(b is None or isinstance(b, ast.Constant)
           for b in (lower, upper)):
        return False
    if isinstance(lower, ast.Name) and isinstance(upper, ast.BinOp) \
            and isinstance(upper.op, ast.Add):
        # off : off + CONST (either operand order) — constant width
        operands = (upper.left, upper.right)
        if any(isinstance(a, ast.Name) and a.id == lower.id
               for a in operands) and \
                any(isinstance(a, ast.Constant) for a in operands):
            return False
    return True


class RecompilePass(LintPass):
    name = "recompile"
    description = ("jit-cache hazards: jit built in loops, per-call/"
                   "unhashable statics, mutable attr capture, "
                   "unbucketed shapes")

    def run(self, module: Module) -> Iterator[Finding]:
        fns = function_table(module.tree)
        entries = jit_entries(module.tree, fns)
        aliases = jit_aliases(module.tree, fns, entries)
        mutated = self._mutated_attrs(module.tree)
        yield from self._scan_jit_in_loop(module)
        yield from self._scan_call_sites(module, fns, entries, aliases)
        yield from self._scan_captures(module, fns, entries, mutated)
        yield from self._scan_quant_in_dispatch(module, fns, entries)

    # -- (1) jit built inside a loop --------------------------------------

    def _scan_jit_in_loop(self, module: Module) -> Iterator[Finding]:
        findings: List[Finding] = []

        def scan(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, _LOOPS):
                in_loop = True
            if in_loop and isinstance(node, ast.Call) and \
                    call_name(node) in JIT_NAMES:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "jax.jit(...) evaluated inside a loop builds a "
                    "fresh wrapper (and empty cache) per iteration — "
                    "every call recompiles; hoist the jit out of the "
                    "loop", getattr(node, "end_lineno", None)))
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        scan(module.tree, in_loop=False)
        yield from findings

    # -- (2) + (4) call sites of known jitted callables -------------------

    def _static_positions(self, alias: JitAlias
                          ) -> Tuple[Set[int], Set[str]]:
        """(call-site static positions, static argnames): positions
        are in CALL-SITE terms — partial-bound leading params are gone
        from the callable's signature, so entry params map through
        ``alias.bound`` (alias.static_argnums already index the
        reduced signature). An alias whose own jit site declared
        statics trusts ONLY those: the entry may merge several jit
        sites of one function, and another alias's declarations must
        not reclassify this one's traced positions."""
        nums = set(alias.static_argnums)
        names = set(alias.static_argnames)
        if not alias.explicit and alias.entry is not None:
            for i, p in enumerate(alias.entry.params[alias.bound:]):
                if p in alias.entry.static_params:
                    nums.add(i)
                    names.add(p)
        return nums, names

    @staticmethod
    def _param_at(alias: JitAlias, i: int) -> Optional[str]:
        """The callee parameter a call-site positional ``i`` binds to,
        through the partial-bound offset."""
        if alias.entry is None:
            return None
        params = alias.entry.params
        j = alias.bound + i
        return params[j] if j < len(params) else None

    def _resolve_alias(self, node: ast.Call,
                       aliases: Dict[str, JitAlias]) -> Optional[JitAlias]:
        f = node.func
        if isinstance(f, ast.Name):
            return aliases.get(f.id)
        attr = _is_self_attr(f)
        if attr is not None:
            return aliases.get(attr)
        return None

    def _scan_call_sites(self, module: Module, fns, entries,
                         aliases: Dict[str, JitAlias]
                         ) -> Iterator[Finding]:
        findings: List[Finding] = []

        def check_call(node: ast.Call, in_loop: bool) -> None:
            alias = self._resolve_alias(node, aliases)
            if alias is None:
                return
            static_nums, static_names = self._static_positions(alias)
            entry = alias.entry
            branched = (_branched_params(entry.fn)
                        if entry is not None else set())
            for i, arg in enumerate(node.args):
                param = self._param_at(alias, i)
                is_static = i in static_nums or (
                    param is not None and param in static_names)
                if is_static:
                    if isinstance(arg, _UNHASHABLE):
                        findings.append(Finding(
                            RULE, module.rel, arg.lineno,
                            f"unhashable literal in static position "
                            f"{i} of jitted {alias.key!r}: TypeError "
                            f"at dispatch (statics key the jit cache "
                            f"by hash)",
                            getattr(arg, "end_lineno", None)))
                    elif isinstance(arg, ast.JoinedStr):
                        findings.append(Finding(
                            RULE, module.rel, arg.lineno,
                            f"f-string in static position {i} of "
                            f"jitted {alias.key!r}: a per-call string "
                            f"keys a fresh cache entry — one compile "
                            f"per call",
                            getattr(arg, "end_lineno", None)))
                    continue
                # traced positions
                if in_loop and _loose_slice(arg):
                    findings.append(Finding(
                        RULE, module.rel, arg.lineno,
                        f"unbucketed slice passed to jitted "
                        f"{alias.key!r} inside a loop: every distinct "
                        f"length is a fresh compile — pad to a bucket "
                        f"ladder", getattr(arg, "end_lineno", None)))
                desc = _shape_derived(arg)
                if desc is not None and param is not None \
                        and param in branched:
                    findings.append(Finding(
                        RULE, module.rel, arg.lineno,
                        f"host scalar ({desc}) passed as traced arg "
                        f"{param!r} of jitted {alias.key!r}, "
                        f"which branches on it: the branch cannot "
                        f"trace — and marking it static recompiles "
                        f"per distinct value; bucket it or use "
                        f"lax.cond/fori_loop",
                        getattr(arg, "end_lineno", None)))
            for kw in node.keywords:
                if kw.arg in static_names and \
                        isinstance(kw.value, _UNHASHABLE):
                    findings.append(Finding(
                        RULE, module.rel, kw.value.lineno,
                        f"unhashable literal for static argname "
                        f"{kw.arg!r} of jitted {alias.key!r}: "
                        f"TypeError at dispatch",
                        getattr(kw.value, "end_lineno", None)))
                elif kw.arg in static_names and \
                        isinstance(kw.value, ast.JoinedStr):
                    findings.append(Finding(
                        RULE, module.rel, kw.value.lineno,
                        f"f-string for static argname {kw.arg!r} of "
                        f"jitted {alias.key!r}: one compile per call",
                        getattr(kw.value, "end_lineno", None)))

        def scan(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, _LOOPS):
                in_loop = True
            if isinstance(node, ast.Call):
                check_call(node, in_loop)
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        scan(module.tree, in_loop=False)
        yield from findings

    # -- quant-in-dispatch: load-time quantizers re-run per call ----------

    @staticmethod
    def _quant_transform(node: ast.Call) -> Optional[str]:
        """Trailing name of an ops/quant.py tree-transform call
        (``quant.w8a8_tree_host(...)`` or the bare imported name);
        None otherwise."""
        name = call_name(node)
        if name is None:
            return None
        leaf = name.rsplit(".", 1)[-1]
        return leaf if leaf in QUANT_TREE_TRANSFORMS else None

    def _scan_quant_in_dispatch(self, module: Module, fns,
                                entries: Dict[ast.AST, JitEntry]
                                ) -> Iterator[Finding]:
        findings: List[Finding] = []
        seen: Set[int] = set()

        def report(node: ast.Call, leaf: str, why: str) -> None:
            if node.lineno in seen:
                return
            seen.add(node.lineno)
            findings.append(Finding(
                QUANT_RULE, module.rel, node.lineno,
                f"{leaf}(...) {why} — the ops/quant.py tree "
                f"transforms are quantize-once-at-LOAD; quantize in "
                f"the loader transform and pass the quantized tree in",
                getattr(node, "end_lineno", None)))

        def scan(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, _LOOPS):
                in_loop = True
            if in_loop and isinstance(node, ast.Call):
                leaf = self._quant_transform(node)
                if leaf is not None:
                    report(node, leaf,
                           "inside a loop re-quantizes the whole "
                           "param tree per iteration (a host "
                           "bandwidth cliff that also hands every "
                           "dispatch fresh buffer ids)")
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        scan(module.tree, in_loop=False)
        for fn in jit_closure(module.tree, fns, set(entries)):
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    leaf = self._quant_transform(n)
                    if leaf is not None:
                        report(n, leaf,
                               f"inside jit-traced {fn.name!r} bakes "
                               f"a per-dispatch requantize into the "
                               f"compiled graph")
        yield from findings

    # -- (3) mutable attribute capture ------------------------------------

    @staticmethod
    def _mutated_attrs(tree: ast.Module) -> Dict[str, Set[str]]:
        """class -> ``self.X`` attrs that are genuinely *mutated*:
        AugAssigned anywhere, or plain-assigned outside ``__init__``
        when ``__init__`` also assigns them (reassignment of
        constructed state), or assigned across SEVERAL non-init
        methods. One-shot lazy assignment outside __init__ — even a
        branchy one inside a single ``_ensure``-style method — is
        construction, not mutation."""
        out: Dict[str, Set[str]] = {}
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            init_assigned: Set[str] = set()
            later_methods: Dict[str, Set[str]] = {}
            aug: Set[str] = set()
            for sub in node.body:
                if not isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    continue
                for n in ast.walk(sub):
                    targets: List[ast.expr] = []
                    if isinstance(n, ast.Assign):
                        targets = n.targets
                    elif isinstance(n, ast.AugAssign):
                        attr = _is_self_attr(n.target)
                        if attr is not None:
                            aug.add(attr)
                        continue
                    for t in targets:
                        attr = _is_self_attr(t)
                        if attr is None:
                            continue
                        if sub.name == "__init__":
                            init_assigned.add(attr)
                        else:
                            later_methods.setdefault(
                                attr, set()).add(sub.name)
            mutated = aug | {a for a, ms in later_methods.items()
                             if a in init_assigned or len(ms) > 1}
            if mutated:
                out[node.name] = mutated
        return out

    def _scan_captures(self, module: Module, fns,
                       entries: Dict[ast.AST, JitEntry],
                       mutated: Dict[str, Set[str]]) -> Iterator[Finding]:
        if not mutated:
            return
        # map each method node to its class
        cls_of: Dict[ast.AST, str] = {}
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        cls_of[sub] = node.name
        closure = jit_closure(module.tree, fns, set(entries))
        for fn in closure:
            cls = cls_of.get(fn)
            if cls is None or cls not in mutated:
                continue
            reported: Set[str] = set()
            for n in ast.walk(fn):
                if not isinstance(n, ast.Attribute) or \
                        not isinstance(n.ctx, ast.Load):
                    continue
                attr = _is_self_attr(n)
                if attr in mutated[cls] and attr not in reported:
                    reported.add(attr)
                    yield Finding(
                        RULE, module.rel, n.lineno,
                        f"jit-traced {fn.name!r} captures mutable "
                        f"attribute self.{attr} (reassigned elsewhere "
                        f"in {cls}): the trace bakes the value at "
                        f"compile time, so mutations are silently "
                        f"stale — pass it as an argument",
                        getattr(n, "end_lineno", None))
