"""Mask-selection agreement with the reference algorithm.

The reference selects mask words by NLTK POS filter + word2vec distance
from the candidate mean (reference src/utils.py:74-104). This module
replays that algorithm — the tag filter, the TF-IDF weight that is
identically 1 on a single sentence, and ``words.index`` first-occurrence
index lookup — over a hand-annotated gold corpus (data/pos_gold.txt,
NLTK-convention Penn tags), and compares against this framework's
selection (engine/masking.select_masks with the vendored POS
classifier). Two reference quirks are NOT modeled because they are
vacuous under the dense embedders used here (hash or MiniLM embed every
string): word2vec's distance-0 for out-of-model words and its
mean-over-in-vocab-only; a word2vec-backed run would need an in-vocab
predicate threaded through ``embed``.

Two numbers come out:

- ``tag_accuracy``: per-token agreement of engine/pos.is_maskable with
  the gold tags' maskability (the {JJ*, RB*, NN, NNS} test);
- ``mask_agreement``: fraction of prompts whose selected mask sets
  match the reference algorithm's exactly (plus mean Jaccard).

Both are recorded in PARITY.md; the VERDICT round-3 bar is >=80%
selection agreement.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

# the reference's descriptive_tags, src/utils.py:87
DESCRIPTIVE_TAGS = frozenset(
    ["JJ", "RB", "NN", "NNS", "JJR", "JJS", "RBR", "RBS"]
)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
GOLD_PATH = os.path.join(_REPO, "data", "pos_gold.txt")


def load_gold(path: str = GOLD_PATH) -> List[List[Tuple[str, str]]]:
    """[[(token, tag), ...] per prompt]."""
    return [pairs for _, pairs in load_gold_sections(path)]


def load_gold_sections(
    path: str = GOLD_PATH,
) -> List[Tuple[str, List[Tuple[str, str]]]]:
    """[(section, [(token, tag), ...]) per prompt] — sections come from
    ``# section: NAME`` comment lines (docs/POS_ANNOTATION.md)."""
    prompts = []
    section = "unsectioned"
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("# section:"):
                section = line.split(":", 1)[1].strip()
                continue
            if not line or line.startswith("#"):
                continue
            pairs = []
            for item in line.split():
                word, _, tag = item.rpartition("/")
                assert word and tag, f"malformed gold item {item!r}"
                pairs.append((word, tag))
            prompts.append((section, pairs))
    return prompts


def reference_select(
    tagged: Sequence[Tuple[str, str]],
    embed: Callable[[Sequence[str]], np.ndarray],
    num_masked: int = 2,
) -> List[int]:
    """The reference's ``select_descriptive_words`` replayed over gold
    tags (src/utils.py:81-104): filter by tag + isalpha, score by L2
    distance from the filtered-set mean embedding (IDF factor == 1 on a
    one-sentence fit), take the top ``num_masked`` by ascending-argsort
    tail, map back through first-occurrence ``words.index``."""
    words = [w for w, _ in tagged]
    filtered = [w for w, tag in tagged
                if w.isalpha() and tag in DESCRIPTIVE_TAGS]
    if not filtered:
        return []
    vecs = np.asarray(embed([w.lower() for w in filtered]),
                      dtype=np.float32)
    mean = vecs.mean(axis=0, keepdims=True)
    distances = np.linalg.norm(vecs - mean, axis=1)
    # default (introsort) argsort, matching the reference's np.argsort
    # call — exact-tie ordering follows NumPy's unstable sort in both
    top = np.argsort(distances)[-num_masked:]
    return sorted({words.index(filtered[i]) for i in top})


def framework_select(
    tokens: Sequence[str],
    embed: Callable[[Sequence[str]], np.ndarray],
    num_masked: int = 2,
) -> List[int]:
    from cassmantle_tpu.engine.masking import select_masks

    return select_masks(tokens, embed, num_masked)


def tag_maskable(tag: str) -> bool:
    return tag in DESCRIPTIVE_TAGS


def surface_class(tok: str) -> str:
    """Audit bucket for a token, by SURFACE form only (derivable
    without the classifier, so the per-class error report can be
    checked against the corpus by hand). Buckets mirror the
    classifier's decision families (engine/pos.py)."""
    from cassmantle_tpu.engine.pos import (
        IRREGULAR_PAST,
        PARTICIPLE_ADJ,
        VERB_BASES,
    )

    low = tok.lower()
    if low in VERB_BASES:
        return "bare-verb-base"
    if low in IRREGULAR_PAST or low in PARTICIPLE_ADJ:
        return "irregular-past-or-participle"
    if low.endswith("ing"):
        return "ing-form"
    if low.endswith("ed"):
        return "ed-form"
    if low.endswith("ly"):
        return "ly-form"
    if low.endswith("s") and not low.endswith("ss"):
        return "s-form"
    return "other"


def evaluate(
    embed: Callable[[Sequence[str]], np.ndarray],
    num_masked: int = 2,
    path: str = GOLD_PATH,
) -> Dict[str, object]:
    from cassmantle_tpu.engine.pos import is_maskable
    from cassmantle_tpu.utils.text import is_wordlike

    gold = load_gold_sections(path)
    tag_hits = tag_total = 0
    exact = 0
    jaccards = []
    disagreements = []
    by_class: Dict[str, Dict[str, int]] = {}
    by_section: Dict[str, Dict[str, int]] = {}
    tag_errors = []
    for section, tagged in gold:
        tokens = [w for w, _ in tagged]
        sec = by_section.setdefault(
            section, {"prompts": 0, "tag_total": 0, "tag_errors": 0,
                      "mask_exact": 0})
        sec["prompts"] += 1
        for i, (tok, tag) in enumerate(tagged):
            if not (is_wordlike(tok) and tok.isalpha()):
                continue
            tag_total += 1
            sec["tag_total"] += 1
            cls = by_class.setdefault(surface_class(tok),
                                      {"total": 0, "errors": 0})
            cls["total"] += 1
            if is_maskable(tokens, i) == tag_maskable(tag):
                tag_hits += 1
            else:
                cls["errors"] += 1
                sec["tag_errors"] += 1
                tag_errors.append({
                    "token": tok, "gold_tag": tag,
                    "class": surface_class(tok), "section": section,
                    "context": " ".join(tokens[max(0, i - 3): i + 3]),
                })
        ref = set(reference_select(tagged, embed, num_masked))
        ours = set(framework_select(tokens, embed, num_masked))
        union = ref | ours
        jac = len(ref & ours) / len(union) if union else 1.0
        jaccards.append(jac)
        if ref == ours:
            exact += 1
            sec["mask_exact"] += 1
        else:
            disagreements.append({
                "text": " ".join(tokens),
                "section": section,
                "reference": sorted(ref),
                "framework": sorted(ours),
            })
    return {
        "prompts": len(gold),
        "tag_accuracy": round(tag_hits / max(1, tag_total), 4),
        "mask_agreement": round(exact / max(1, len(gold)), 4),
        "mean_jaccard": round(float(np.mean(jaccards)), 4),
        "by_section": {
            k: {
                "prompts": v["prompts"],
                "tag_accuracy": round(
                    1 - v["tag_errors"] / max(1, v["tag_total"]), 4),
                "mask_agreement": round(
                    v["mask_exact"] / max(1, v["prompts"]), 4),
            }
            for k, v in by_section.items()
        },
        "tag_errors_by_class": {
            k: {**v, "accuracy": round(1 - v["errors"] / v["total"], 4)}
            for k, v in sorted(by_class.items())
        },
        "tag_errors": tag_errors,
        "disagreements": disagreements,
    }


def main() -> None:
    """CLI: deterministic hash embedding by default (isolates the
    filter difference — both selectors rank with the same vectors);
    --minilm ranks with the real scorer embeddings instead."""
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--minilm", action="store_true",
                    help="rank with MiniLM embeddings (loads the model)")
    ap.add_argument("--num-masked", type=int, default=2)
    ap.add_argument("--verbose", action="store_true",
                    help="print per-prompt disagreements")
    args = ap.parse_args()

    if args.minilm:
        from cassmantle_tpu.config import FrameworkConfig
        from cassmantle_tpu.ops.scorer import EmbeddingScorer

        scorer = EmbeddingScorer(FrameworkConfig().models.minilm)
        embed = lambda words: scorer.embed(list(words))  # noqa: E731
    else:
        from cassmantle_tpu.engine.content import hash_embed

        embed = hash_embed

    report = evaluate(embed, num_masked=args.num_masked)
    if not args.verbose:
        report = {**report,
                  "disagreements": len(report["disagreements"]),
                  "tag_errors": len(report["tag_errors"])}
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
