"""CLIP-similarity parity harness (BASELINE.md quality gate).

Because RNG streams differ from any CUDA baseline, pixel-exact parity is
impossible; the meaningful check (SURVEY.md §7 hard part (a)) is that
generated images score comparably against their prompts under CLIP. This
harness computes image-text CLIP similarity fully on-device:

    sim = <normalize(vision(image))>, normalize(project(text(prompt)))>

With real CLIP weights in ``weights_dir`` this is the true metric; with
random init it still validates the plumbing end to end.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.config import ClipTextConfig
from cassmantle_tpu.models.clip_text import ClipTextEncoder
from cassmantle_tpu.models.clip_vision import (
    ClipVisionConfig,
    ClipVisionEncoder,
    preprocess_for_clip,
)
from cassmantle_tpu.models.weights import (
    convert_clip_text,
    convert_clip_text_projection,
    convert_clip_vision,
    convert_tensors,
    init_params,
    load_checkpoint_tensors,
)
from cassmantle_tpu.utils.tokenizers import load_tokenizer


class ClipSimilarityHarness:
    def __init__(
        self,
        text_cfg: Optional[ClipTextConfig] = None,
        vision_cfg: Optional[ClipVisionConfig] = None,
        weights_dir: Optional[str] = None,
        pad_len: int = 77,
    ) -> None:
        self.text_cfg = text_cfg or ClipTextConfig()
        self.vision_cfg = vision_cfg or ClipVisionConfig()
        self.pad_len = min(pad_len, self.text_cfg.max_positions)
        self.tokenizer = load_tokenizer(
            weights_dir, "clip", self.text_cfg.vocab_size
        )

        # ONE read of the full CLIPModel checkpoint feeds all three
        # stages (text tower, vision tower, text projection)
        tensors = load_checkpoint_tensors(
            weights_dir, "clip_text.safetensors", "clip_full")

        self.text = ClipTextEncoder(self.text_cfg)
        ids = jnp.zeros((1, self.pad_len), dtype=jnp.int32)
        loaded_text = convert_tensors(
            tensors,
            lambda t: convert_clip_text(t, self.text_cfg.num_layers),
            "clip_text")
        self.text_params = (
            loaded_text if loaded_text is not None
            else init_params(self.text, 11, ids)
        )

        # the vision tower and both projections live in the SAME full
        # CLIPModel checkpoint as the text tower (clip_text.safetensors =
        # openai/clip-vit-large-patch14 model.safetensors) — no separate
        # vision file to fetch
        self.vision = ClipVisionEncoder(self.vision_cfg)
        img = jnp.zeros(
            (1, self.vision_cfg.image_size, self.vision_cfg.image_size, 3)
        )
        loaded_vision = convert_tensors(
            tensors,
            lambda t: convert_clip_vision(t, self.vision_cfg.num_layers),
            "clip_vision")
        self.vision_params = (
            loaded_vision if loaded_vision is not None
            else init_params(self.vision, 12, img)
        )

        # text projection into the shared space
        proj = convert_tensors(tensors, convert_clip_text_projection,
                               "clip_text_projection")
        # a real parity number needs EVERY stage loaded, not just some —
        # a partial load (e.g. vision conversion KeyError falling back to
        # random init) must not masquerade as a quality measurement
        self.loaded_real_weights = (
            loaded_text is not None
            and loaded_vision is not None
            and proj is not None
        )
        if proj is None:
            proj = jax.random.normal(
                jax.random.PRNGKey(13),
                (self.text_cfg.hidden_size, self.vision_cfg.projection_dim),
            ) * 0.02
        self.text_projection = proj
        # params as jit args (device buffers), not captured constants
        self._params = {"text": self.text_params,
                        "vision": self.vision_params,
                        "proj": self.text_projection}
        self._jit_sim = jax.jit(self._sim_impl)
        self._jit_pair_sim = jax.jit(self._pair_sim_impl)

    def _tokenize(self, prompts: Sequence[str]) -> np.ndarray:
        out = np.full((len(prompts), self.pad_len),
                      self.tokenizer.pad_id, dtype=np.int32)
        for i, p in enumerate(prompts):
            toks = self.tokenizer.encode(p)[: self.pad_len - 1]
            toks = toks + [self.tokenizer.eos_id]
            out[i, : len(toks)] = (
                np.asarray(toks) % self.text_cfg.vocab_size
            )
        return out

    def _sim_impl(self, params, ids, images_u8):
        pooled = self.text.apply(params["text"], ids)["pooled"]
        temb = pooled.astype(jnp.float32) @ params["proj"]
        temb = temb / (jnp.linalg.norm(temb, axis=-1, keepdims=True) + 1e-8)
        pre = preprocess_for_clip(images_u8, self.vision_cfg.image_size)
        vemb = self.vision.apply(params["vision"], pre)
        return jnp.sum(temb * vemb, axis=-1)

    def similarity(self, images_u8: np.ndarray,
                   prompts: Sequence[str]) -> np.ndarray:
        """(B,H,W,3) uint8 + B prompts -> (B,) CLIP similarities."""
        ids = jnp.asarray(self._tokenize(prompts))
        return np.asarray(
            self._jit_sim(self._params, ids, jnp.asarray(images_u8))
        )

    def parity_report(self, images_u8, prompts,
                      baseline_mean: Optional[float] = None) -> dict:
        sims = self.similarity(images_u8, prompts)
        report = {
            "clip_sim_mean": float(np.mean(sims)),
            "clip_sim_std": float(np.std(sims)),
            "n": int(len(sims)),
            # False => plumbing-only run (random init): NOT a quality claim
            "real_weights": self.loaded_real_weights,
        }
        if baseline_mean is not None:
            report["baseline_mean"] = float(baseline_mean)
            report["parity_ratio"] = float(np.mean(sims) / baseline_mean)
        return report

    def _pair_sim_impl(self, params, images_a_u8, images_b_u8):
        def embed(imgs):
            pre = preprocess_for_clip(imgs, self.vision_cfg.image_size)
            return self.vision.apply(params["vision"], pre)

        return jnp.sum(embed(images_a_u8) * embed(images_b_u8), axis=-1)

    def image_similarity(self, images_a_u8: np.ndarray,
                         images_b_u8: np.ndarray) -> np.ndarray:
        """(B,) cosine similarities between the CLIP-vision embeddings
        of two image batches — the image↔image counterpart of
        :meth:`similarity`, jitted once like it (``_jit_pair_sim``).
        Identical batches score 1.0 exactly (both arms embed through
        the same compiled tower), which is what makes the stride-1
        exact-parity leg of the encprop gate a deterministic tier-1
        assertion even on random init."""
        return np.asarray(self._jit_pair_sim(
            self._params, jnp.asarray(images_a_u8),
            jnp.asarray(images_b_u8)))


# Image-quality floor for encoder-propagation serving (the approximation
# contract in PARITY.md): mean CLIP-vision similarity between the
# encprop arm's images and the full-forward arm's SAME-SEED images must
# stay above this. At stride 1 encprop IS the full forward (bit-exact,
# similarity 1.0 — pinned in tier-1); the default key schedule is gated
# against this floor whenever the harness runs with real weights
# (random-init runs report advisory only, like every QualityGateConfig
# gate).
ENCPROP_IMAGE_SIM_FLOOR = 0.95


# Image-quality floor for few-step consistency serving: mean
# CLIP-vision similarity between the 4-step student's images and the
# teacher's SAME-SEED full-schedule images must stay above this. Lower
# than the encprop floor — the student is a learned approximation of
# the whole trajectory, not a feature-reuse of it (LCM-class quality,
# the `lcm` row of QualityGateConfig). Enforced only on real-weights
# runs, advisory on random init, like every other gate.
CONSISTENCY_IMAGE_SIM_FLOOR = 0.90


def consistency_quality_report(
    harness: ClipSimilarityHarness,
    images_student: np.ndarray,
    images_teacher: np.ndarray,
    prompts: Sequence[str],
    floor: float = CONSISTENCY_IMAGE_SIM_FLOOR,
) -> dict:
    """The few-step quality gate (ISSUE 15): same-seed student (4-step
    consistency) vs teacher (full-schedule) outputs compared in
    CLIP-vision space, plus both arms' prompt CLIP-sim for the record —
    the encprop gate's structure applied to the distilled student.
    ``passes_floor`` is the gate verdict; ``gate_enforced`` says
    whether it is a real-weights measurement or plumbing-only."""
    pair = harness.image_similarity(images_student, images_teacher)
    return {
        "image_sim_mean": float(np.mean(pair)),
        "image_sim_min": float(np.min(pair)),
        "floor": float(floor),
        "passes_floor": bool(np.mean(pair) >= floor),
        "exact": bool(np.array_equal(images_student, images_teacher)),
        "clip_sim_student": float(
            np.mean(harness.similarity(images_student, prompts))),
        "clip_sim_teacher": float(
            np.mean(harness.similarity(images_teacher, prompts))),
        "n": int(images_teacher.shape[0]),
        "real_weights": harness.loaded_real_weights,
        "gate_enforced": harness.loaded_real_weights,
    }


# Image-quality floor for W8A8 quantized serving (ISSUE 20): mean
# CLIP-vision similarity between the int8-kernel arm's images and the
# fp arm's SAME-SEED images. Higher than the consistency floor —
# quantization is a numerics approximation of the SAME trajectory
# (per-channel weight scales + calibrated activation scales), not a
# learned shortcut; the `w8a8`/`sdxl_w8a8` rows of QualityGateConfig
# carry the per-pipeline bars. Enforced only on real-weights runs,
# advisory on random init, like every other gate.
W8A8_IMAGE_SIM_FLOOR = 0.98


def w8a8_quality_report(
    harness: ClipSimilarityHarness,
    images_w8a8: np.ndarray,
    images_fp: np.ndarray,
    prompts: Sequence[str],
    floor: float = W8A8_IMAGE_SIM_FLOOR,
) -> dict:
    """The W8A8 quality gate: same-seed quantized vs fp outputs
    compared in CLIP-vision space (the encprop gate's structure applied
    to the int8 kernel path). ``passes_floor`` is the gate verdict;
    ``gate_enforced`` says whether it is a real-weights measurement or
    plumbing-only."""
    pair = harness.image_similarity(images_w8a8, images_fp)
    return {
        "image_sim_mean": float(np.mean(pair)),
        "image_sim_min": float(np.min(pair)),
        "floor": float(floor),
        "passes_floor": bool(np.mean(pair) >= floor),
        "exact": bool(np.array_equal(images_w8a8, images_fp)),
        "clip_sim_w8a8": float(
            np.mean(harness.similarity(images_w8a8, prompts))),
        "clip_sim_fp": float(
            np.mean(harness.similarity(images_fp, prompts))),
        "n": int(images_fp.shape[0]),
        "real_weights": harness.loaded_real_weights,
        "gate_enforced": harness.loaded_real_weights,
    }


def encprop_quality_report(
    harness: ClipSimilarityHarness,
    images_encprop: np.ndarray,
    images_full: np.ndarray,
    prompts: Sequence[str],
    floor: float = ENCPROP_IMAGE_SIM_FLOOR,
) -> dict:
    """The encprop image-quality gate: same-seed encprop vs full-forward
    outputs compared in CLIP-vision space (robust, image↔image — no
    text-prompt noise term), plus both arms' prompt CLIP-sim for the
    record. ``passes_floor`` is the gate verdict; ``gate_enforced``
    says whether it is a real-weights measurement or plumbing-only
    (the enforcement convention of QualityGateConfig)."""
    pair = harness.image_similarity(images_encprop, images_full)
    report = {
        "image_sim_mean": float(np.mean(pair)),
        "image_sim_min": float(np.min(pair)),
        "floor": float(floor),
        "passes_floor": bool(np.mean(pair) >= floor),
        "exact": bool(np.array_equal(images_encprop, images_full)),
        "clip_sim_encprop": float(
            np.mean(harness.similarity(images_encprop, prompts))),
        "clip_sim_full": float(
            np.mean(harness.similarity(images_full, prompts))),
        "n": int(images_full.shape[0]),
        "real_weights": harness.loaded_real_weights,
        "gate_enforced": harness.loaded_real_weights,
    }
    return report
