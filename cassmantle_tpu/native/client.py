"""Asyncio client for mantlestore (the native C++ state store).

Implements the same :class:`StateStore` contract as MemoryStore, so the
game engine can run multi-process: N server workers (like the reference's
multi-worker uvicorn) share one mantlestore exactly as the reference's
workers share one Redis (SURVEY.md §5.8). The wire protocol is a RESP2
subset; blocking lock acquisition is client-side polling against the
server's atomic LOCK/UNLOCK (token + TTL, self-expiring on holder crash).
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import subprocess
from typing import Dict, Optional, Set

from cassmantle_tpu.chaos import afault_point
from cassmantle_tpu.engine.store import (
    LockTimeout,
    StateStore,
    Value,
    polled_store_lock,
)

__all__ = ["LockTimeout", "MantleStore", "ensure_built", "spawn_server"]
from cassmantle_tpu.utils.logging import get_logger

log = get_logger("native.store")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
BINARY = os.path.join(NATIVE_DIR, "build", "mantlestore")


def _binary_runs() -> bool:
    """True when the existing binary actually executes on THIS host. A
    binary built on a newer base image can be present but dead on
    arrival (GLIBC/GLIBCXX version mismatch): the dynamic loader refuses
    it at exec and it dies instantly with the complaint on stderr. A
    healthy mantlestore, by contrast, prints its "listening" line and
    serves until killed — so probe by spawning on port 0 (kernel picks
    an ephemeral port; never collides with a live server) and watching
    stderr briefly for either outcome."""
    import select

    try:
        proc = subprocess.Popen([BINARY, "0"], stdout=subprocess.DEVNULL,
                                stderr=subprocess.PIPE)
    # lint: ignore[swallowed-error] — "does the binary run" probe: False IS the answer, and callers rebuild or fall back on it
    except Exception:
        return False
    try:
        ready, _, _ = select.select([proc.stderr], [], [], 10.0)
        if not ready:  # neither died nor spoke: treat as unusable
            return False
        return b"listening" in proc.stderr.readline()
    # lint: ignore[swallowed-error] — same probe contract: an unreadable stderr means unusable, which is the False the caller acts on
    except Exception:
        return False
    finally:
        proc.kill()
        proc.wait()


def ensure_built() -> Optional[str]:
    """Build the server if needed; returns binary path or None. A
    present-but-unrunnable binary (toolchain mismatch with the build
    host) rebuilds from source like a missing one, and so does a binary
    older than mantlestore.cc (a stale build would silently drop source
    fixes — e.g. the lock-tombstone sweep semantics)."""
    source = os.path.join(NATIVE_DIR, "mantlestore.cc")
    runnable = os.path.exists(BINARY) and _binary_runs()
    stale = runnable and os.path.exists(source) and \
        os.path.getmtime(source) > os.path.getmtime(BINARY)
    if runnable and not stale:
        return BINARY
    try:
        subprocess.run(
            ["sh", os.path.join(NATIVE_DIR, "build.sh")],
            check=True, capture_output=True, timeout=120,
        )
        return BINARY if os.path.exists(BINARY) else None
    # lint: ignore[swallowed-error] — documented degrade ladder: stale binary beats no store, None falls back to the memory store; both logged and visible in the store banner
    except Exception as exc:  # no toolchain: callers fall back to memory
        if runnable:
            # a stale-but-runnable binary beats no store at all (git
            # checkouts don't preserve mtimes; a toolchain-less deploy
            # host must keep using the prebuilt binary)
            log.warning("mantlestore rebuild failed (%s); using the "
                        "existing binary despite newer source", exc)
            return BINARY
        log.warning("mantlestore build failed: %s", exc)
        return None


def spawn_server(port: int = 7070,
                 snapshot_path: Optional[str] = None,
                 snapshot_interval_s: float = 30.0,
                 repl: bool = False,
                 follower: bool = False,
                 repl_id: Optional[str] = None,
                 lease_ms: Optional[int] = None) -> subprocess.Popen:
    """Spawn mantlestore. With ``snapshot_path`` the server restores that
    snapshot at boot and persists to it periodically and on SIGTERM —
    the Redis-durability resume semantics of the reference (SURVEY §5.4).

    ``repl=True`` enables the replication log + leader lease heartbeat
    (the node boots as leader); ``follower=True`` boots it readonly,
    waiting for a pump to ship it the leader's log (engine/store.py
    ReplicatedStore). ``repl_id`` names the node in the lease;
    ``lease_ms`` sizes the leader lease TTL (failover detection time)."""
    binary = ensure_built()
    assert binary, "mantlestore binary unavailable"
    cmd = [binary, str(port)]
    if snapshot_path:
        cmd += [snapshot_path, str(snapshot_interval_s)]
    if repl or follower:
        cmd.append("--follower" if follower else "--repl")
        # ids must be UNIQUE per node: the PROMOTE lease fence skips the
        # liveness refusal for the lease holder's own id, so two nodes
        # sharing the binary's default id could promote past a live
        # leader (split brain). Default to a per-port id.
        cmd += ["--id", repl_id or f"node-{port}"]
        if lease_ms is not None:
            cmd += ["--lease-ms", str(int(lease_ms))]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    # wait for the listening line (restore logs precede it)
    while True:
        line = proc.stderr.readline().decode()
        assert line, "mantlestore exited before listening"
        if "listening" in line:
            return proc


def _b(v: Value) -> bytes:
    return v if isinstance(v, bytes) else str(v).encode()


class MantleStore(StateStore):
    def __init__(self, host: str = "127.0.0.1", port: int = 7070) -> None:
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._io_lock = asyncio.Lock()

    async def connect(self) -> "MantleStore":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        assert await self._cmd(b"PING") == b"PONG"
        return self

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            with contextlib.suppress(Exception):
                await self._writer.wait_closed()
            self._writer = None
            self._reader = None

    # -- protocol ---------------------------------------------------------
    async def _cmd(self, *args: bytes):
        # the store-boundary fault point (docs/CHAOS.md): latency here is
        # a slow store, partition (peer-scoped host:port) is a network
        # cut this client treats exactly like a refused connection
        await afault_point("store.client.op",
                           peer=f"{self.host}:{self.port}")
        if self._writer is None:
            await self.connect()
        async with self._io_lock:
            payload = b"*%d\r\n" % len(args)
            for a in args:
                payload += b"$%d\r\n%s\r\n" % (len(a), a)
            try:
                self._writer.write(payload)
                await self._writer.drain()
                return await self._read_reply()
            except asyncio.CancelledError:
                # a cancelled round trip (e.g. an aiohttp handler whose
                # client gave up) may leave this command's reply in
                # flight; the connection is shared, so the NEXT command
                # would read the stale reply and every later caller
                # desyncs. Drop the socket — the next op redials clean.
                writer, self._reader, self._writer = \
                    self._writer, None, None
                if writer is not None:
                    writer.close()
                raise

    async def raw_command(self, *args: bytes):
        """One command round trip — the public form of ``_cmd`` for
        composition (the shared lock protocol, ReplicatedStore)."""
        return await self._cmd(*args)

    async def _read_reply(self):
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("mantlestore closed connection")
        kind, rest = line[:1], line[1:].strip()
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RuntimeError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self._reader.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            return [await self._read_reply() for _ in range(int(rest))]
        raise RuntimeError(f"bad reply kind {kind!r}")

    # -- plain keys -------------------------------------------------------
    async def set(self, key, value):
        await self._cmd(b"SET", key.encode(), _b(value))

    async def get(self, key):
        return await self._cmd(b"GET", key.encode())

    async def setex(self, key, ttl, value):
        await self._cmd(b"SETEX", key.encode(),
                        str(int(ttl * 1000)).encode(), _b(value))

    async def delete(self, *keys):
        if keys:
            await self._cmd(b"DEL", *[k.encode() for k in keys])

    async def exists(self, key):
        return bool(await self._cmd(b"EXISTS", key.encode()))

    async def expire(self, key, ttl):
        await self._cmd(b"PEXPIRE", key.encode(),
                        str(int(ttl * 1000)).encode())

    async def ttl(self, key):
        ms = await self._cmd(b"PTTL", key.encode())
        if ms in (-1, -2):
            return float(ms)
        return ms / 1000.0

    # The server's RESP parser caps commands at 1024 args; multi-member
    # writes are chunked client-side so arbitrarily large collections
    # never wedge the connection (a too-long command would never parse
    # and the reply would never come).
    _CHUNK = 500

    async def _cmd_chunked(self, head, pairs_or_members, stride):
        for i in range(0, len(pairs_or_members), self._CHUNK * stride):
            await self._cmd(*head,
                            *pairs_or_members[i:i + self._CHUNK * stride])

    # -- hashes -----------------------------------------------------------
    async def hset(self, key, field=None, value=None, mapping=None):
        args = []
        if field is not None:
            args += [field.encode(), _b(value)]
        if mapping:
            for k, v in mapping.items():
                args += [k.encode(), _b(v)]
        if args:
            await self._cmd_chunked([b"HSET", key.encode()], args, 2)

    async def hget(self, key, field):
        return await self._cmd(b"HGET", key.encode(), field.encode())

    async def hgetall(self, key) -> Dict[str, bytes]:
        flat = await self._cmd(b"HGETALL", key.encode())
        return {
            flat[i].decode(): flat[i + 1] for i in range(0, len(flat), 2)
        }

    async def hdel(self, key, *fields):
        if fields:
            await self._cmd_chunked([b"HDEL", key.encode()],
                                    [f.encode() for f in fields], 1)

    async def hincrby(self, key, field, amount: int = 1) -> int:
        return await self._cmd(b"HINCRBY", key.encode(), field.encode(),
                               str(amount).encode())

    # -- sets -------------------------------------------------------------
    async def sadd(self, key, *members):
        if members:
            await self._cmd_chunked([b"SADD", key.encode()],
                                    [m.encode() for m in members], 1)

    async def srem(self, key, *members):
        if members:
            await self._cmd_chunked([b"SREM", key.encode()],
                                    [m.encode() for m in members], 1)

    async def smembers(self, key) -> Set[str]:
        return {m.decode() for m in await self._cmd(b"SMEMBERS",
                                                    key.encode())}

    async def sismember(self, key, member) -> bool:
        return bool(await self._cmd(b"SISMEMBER", key.encode(),
                                    member.encode()))

    # -- locks ------------------------------------------------------------
    def lock(self, name: str, timeout: float = 120.0,
             blocking_timeout: float = 2.0):
        # the shared polled protocol (engine/store.py): one definition
        # of the acquire loop and the :2/:0 hazard taxonomy for both
        # the single-node and replicated transports
        return polled_store_lock(self._cmd, name, timeout,
                                 blocking_timeout)

    async def flushall(self) -> None:
        await self._cmd(b"FLUSHALL")

    # -- replication (REPL verbs; see native/mantlestore.cc header) --------
    async def repl_role(self) -> str:
        return (await self._cmd(b"REPL", b"ROLE")).decode()

    async def repl_offset(self) -> tuple:
        """(log_start, log_end, applied). On a healthy node
        applied == log_end; lag of a follower = leader log_end - this."""
        start, end, applied = await self._cmd(b"REPL", b"OFFSET")
        return start, end, applied

    async def repl_tail(self, offset: int, max_commands: int = 256):
        """(next_offset, raw command stream) from ``offset``; None when
        the log was trimmed past it (caller must full-resync via
        repl_dump/repl_reset)."""
        reply = await self._cmd(b"REPL", b"TAIL", str(offset).encode(),
                                str(max_commands).encode())
        if len(reply) == 1:
            return None
        return reply[0], reply[1]

    async def repl_apply(self, expected_offset: int, stream: bytes) -> int:
        """Replay ``stream`` iff this follower's offset == expected;
        returns the follower's applied offset either way (exactly-once
        under racing pumps)."""
        return await self._cmd(b"REPL", b"APPLY",
                               str(expected_offset).encode(), stream)

    async def repl_dump(self) -> tuple:
        """(log_end, full-state command stream incl. live locks)."""
        end, stream = await self._cmd(b"REPL", b"DUMP")
        return end, stream

    async def repl_reset(self, offset: int, stream: bytes) -> int:
        """Full resync: flush, replay ``stream`` unlogged, set offsets."""
        return await self._cmd(b"REPL", b"RESET", str(offset).encode(),
                               stream)

    async def repl_promote(self) -> bool:
        """Ask a follower to take leadership; True when it did (False =
        the replicated leader lease is still live — the leader was
        heartbeating within its TTL)."""
        return await self._cmd(b"REPL", b"PROMOTE") == b"OK"

    async def repl_lease(self) -> tuple:
        """(holder id or '', seconds remaining) of the leader lease as
        this node sees it."""
        holder, ms = await self._cmd(b"REPL", b"LEASE")
        return holder.decode(), ms / 1000.0
