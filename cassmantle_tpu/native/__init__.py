from cassmantle_tpu.native.client import MantleStore, ensure_built, spawn_server  # noqa: F401
