"""Diffusion UNet (SD1.5 geometry by default, SDXL via UNetConfig.sdxl()).

This is the flagship TPU model: it replaces the reference's remote SDXL
Inference-API call (backend.py:270-295) with a local Flax module whose
denoise step runs as one jit'd XLA graph per DDIM step (ops/ddim.py wraps it
in a lax.scan).

TPU-first choices:
- NHWC layout end to end (XLA TPU-native conv layout; no transposes);
- bf16 params/activations with fp32 GroupNorm and fp32 softmax (via
  ops.attention), preserving image quality while feeding the MXU bf16;
- attention over image tokens (H·W up to 4096 at 512², 16k+ at SDXL-1024)
  goes through ops.attention → Pallas flash kernel on TPU;
- static shapes everywhere: the batch/resolution buckets come from
  ServingConfig, so XLA compiles once per bucket.

Structure matches Stable Diffusion's UNet so safetensors checkpoints map
1:1 (models/weights.py): conv_in → time-embed MLP → down levels (ResBlocks
+ spatial transformers + strided-conv downsample) → mid → up levels with
skip concatenation and nearest-neighbor upsample → GroupNorm/SiLU/conv_out.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import UNetConfig
from cassmantle_tpu.models.layers import (
    GEGLU,
    GroupNorm32,
    LayerNorm32,
    MultiHeadAttention,
    fused_gn_silu_conv3x3,
    nearest_upsample_2x,
    timestep_embedding,
)


class ResBlock(nn.Module):
    """GN/SiLU/conv3x3 x2 + time injection + skip.

    ``fused_conv`` routes both norm+act+conv sequences through the
    Pallas fused kernel (ops/fused_conv.py): GroupNorm statistics still
    reduce in fp32 here (``return_affine``), but the normalize, SiLU,
    and 3x3 conv run as one kernel so the activated tensor never
    round-trips HBM. The param tree is IDENTICAL either way
    (Conv3x3Params declares nn.Conv's exact kernel/bias layout), so
    checkpoints, the init cache, and the A/B share one tree;
    ``conv_pad_to`` additionally pads channel dims to MXU-friendly
    multiples inside the fused op (zero-fill, output sliced back).
    """

    out_channels: int
    dtype: jnp.dtype
    fused_conv: bool = False
    conv_pad_to: int = 0

    def _gn_silu_conv(self, x, norm_name: str, conv_name: str):
        return fused_gn_silu_conv3x3(
            x, self.out_channels, self.dtype, norm_name, conv_name,
            pad_to=self.conv_pad_to)

    @nn.compact
    def __call__(self, x, temb):
        if self.fused_conv:
            h = self._gn_silu_conv(x, "norm1", "conv1")
        else:
            h = GroupNorm32(name="norm1")(x)
            h = nn.silu(h)
            h = nn.Conv(self.out_channels, (3, 3), padding=1,
                        dtype=self.dtype, name="conv1")(h)
        t = nn.Dense(self.out_channels, dtype=self.dtype,
                     name="time_proj")(nn.silu(temb))
        h = h + t[:, None, None, :]
        if self.fused_conv:
            h = self._gn_silu_conv(h, "norm2", "conv2")
        else:
            h = GroupNorm32(name="norm2")(h)
            h = nn.silu(h)
            h = nn.Conv(self.out_channels, (3, 3), padding=1,
                        dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1),
                        dtype=self.dtype, name="skip")(x)
        return x + h


class BasicTransformerBlock(nn.Module):
    num_heads: int
    context_dim: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, context):
        h = LayerNorm32(name="ln1")(x)
        # bias-free q/k/v but biased out-projection: the published UNet
        # layout (manifests unet_sd15/unet_sdxl: to_out.0 has a bias).
        # fused_qkv: one projection matmul per site instead of three
        # (converters concatenate to_q/to_k/to_v at load) — the UNet
        # only ever runs full forwards, never cached decode.
        x = x + MultiHeadAttention(
            num_heads=self.num_heads, dtype=self.dtype, use_bias=False,
            out_bias=True, fused_qkv=True, name="self_attn",
        )(h)
        h = LayerNorm32(name="ln2")(x)
        x = x + MultiHeadAttention(
            num_heads=self.num_heads, dtype=self.dtype, use_bias=False,
            out_bias=True, fused_qkv=True, name="cross_attn",
        )(h, context=context)
        h = LayerNorm32(name="ln3")(x)
        x = x + GEGLU(
            intermediate=x.shape[-1] * 4, dtype=self.dtype, name="ff"
        )(h)
        return x


class SpatialTransformer(nn.Module):
    """Flatten HW -> tokens, run transformer blocks with text cross-attn."""

    num_heads: int
    depth: int
    context_dim: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, context):
        b, h, w, c = x.shape
        residual = x
        # diffusers' Transformer2DModel hardcodes eps=1e-6 for this norm
        # (unlike the resblock norms at the 1e-5 norm_eps default)
        x = GroupNorm32(epsilon=1e-6, name="norm")(x)
        x = nn.Dense(c, dtype=self.dtype, name="proj_in")(x)
        x = x.reshape(b, h * w, c)
        for i in range(self.depth):
            x = BasicTransformerBlock(
                num_heads=self.num_heads, context_dim=self.context_dim,
                dtype=self.dtype, name=f"block_{i}",
            )(x, context)
        x = x.reshape(b, h, w, c)
        x = nn.Dense(c, dtype=self.dtype, name="proj_out")(x)
        return x + residual


class UNet(nn.Module):
    cfg: UNetConfig

    def _heads(self, channels: int) -> int:
        if self.cfg.num_heads is not None:
            return self.cfg.num_heads
        return max(1, channels // 64)  # SDXL convention: head_dim 64

    @nn.compact
    def __call__(
        self,
        latents: Optional[jax.Array],        # (B, H, W, 4) noisy latents
        timesteps: jax.Array,                # (B,) int/float
        context: jax.Array,                  # (B, S, context_dim) text states
        addition_embeds: Optional[jax.Array] = None,  # SDXL micro-conds
        deep_cache: Optional[jax.Array] = None,
        return_deep: bool = False,
        skips_cache=None,
        return_skips: bool = False,
    ) -> jax.Array:
        """Denoise forward. Two pairs of extra modes implement feature
        reuse across adjacent diffusion steps (PARITY.md documents both
        approximation contracts):

        Deep-feature reuse (DeepCache-style — ops/ddim.py::
        ddim_sample_deepcache):

        - ``return_deep=True``: also return the activation entering the
          SHALLOWEST up level (captured after level 1's upsample conv).
        - ``deep_cache=<that activation>``: run only conv_in + level-0
          down blocks (fresh skips), substitute the cached deep
          activation, and finish with level-0 up blocks + conv_out —
          skipping every deeper level and the mid block entirely.

        Encoder propagation (Faster Diffusion-style — ops/ddim.py::
        ddim_sample_encprop; the symmetric counterpart that skips the
        ENCODER instead of the deep levels):

        - ``return_skips=True``: also return the encoder feature cache
          ``(skip stack, up-path entry)`` — the full down-path skip
          stack plus the activation entering the up path (the mid-block
          output) as captured at a key step.
        - ``skips_cache=<that cache>``: skip conv_in, every down level,
          and the mid block; run ONLY the up path (+ conv_out) against
          the cached skips. The time embedding stays fresh — it is the
          only place the current timestep enters the decoder — so
          ``latents`` may be None (nothing reads it). Because the
          decoder never touches x_t, a run of consecutive propagated
          steps can batch into ONE decoder forward (the cache rows
          tile along batch; ops/ddim.py::make_cfg_denoiser_encprop).

        Both return_* flags may be combined (the composed
        deepcache+encprop serving loop captures both at key steps);
        ``deep_cache`` and ``skips_cache`` are mutually exclusive.
        """
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        decoder_only = skips_cache is not None
        assert not (decoder_only and deep_cache is not None), (
            "deep_cache and skips_cache are mutually exclusive modes"
        )
        if latents is not None:
            latents = latents.astype(dtype)
        else:
            assert decoder_only, "latents may be None only with skips_cache"
        context = context.astype(dtype)
        shallow_only = deep_cache is not None
        assert not (return_skips and (shallow_only or decoder_only)), (
            "return_skips needs the full encoder to have run"
        )

        # -- time embedding ------------------------------------------------
        temb = timestep_embedding(timesteps, cfg.base_channels)
        temb = nn.Dense(cfg.time_embed_dim, dtype=dtype, name="time_fc1")(
            temb.astype(dtype))
        temb = nn.Dense(cfg.time_embed_dim, dtype=dtype, name="time_fc2")(
            nn.silu(temb))
        if cfg.addition_embed_dim and addition_embeds is not None:
            aemb = nn.Dense(cfg.time_embed_dim, dtype=dtype,
                            name="add_fc1")(addition_embeds.astype(dtype))
            aemb = nn.Dense(cfg.time_embed_dim, dtype=dtype,
                            name="add_fc2")(nn.silu(aemb))
            temb = temb + aemb

        levels = len(cfg.channel_mults)

        def res_block(ch: int, name: str) -> ResBlock:
            return ResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                            conv_pad_to=cfg.conv_pad_to, name=name)

        if decoder_only:
            # encoder propagation: the whole encoder (conv_in + down
            # levels + mid block) is skipped — the cached skip stack and
            # up-path entry stand in for it. Only temb above is fresh.
            cached_skips, up_entry = skips_cache
            skips = [s.astype(dtype) for s in cached_skips]
            x = up_entry.astype(dtype)
        else:
            x = nn.Conv(cfg.base_channels, (3, 3), padding=1,
                        dtype=dtype, name="conv_in")(latents)

            # -- down ------------------------------------------------------
            skips = [x]
            down_levels = 1 if shallow_only else levels
            for lvl in range(down_levels):
                ch = cfg.base_channels * cfg.channel_mults[lvl]
                for blk in range(cfg.blocks_per_level):
                    x = res_block(ch, f"down_{lvl}_res_{blk}")(x, temb)
                    if cfg.attention_levels[lvl] \
                            and cfg.transformer_depth[lvl]:
                        x = SpatialTransformer(
                            num_heads=self._heads(ch),
                            depth=cfg.transformer_depth[lvl],
                            context_dim=cfg.context_dim, dtype=dtype,
                            name=f"down_{lvl}_attn_{blk}",
                        )(x, context)
                    skips.append(x)
                if lvl != levels - 1 and not shallow_only:
                    x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=1,
                                dtype=dtype,
                                name=f"down_{lvl}_downsample")(x)
                    skips.append(x)

        skips_out = tuple(skips) if return_skips else None

        if not shallow_only and not decoder_only:
            # -- mid -------------------------------------------------------
            mid_ch = cfg.base_channels * cfg.channel_mults[-1]
            mid_depth = max(
                [d for lvl, d in enumerate(cfg.transformer_depth)
                 if cfg.attention_levels[lvl]] or [1]
            )
            x = res_block(mid_ch, "mid_res_0")(x, temb)
            x = SpatialTransformer(
                num_heads=self._heads(mid_ch), depth=mid_depth,
                context_dim=cfg.context_dim, dtype=dtype, name="mid_attn",
            )(x, context)
            x = res_block(mid_ch, "mid_res_1")(x, temb)

        up_entry_out = x if return_skips else None

        # -- up ------------------------------------------------------------
        deep_out = None
        up_levels = [0] if shallow_only else list(reversed(range(levels)))
        if shallow_only:
            x = deep_cache.astype(dtype)
        for lvl in up_levels:
            if lvl == 0 and return_deep:
                deep_out = x
            ch = cfg.base_channels * cfg.channel_mults[lvl]
            for blk in range(cfg.blocks_per_level + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = res_block(ch, f"up_{lvl}_res_{blk}")(x, temb)
                if cfg.attention_levels[lvl] and cfg.transformer_depth[lvl]:
                    x = SpatialTransformer(
                        num_heads=self._heads(ch),
                        depth=cfg.transformer_depth[lvl],
                        context_dim=cfg.context_dim, dtype=dtype,
                        name=f"up_{lvl}_attn_{blk}",
                    )(x, context)
            if lvl != 0:
                x = nearest_upsample_2x(x)
                x = nn.Conv(ch, (3, 3), padding=1, dtype=dtype,
                            name=f"up_{lvl}_upsample")(x)

        assert not skips, f"unconsumed skips: {len(skips)}"

        # -- out -----------------------------------------------------------
        x = GroupNorm32(name="norm_out")(x)
        x = nn.silu(x)
        x = nn.Conv(cfg.sample_channels, (3, 3), padding=1,
                    dtype=jnp.float32, name="conv_out")(x)
        eps = x.astype(jnp.float32)
        if return_deep and return_skips:
            return eps, deep_out, (skips_out, up_entry_out)
        if return_deep:
            return eps, deep_out
        if return_skips:
            return eps, (skips_out, up_entry_out)
        return eps
