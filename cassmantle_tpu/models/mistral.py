"""Mistral-7B-class causal LM (RoPE + GQA + sliding window + SwiGLU).

The reference's prompt model IS Mistral-7B-Instruct — it calls the hosted
HF Inference endpoint for it (reference backend.py:25, 240-268). This module
is the local TPU-native equivalent of that model family, exposing the same
``__call__`` / ``prefill`` / ``decode_step`` contract as GPT2LM so the
jitted greedy-decode scan (ops/decode.py) and the serving PromptGenerator
drive either family unchanged.

TPU-first choices:
- grouped-query attention: K/V projected at ``num_kv_heads`` and the cache
  stored at KV width (4x less HBM traffic per decode step at 7B scale than
  full-head caches); heads are repeated to query width only at the attention
  site, feeding the MXU full-width batched matmuls;
- RoPE computed in fp32 and applied pre-cache, so cached K is
  position-encoded once and decode steps touch only one new position;
- sliding-window attention expressed as a static band mask under jit —
  no dynamic shapes; the window is part of the compiled graph;
- RMSNorm/softmax accumulate fp32, matmuls run bf16 into the MXU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import MistralConfig
from cassmantle_tpu.ops.attention import multi_head_attention


class RMSNorm(nn.Module):
    """Root-mean-square LayerNorm (no mean subtraction, no bias), fp32."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * jax.lax.rsqrt(var + self.epsilon)
        return (out * scale.astype(jnp.float32)).astype(orig_dtype)


def rope_tables(positions: jax.Array, head_dim: int,
                theta: float) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for rotary embedding. positions (...,S) -> two
    (..., S, head_dim/2) fp32 arrays."""
    half = head_dim // 2
    freqs = theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotary position embedding, GPT-NeoX split-half convention (the
    Mistral/Llama family layout). x: (..., S, H, D); cos/sin (..., S, D/2)
    broadcast over heads."""
    orig_dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # broadcast over the head axis
    s = sin[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(orig_dtype)


def repeat_kv(kv: jax.Array, n_rep: int) -> jax.Array:
    """(..., S, KVH, D) -> (..., S, KVH*n_rep, D) by head repetition."""
    if n_rep == 1:
        return kv
    return jnp.repeat(kv, n_rep, axis=-2)


def band_mask(q_pos: jax.Array, k_pos: jax.Array,
              window: int) -> jax.Array:
    """Causal sliding-window mask: attend iff 0 <= q - k < window.

    q_pos (Sq,), k_pos (Sk,) -> bool (Sq, Sk). Static under jit.
    """
    diff = q_pos[:, None] - k_pos[None, :]
    return (diff >= 0) & (diff < window)


class MistralAttention(nn.Module):
    cfg: MistralConfig
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, cos, sin, mask=None, kv_cache=None,
                 return_kv: bool = False, causal: bool = False):
        """GQA attention with RoPE applied to q/k before caching.

        Same cache contract as models/layers.py::MultiHeadAttention, but
        the cache holds ``num_kv_heads`` heads: decode mode takes
        ``kv_cache=(cache_k, cache_v, index)`` with cache_k/v shaped
        (B, max_len, KVH, D) and writes this call's (RoPE'd) k/v at
        ``index``.
        """
        cfg = self.cfg
        d = cfg.head_dim
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n * d, use_bias=False, dtype=self.dtype, name=name
        )
        b, s, _ = x.shape
        q = dense(cfg.num_heads, "q")(x).reshape(b, s, cfg.num_heads, d)
        k = dense(cfg.num_kv_heads, "k")(x).reshape(b, s, cfg.num_kv_heads, d)
        v = dense(cfg.num_kv_heads, "v")(x).reshape(b, s, cfg.num_kv_heads, d)

        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        kv_out = None
        if kv_cache is not None:
            cache_k, cache_v, index = kv_cache
            cache_k = jax.lax.dynamic_update_slice_in_dim(
                cache_k, k.astype(cache_k.dtype), index, axis=-3
            )
            cache_v = jax.lax.dynamic_update_slice_in_dim(
                cache_v, v.astype(cache_v.dtype), index, axis=-3
            )
            k, v = cache_k, cache_v
            kv_out = (cache_k, cache_v)
        elif return_kv:
            kv_out = (k, v)

        n_rep = cfg.num_heads // cfg.num_kv_heads
        out = multi_head_attention(
            q, repeat_kv(k, n_rep), repeat_kv(v, n_rep), mask=mask,
            causal=causal,
        )
        out = out.reshape(b, s, cfg.num_heads * d)
        out = nn.Dense(cfg.hidden_size, use_bias=False, dtype=self.dtype,
                       name="out")(out)
        if kv_out is not None:
            return out, kv_out
        return out


class SwiGLU(nn.Module):
    """Mistral/Llama MLP: down(silu(gate(x)) * up(x))."""

    intermediate: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        g = nn.Dense(self.intermediate, use_bias=False, dtype=self.dtype,
                     name="gate")(x)
        u = nn.Dense(self.intermediate, use_bias=False, dtype=self.dtype,
                     name="up")(x)
        return nn.Dense(features, use_bias=False, dtype=self.dtype,
                        name="down")(nn.silu(g) * u)


class MistralBlock(nn.Module):
    cfg: MistralConfig
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, cos, sin, mask=None, kv_cache=None,
                 return_kv: bool = False, causal: bool = False):
        h = RMSNorm(self.cfg.rms_eps, name="ln1")(x)
        attn_out = MistralAttention(self.cfg, self.dtype, name="attn")(
            h, cos, sin, mask=mask, kv_cache=kv_cache,
            return_kv=return_kv, causal=causal,
        )
        if kv_cache is not None or return_kv:
            a, kv = attn_out
        else:
            a, kv = attn_out, None
        x = x + a
        h = RMSNorm(self.cfg.rms_eps, name="ln2")(x)
        x = x + SwiGLU(self.cfg.intermediate_size, self.dtype,
                       name="mlp")(h)
        return x, kv


class MistralLM(nn.Module):
    """Causal LM with the GPT2LM serving contract (__call__/prefill/
    decode_step), so ops/decode.py::greedy_decode drives it unchanged."""

    cfg: MistralConfig

    @property
    def _dtype(self):
        return jnp.dtype(self.cfg.dtype)

    def setup(self):
        cfg = self.cfg
        self.embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                              dtype=self._dtype, name="embed")
        self.blocks = [
            MistralBlock(cfg, self._dtype, name=f"block_{i}")
            for i in range(cfg.num_layers)
        ]
        self.ln_f = RMSNorm(cfg.rms_eps, name="ln_f")
        self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=jnp.float32, name="lm_head")

    def _logits(self, hidden: jax.Array) -> jax.Array:
        # fp32 head keeps greedy argmax stable under bf16 activations
        return self.lm_head(hidden.astype(jnp.float32))

    def __call__(self, input_ids: jax.Array,
                 valid: Optional[jax.Array] = None,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        """Plain forward: (B, S) [+ (B, S) validity] -> (B, S, V).

        Explicit (B, S) ``positions`` select the context-parallel form
        (zigzag-permuted data, parallel/lm_train.py): RoPE follows the
        per-token true positions, the mask is owned by the attention op
        (plain causal, dispatchable to the sharded zigzag ring), and the
        sequence must fit the sliding window — the band mask degenerates
        to causal there, which is what the zigzag kernel implements."""
        cfg = self.cfg
        _, s = input_ids.shape
        if positions is not None:
            assert valid is None, \
                "positions mode owns masking; pre-mask inputs instead"
            assert s <= cfg.sliding_window, (
                f"context-parallel Mistral needs seq {s} <= "
                f"sliding_window {cfg.sliding_window} (banded zigzag "
                f"attention not implemented)")
            mask = None
        else:
            positions = jnp.arange(s)
            mask = band_mask(
                positions, positions, cfg.sliding_window)[None, None]
            if valid is not None:
                mask = mask & valid[:, None, None, :]
        cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        x = self.embed(input_ids)
        for block in self.blocks:
            x, _ = block(x, cos, sin, mask=mask, causal=mask is None)
        return self._logits(self.ln_f(x))

    def prefill(
        self, input_ids: jax.Array, prompt_len: jax.Array, max_len: int
    ) -> Tuple[jax.Array, Tuple]:
        """Right-padded prompt forward seeding a ``max_len`` decode cache.

        Cache layout: per-layer (k, v), each (B, max_len, KVH, D) with
        RoPE already applied to K and positions >= P zero-filled.
        """
        cfg = self.cfg
        b, p = input_ids.shape
        assert p <= max_len
        positions = jnp.arange(p)
        cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        x = self.embed(input_ids)
        band = band_mask(positions, positions, cfg.sliding_window)
        valid = positions[None, :] < prompt_len[:, None]
        mask = band[None, None] & valid[:, None, None, :]
        cache = []
        for block in self.blocks:
            x, (k, v) = block(x, cos, sin, mask=mask, return_kv=True)
            pad = ((0, 0), (0, max_len - p), (0, 0), (0, 0))
            cache.append((jnp.pad(k, pad), jnp.pad(v, pad)))
        logits = self._logits(self.ln_f(x))
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[:, None, None], axis=1
        ).squeeze(1)
        return last, tuple(cache)

    def decode_step(
        self,
        token: jax.Array,      # (B,) ids for position ``index``
        index: jax.Array,      # scalar int32
        cache: Tuple,
        valid: jax.Array,      # (B, max_len) cache validity incl. this step
    ) -> Tuple[jax.Array, Tuple]:
        """One cached decode step; the S=1 case of :meth:`decode_chunk`
        (one code path shared with the speculative verify forward).
        Returns (logits (B, V), new cache)."""
        logits, new_cache = self.decode_chunk(
            token[:, None], index, cache, valid)
        return logits[:, 0], new_cache

    def decode_chunk(
        self,
        tokens: jax.Array,     # (B, S) ids for positions index..index+S-1
        index: jax.Array,      # scalar int32: cache position of tokens[:, 0]
        cache: Tuple,
        valid: jax.Array,      # (B, max_len) cache validity incl. the chunk
    ) -> Tuple[jax.Array, Tuple]:
        """Multi-token cached decode (the GPT2LM.decode_chunk contract):
        RoPE follows the true positions ``index + j`` and the sliding
        window is enforced per query inside the shared causal chunk
        mask — cache positions at or below ``index + j - window`` are
        never attended by query j. Returns (logits (B, S, V), new
        cache)."""
        from cassmantle_tpu.models.layers import chunk_causal_mask

        cfg = self.cfg
        _, s = tokens.shape
        mask = chunk_causal_mask(valid, index, s,
                                 window=cfg.sliding_window)
        positions = index + jnp.arange(s)
        cos, sin = rope_tables(positions[None, :], cfg.head_dim,
                               cfg.rope_theta)
        x = self.embed(tokens)
        new_cache = []
        for block, (ck, cv) in zip(self.blocks, cache):
            x, kv = block(x, cos, sin, mask=mask, kv_cache=(ck, cv, index))
            new_cache.append(kv)
        return self._logits(self.ln_f(x)), tuple(new_cache)
