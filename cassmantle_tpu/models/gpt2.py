"""GPT-2-class causal LM for prompt/story generation.

Replaces the reference's remote Mistral-7B Inference-API call
(backend.py:240-268): story episodes are generated locally by greedy decode
(ops/decode.py) over this module, 32-96 new tokens per round, matching the
reference's decode budget (backend.py:250-255).

Two call modes, one parameter set, all static shapes:
- ``prefill``: full forward over the right-padded prompt bucket; returns
  last-real-token logits plus every layer's k/v to seed a fixed-size decode
  cache.
- ``decode_step``: single-token step extending the cache; runs inside the
  sampler's lax.scan. The caller owns the cache-validity mask (right-padded
  prompt positions stay masked forever).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import GPT2Config
from cassmantle_tpu.models.layers import (
    MultiHeadAttention,
    TransformerMLP,
    chunk_causal_mask,
)


class GPT2Block(nn.Module):
    cfg: GPT2Config
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, mask=None, kv_cache=None, return_kv=False,
                 causal=False):
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln1")(x)
        # act_per_token: under W8A8 (lm_w8a8) LM activations quantize
        # with per-token scales — decode activations are outlier-heavy
        # per position, and a row-max costs nothing against the matmul
        attn_out = MultiHeadAttention(
            num_heads=self.cfg.num_heads, dtype=self.dtype, name="attn",
            act_per_token=True,
        )(h, mask=mask, kv_cache=kv_cache, return_kv=return_kv,
          causal=causal)
        if kv_cache is not None or return_kv:
            a, kv = attn_out
        else:
            a, kv = attn_out, None
        x = x + a
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln2")(x)
        x = x + TransformerMLP(
            intermediate=self.cfg.hidden_size * 4, dtype=self.dtype,
            name="mlp", act_per_token=True,
        )(h)
        return x, kv


class GPT2LM(nn.Module):
    cfg: GPT2Config

    @property
    def _dtype(self):
        return jnp.dtype(self.cfg.dtype)

    def setup(self):
        dtype = self._dtype
        self.wte = nn.Embed(self.cfg.vocab_size, self.cfg.hidden_size,
                            dtype=dtype, name="wte")
        self.wpe = nn.Embed(self.cfg.max_positions, self.cfg.hidden_size,
                            dtype=dtype, name="wpe")
        self.blocks = [
            GPT2Block(self.cfg, dtype, name=f"block_{i}")
            for i in range(self.cfg.num_layers)
        ]
        self.ln_f = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_f")

    def _logits(self, hidden: jax.Array) -> jax.Array:
        # weight-tied LM head (fp32 matmul keeps greedy argmax stable)
        emb = self.wte.embedding.astype(jnp.float32)
        return hidden.astype(jnp.float32) @ emb.T

    def __call__(self, input_ids: jax.Array,
                 valid: Optional[jax.Array] = None,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        """Plain forward: (B, S) [+ optional (B, S) validity] -> (B, S, V).

        With ``valid=None`` the causal mask is owned by the attention op
        (never materialized here) — which also makes this forward
        context-parallel capable: under ``ops.attention.context_parallel``
        the attention runs sequence-sharded, and the caller supplies
        zigzag-permuted ``positions`` matching its permuted input_ids
        (parallel/lm_train.py)."""
        _, s = input_ids.shape
        if positions is None:
            positions = jnp.arange(s)[None, :]
        x = self.wte(input_ids) + self.wpe(positions)
        if valid is None:
            mask = None
        else:
            causal = jnp.tril(jnp.ones((s, s), dtype=bool))[None, None]
            mask = causal & valid[:, None, None, :]
        for block in self.blocks:
            x, _ = block(x, mask=mask, causal=mask is None)
        return self._logits(self.ln_f(x))

    def prefill(
        self, input_ids: jax.Array, prompt_len: jax.Array, max_len: int
    ) -> Tuple[jax.Array, Tuple]:
        """Padded-prompt forward seeding a ``max_len`` decode cache.

        input_ids (B, P) right-padded, prompt_len (B,). Returns
        (last-real-token logits (B, V), cache tuple of per-layer (k, v)
        each (B, max_len, H, D) with positions >= P zero-filled).
        """
        b, p = input_ids.shape
        assert p <= max_len
        positions = jnp.arange(p)[None, :]
        x = self.wte(input_ids) + self.wpe(positions)
        causal = jnp.tril(jnp.ones((p, p), dtype=bool))
        valid = positions < prompt_len[:, None]
        mask = causal[None, None] & valid[:, None, None, :]
        cache = []
        for block in self.blocks:
            x, (k, v) = block(x, mask=mask, return_kv=True)
            pad = ((0, 0), (0, max_len - p), (0, 0), (0, 0))
            cache.append((jnp.pad(k, pad), jnp.pad(v, pad)))
        logits = self._logits(self.ln_f(x))
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[:, None, None], axis=1
        ).squeeze(1)
        return last, tuple(cache)

    def decode_step(
        self,
        token: jax.Array,      # (B,) ids for position ``index``
        index: jax.Array,      # scalar int32
        cache: Tuple,
        valid: jax.Array,      # (B, max_len) cache validity incl. this step
    ) -> Tuple[jax.Array, Tuple]:
        """One greedy-decode step; the S=1 case of :meth:`decode_chunk`
        (one code path, so the speculative verify forward and the plain
        greedy scan run the exact same per-position computation).
        Returns (logits (B, V), updated cache)."""
        logits, new_cache = self.decode_chunk(
            token[:, None], index, cache, valid)
        return logits[:, 0], new_cache

    def decode_chunk(
        self,
        tokens: jax.Array,     # (B, S) ids for positions index..index+S-1
        index: jax.Array,      # scalar int32: cache position of tokens[:, 0]
        cache: Tuple,
        valid: jax.Array,      # (B, max_len) cache validity incl. the chunk
    ) -> Tuple[jax.Array, Tuple]:
        """Multi-token cached decode: score S positions in ONE forward.

        The speculative-decode verify step (ops/decode.py): the chunk's
        k/v append into the cache at ``index..index+S-1`` (one
        dynamic-update-slice per layer — the chunk-append contract in
        models/layers.py) and each query j attends the cache under the
        shared causal chunk mask (``<= index + j``), so logits[:, j]
        equals what ``decode_step`` would produce after feeding
        tokens[:, :j+1] one at a time. One weight read serves all S
        positions — the whole point of drafting.

        Returns (logits (B, S, V), updated cache).
        """
        _, s = tokens.shape
        positions = index + jnp.arange(s)
        x = self.wte(tokens) + self.wpe(positions[None, :])
        mask = chunk_causal_mask(valid, index, s)
        new_cache = []
        for block, (ck, cv) in zip(self.blocks, cache):
            x, kv = block(x, mask=mask, kv_cache=(ck, cv, index))
            new_cache.append(kv)
        return self._logits(self.ln_f(x)), tuple(new_cache)
