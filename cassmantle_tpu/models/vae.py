"""SD autoencoder (VAE) — decoder is the serving hot path, encoder included
for completeness (img2img, tests).

Replaces the image-decoding tail of the reference's remote diffusion call
(backend.py:270-295): after the DDIM scan finishes, latents decode to pixels
on-device and only uint8 RGB crosses back to host.

NHWC, fp32 by default (the VAE is the most precision-sensitive stage; its
FLOPs are a rounding error next to 50 UNet steps — though at SDXL-1024 the
decode is 10.47 TF/image, which the decode-side kernels below attack).
Attention in the mid block is single-head over H·W tokens, routed through
ops.attention like every other attention site — on TPU that now dispatches
the wide-head flash variant (ops/flash_attention.py::flash_wide_ok,
512-blocks) instead of materializing the S=16,384 score matrix in HBM at
SDXL's 128² latent. ``VAEConfig.fused_conv`` additionally routes every
ResBlock's GN→SiLU→conv3x3 pair through the fused Pallas kernel.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import VAEConfig
from cassmantle_tpu.models.layers import (
    GroupNorm32,
    MultiHeadAttention,
    fused_gn_silu_conv3x3,
    nearest_upsample_2x,
)


class VAEResBlock(nn.Module):
    """GN/SiLU/conv3x3 x2 + skip — the VAE twin of the UNet ResBlock.

    ``fused_conv`` routes both norm+act+conv sequences through the same
    Pallas fused kernel the UNet hot loop uses (ops/fused_conv.py):
    GroupNorm statistics still reduce in fp32 here (``return_affine``,
    at the VAE's 1e-6 epsilon), and the normalize, SiLU, and 3x3 conv
    run as one kernel — the activated tensor never round-trips HBM,
    which at SDXL decode means the 1024² per-level activations. The
    param tree is IDENTICAL either way (Conv3x3Params declares
    nn.Conv's exact layout), so checkpoints and the init cache are
    shared and ``VAEConfig.arch()`` clears the flag for identity.
    """

    out_channels: int
    dtype: jnp.dtype
    fused_conv: bool = False

    def _gn_silu_conv(self, x, norm_name: str, conv_name: str):
        return fused_gn_silu_conv3x3(
            x, self.out_channels, self.dtype, norm_name, conv_name,
            epsilon=1e-6)

    @nn.compact
    def __call__(self, x):
        if self.fused_conv:
            h = self._gn_silu_conv(x, "norm1", "conv1")
        else:
            h = GroupNorm32(epsilon=1e-6, name="norm1")(x)
            h = nn.silu(h)
            h = nn.Conv(self.out_channels, (3, 3), padding=1,
                        dtype=self.dtype, name="conv1")(h)
        if self.fused_conv:
            h = self._gn_silu_conv(h, "norm2", "conv2")
        else:
            h = GroupNorm32(epsilon=1e-6, name="norm2")(h)
            h = nn.silu(h)
            h = nn.Conv(self.out_channels, (3, 3), padding=1,
                        dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1),
                        dtype=self.dtype, name="skip")(x)
        return x + h


class VAEAttnBlock(nn.Module):
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        residual = x
        x = GroupNorm32(epsilon=1e-6, name="norm")(x)
        x = x.reshape(b, h * w, c)
        x = MultiHeadAttention(num_heads=1, dtype=self.dtype, name="attn")(x)
        return residual + x.reshape(b, h, w, c)


class VAEDecoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, latents: jax.Array) -> jax.Array:
        """(B, h, w, 4) scaled latents -> (B, 8h, 8w, 3) in [-1, 1]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        z = (latents / cfg.scaling_factor).astype(dtype)
        z = nn.Conv(cfg.latent_channels, (1, 1), dtype=dtype,
                    name="post_quant_conv")(z)

        mults = cfg.channel_mults
        ch = cfg.base_channels * mults[-1]
        x = nn.Conv(ch, (3, 3), padding=1, dtype=dtype, name="conv_in")(z)
        x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                        name="mid_res_0")(x)
        x = VAEAttnBlock(dtype, name="mid_attn")(x)
        x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                        name="mid_res_1")(x)

        for i, mult in enumerate(reversed(mults)):
            lvl = len(mults) - 1 - i
            ch = cfg.base_channels * mult
            for blk in range(cfg.blocks_per_level + 1):
                x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                                name=f"up_{lvl}_res_{blk}")(x)
            if lvl != 0:
                x = nearest_upsample_2x(x)
                x = nn.Conv(ch, (3, 3), padding=1, dtype=dtype,
                            name=f"up_{lvl}_upsample")(x)

        x = GroupNorm32(epsilon=1e-6, name="norm_out")(x)
        x = nn.silu(x)
        x = nn.Conv(3, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return x.astype(jnp.float32)


class VAEEncoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, images: jax.Array, rng: jax.Array) -> jax.Array:
        """(B, H, W, 3) in [-1,1] -> sampled scaled latents (B, H/8, W/8, 4)."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Conv(cfg.base_channels, (3, 3), padding=1, dtype=dtype,
                    name="conv_in")(images.astype(dtype))
        for lvl, mult in enumerate(cfg.channel_mults):
            ch = cfg.base_channels * mult
            for blk in range(cfg.blocks_per_level):
                x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                                name=f"down_{lvl}_res_{blk}")(x)
            if lvl != len(cfg.channel_mults) - 1:
                x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=1,
                            dtype=dtype, name=f"down_{lvl}_downsample")(x)
        ch = cfg.base_channels * cfg.channel_mults[-1]
        x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                        name="mid_res_0")(x)
        x = VAEAttnBlock(dtype, name="mid_attn")(x)
        x = VAEResBlock(ch, dtype, fused_conv=cfg.fused_conv,
                        name="mid_res_1")(x)
        x = GroupNorm32(epsilon=1e-6, name="norm_out")(x)
        x = nn.silu(x)
        moments = nn.Conv(cfg.latent_channels * 2, (3, 3), padding=1,
                          dtype=jnp.float32, name="conv_out")(x)
        moments = nn.Conv(cfg.latent_channels * 2, (1, 1), dtype=jnp.float32,
                          name="quant_conv")(moments)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        logvar = jnp.clip(logvar, -30.0, 20.0)
        std = jnp.exp(0.5 * logvar)
        sample = mean + std * jax.random.normal(rng, mean.shape)
        return sample * cfg.scaling_factor


def postprocess_images(decoded: jax.Array) -> jax.Array:
    """[-1,1] float -> uint8 RGB, on device."""
    x = jnp.clip(decoded * 0.5 + 0.5, 0.0, 1.0)
    return jnp.round(x * 255.0).astype(jnp.uint8)
