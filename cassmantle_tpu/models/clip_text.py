"""CLIP text encoder (SD's text tower), Flax.

Replaces the text-conditioning half of the remote SDXL call the reference
makes (backend.py:270-295): prompts are tokenized on host, encoded here on
TPU, and the hidden states feed the UNet's cross-attention.

Architecture: pre-LN causal transformer with learned positional embeddings
and quick-GELU, matching CLIP ViT-L/14's text model so real SD1.5 weights
load via models/weights.py. SDXL's second tower (OpenCLIP bigG) is the same
module at ClipTextConfig.sdxl_big() dims.
"""

from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import ClipTextConfig
from cassmantle_tpu.models.layers import (
    MultiHeadAttention,
    TransformerMLP,
    exact_gelu,
    quick_gelu,
)

# published hidden_act per tower: ViT-L quick_gelu, OpenCLIP bigG gelu
_ACTS = {"quick_gelu": quick_gelu, "gelu": exact_gelu}


class ClipBlock(nn.Module):
    cfg: ClipTextConfig
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, mask):
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln1")(x)
        h = MultiHeadAttention(
            num_heads=self.cfg.num_heads, dtype=self.dtype,
            fused_qkv=True, name="attn"
        )(h, mask=mask)
        x = x + h
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln2")(x)
        h = TransformerMLP(
            intermediate=self.cfg.intermediate_size,
            activation=_ACTS[self.cfg.hidden_act],
            dtype=self.dtype,
            name="mlp",
        )(h)
        return x + h


class ClipTextEncoder(nn.Module):
    cfg: ClipTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> dict:
        """input_ids: (B, S) int32 -> {hidden: (B,S,D), pooled: (B,D)}."""
        _, seq = input_ids.shape
        tok = nn.Embed(
            self.cfg.vocab_size, self.cfg.hidden_size,
            dtype=self.dtype, name="token_embedding",
        )(input_ids)
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.01),
            (self.cfg.max_positions, self.cfg.hidden_size),
        )
        x = tok + pos[None, :seq].astype(self.dtype)

        causal = jnp.tril(jnp.ones((seq, seq), dtype=bool))[None, None]
        penultimate = x
        for i in range(self.cfg.num_layers):
            x = ClipBlock(self.cfg, self.dtype, name=f"block_{i}")(x, causal)
            if i == self.cfg.num_layers - 2:
                # SDXL conditions the UNet on the second-to-last hidden
                # state (no final LN) of both towers — diffusers'
                # ``hidden_states[-2]`` / clip-skip-1 convention.
                penultimate = x

        hidden = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_final")(x)
        # CLIP pools at the EOT token = argmax of ids (highest id is EOT).
        eot = jnp.argmax(input_ids, axis=-1)
        pooled = jnp.take_along_axis(
            hidden, eot[:, None, None], axis=1
        ).squeeze(1)
        return {"hidden": hidden.astype(self.dtype),
                "pooled": pooled.astype(self.dtype),
                "penultimate": penultimate.astype(self.dtype)}
