"""Mixture-of-Experts MLP with expert parallelism (the ``ep`` mesh axis).

The reference has no MoE (it has no local models at all, SURVEY.md §2);
this supplies the expert-parallel rung of the build's mesh so the
framework's parallelism surface covers dp/tp/sp/pp/ep. Design is the
TPU-canonical Switch/GShard formulation — everything is dense einsums over
static shapes, so XLA can lay the expert dim out across the mesh:

- **router**: top-1 token→expert assignment with a fixed capacity
  ``C = capacity_factor · T / E`` per expert. Overflowing tokens fall
  through the residual (standard Switch behavior) — no dynamic shapes.
- **dispatch/combine** are one-hot einsums producing ``(E, C, D)``
  buffers; with the expert axis sharded ``P("ep")`` GSPMD turns the
  einsums into the all-to-all shuffles that ride ICI.
- **expert FFN**: batched (E, ·, ·) matmuls — every expert's GEMM runs
  concurrently on its own shard of the ``ep`` axis.

``MoEMLP`` drops in anywhere a TransformerMLP fits; ``expert_specs`` gives
the ``P("ep", ...)`` param specs for mesh placement.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MoEMLP(nn.Module):
    """Top-1 (Switch) routed MLP: x (B, S, D) -> (B, S, D)."""

    num_experts: int
    intermediate: int
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        e = self.num_experts
        t = b * s
        cap = max(1, int(self.capacity_factor * t / e))

        tokens = x.reshape(t, d)
        # router in fp32: small, and argmax stability matters
        gate_w = self.param(
            "router", nn.initializers.lecun_normal(), (d, e), jnp.float32
        )
        logits = tokens.astype(jnp.float32) @ gate_w          # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                   # (T,)
        gate = jnp.take_along_axis(
            probs, expert[:, None], axis=-1
        )[:, 0]                                               # (T,)

        # position of each token within its expert's capacity buffer
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.int32)   # (T, E)
        pos = jnp.cumsum(onehot, axis=0) * onehot             # 1-based
        pos = jnp.sum(pos, axis=-1) - 1                       # (T,)
        keep = pos < cap                                      # overflow drops

        # dispatch tensor (T, E, C): one-hot routing incl. capacity slot
        disp = (
            jax.nn.one_hot(expert, e, dtype=self.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1,
                             dtype=self.dtype)[:, None, :cap]
        )
        buf = jnp.einsum("td,tec->ecd", tokens.astype(self.dtype), disp)

        # expert FFN: batched GEMMs over the (sharded) expert axis
        w1 = self.param(
            "w1", nn.initializers.lecun_normal(),
            (e, d, self.intermediate), jnp.float32,
        ).astype(self.dtype)
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(),
            (e, self.intermediate, d), jnp.float32,
        ).astype(self.dtype)
        h = jnp.einsum("ecd,edf->ecf", buf, w1)
        h = nn.gelu(h)
        h = jnp.einsum("ecf,efd->ecd", h, w2)

        # combine: weight by the gate, scatter back to token order
        combine = disp * gate[:, None, None].astype(self.dtype)
        out = jnp.einsum("ecd,tec->td", h, combine)
        # aux load-balancing loss (Switch eq. 4), exposed as a sown value
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(
            jax.nn.one_hot(expert, e, dtype=jnp.float32), axis=0
        )
        self.sow("aux_loss", "load_balance", e * jnp.sum(me * ce))
        return out.reshape(b, s, d).astype(x.dtype)


def expert_specs(params) -> dict:
    """PartitionSpecs placing expert-stacked weights over ``ep``."""

    def spec_for(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        if name.endswith("w1") or name.endswith("w2"):
            return P("ep", None, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_moe_params(params, mesh: Mesh):
    """Place MoE params: experts over ``ep``, router replicated."""
    ep = int(mesh.shape.get("ep", 1))

    def place(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        spec = P()
        if (name.endswith("w1") or name.endswith("w2")) and \
                leaf.shape[0] % ep == 0:
            spec = P("ep", None, None)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


@functools.lru_cache(maxsize=32)
def _moe_jitted(model: MoEMLP, mesh: Mesh):
    """One compiled executable per (model config, mesh) — MoEMLP is a
    frozen dataclass and Mesh hashes by devices+axes, so both key the
    cache; a fresh closure per call would retrace every time."""
    batch_spec = P("dp") if "dp" in mesh.axis_names else P()

    @jax.jit
    def fn(p, x):
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, batch_spec)
        )
        return model.apply(p, x)

    return fn


def moe_sharded_apply(model: MoEMLP, params, x: jax.Array, mesh: Mesh):
    """MoE forward with expert-sharded params and batch-sharded
    activations; GSPMD inserts the dispatch/combine all-to-alls."""
    return _moe_jitted(model, mesh)(params, x)
