"""CLIP vision tower (ViT) — for the CLIP-similarity parity harness.

BASELINE.md's quality gate is "CLIP-similarity parity vs the CUDA
baseline": score each generated image against its prompt with CLIP and
compare distributions. That needs the image side of CLIP locally; this is
the standard ViT with class token, pre-LN blocks, and a projection to the
shared text-image embedding space. Weights load from transformers-style
safetensors (``convert_clip_vision``); random-init otherwise (the harness
then still validates plumbing, not quality).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.models.layers import (
    MultiHeadAttention,
    TransformerMLP,
    quick_gelu,
)


@dataclasses.dataclass(frozen=True)
class ClipVisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    projection_dim: int = 768
    dtype: str = "float32"

    @staticmethod
    def tiny() -> "ClipVisionConfig":
        return ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4,
            projection_dim=64,
        )


class ClipVisionBlock(nn.Module):
    cfg: ClipVisionConfig
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln1")(x)
        x = x + MultiHeadAttention(
            num_heads=self.cfg.num_heads, dtype=self.dtype,
            fused_qkv=True, name="attn"
        )(h)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln2")(x)
        x = x + TransformerMLP(
            intermediate=self.cfg.intermediate_size,
            activation=quick_gelu, dtype=self.dtype, name="mlp",
        )(h)
        return x


class ClipVisionEncoder(nn.Module):
    cfg: ClipVisionConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        """(B, H, W, 3) images normalized to CLIP stats -> (B, P) unit
        embeddings in the shared text-image space."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        b = images.shape[0]
        x = nn.Conv(
            cfg.hidden_size,
            (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            use_bias=False, dtype=dtype, name="patch_embed",
        )(images.astype(dtype))
        x = x.reshape(b, -1, cfg.hidden_size)
        cls = self.param(
            "class_embedding", nn.initializers.normal(0.02),
            (cfg.hidden_size,),
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)).astype(dtype), x],
            axis=1,
        )
        n_pos = x.shape[1]
        pos = self.param(
            "position_embedding", nn.initializers.normal(0.02),
            (n_pos, cfg.hidden_size),
        )
        x = x + pos[None].astype(dtype)
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="pre_ln")(x)
        for i in range(cfg.num_layers):
            x = ClipVisionBlock(cfg, dtype, name=f"block_{i}")(x)
        pooled = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="post_ln")(x[:, 0])
        proj = self.param(
            "projection", nn.initializers.normal(0.02),
            (cfg.hidden_size, cfg.projection_dim),
        )
        emb = pooled @ proj.astype(jnp.float32)
        return emb / (jnp.linalg.norm(emb, axis=-1, keepdims=True) + 1e-8)


CLIP_IMAGE_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_IMAGE_STD = (0.26862954, 0.26130258, 0.27577711)


def preprocess_for_clip(images_u8: jax.Array, size: int = 224) -> jax.Array:
    """uint8 (B, H, W, 3) -> resized, CLIP-normalized float32.

    Bicubic resize like the published CLIP eval transform (whose
    shortest-side-resize + center-crop equals a straight resize for the
    square images our pipelines emit)."""
    x = images_u8.astype(jnp.float32) / 255.0
    b, h, w, c = x.shape
    # clamp the cubic overshoot: the reference transform resizes uint8
    # (implicitly clamped) before normalizing
    x = jnp.clip(jax.image.resize(x, (b, size, size, c), "cubic"),
                 0.0, 1.0)
    mean = jnp.asarray(CLIP_IMAGE_MEAN)
    std = jnp.asarray(CLIP_IMAGE_STD)
    return (x - mean) / std
