from cassmantle_tpu.models.clip_text import ClipTextEncoder  # noqa: F401
from cassmantle_tpu.models.gpt2 import GPT2LM  # noqa: F401
from cassmantle_tpu.models.minilm import MiniLMEncoder  # noqa: F401
from cassmantle_tpu.models.unet import UNet  # noqa: F401
from cassmantle_tpu.models.vae import VAEDecoder, VAEEncoder  # noqa: F401
