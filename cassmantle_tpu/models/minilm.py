"""MiniLM-class sentence encoder for guess-similarity scoring.

Replaces the reference's CPU word2vec scorer (backend.py:45, 303-317;
artifact from download_model.py:9-10) with a BERT-style bidirectional
encoder + masked mean pooling + L2 normalization — the all-MiniLM-L6-v2
recipe — so guess/answer similarity is an embedding cosine computed in
batches on TPU (1k concurrent guesses coalesce into one device call,
BASELINE.json config #1).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.config import MiniLMConfig
from cassmantle_tpu.models.layers import (
    MultiHeadAttention,
    TransformerMLP,
    exact_gelu,
)


class BertBlock(nn.Module):
    """Post-LN transformer block (BERT convention)."""

    cfg: MiniLMConfig
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, mask):
        a = MultiHeadAttention(
            num_heads=self.cfg.num_heads, dtype=self.dtype,
            fused_qkv=True, name="attn"
        )(x, mask=mask)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, name="ln1")(x + a)
        # published BERT uses the EXACT (erf) gelu, not the tanh approx
        h = TransformerMLP(
            intermediate=self.cfg.intermediate_size, dtype=self.dtype,
            activation=exact_gelu,
            name="mlp",
        )(x)
        return nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, name="ln2")(x + h)


class MiniLMEncoder(nn.Module):
    cfg: MiniLMConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 attention_mask: jax.Array) -> jax.Array:
        """(B, S) ids + (B, S) 0/1 mask -> (B, D) unit-norm embeddings."""
        dtype = jnp.dtype(self.cfg.dtype)
        _, s = input_ids.shape
        x = nn.Embed(self.cfg.vocab_size, self.cfg.hidden_size,
                     dtype=dtype, name="word_embeddings")(input_ids)
        pos = self.param(
            "position_embeddings", nn.initializers.normal(0.02),
            (self.cfg.max_positions, self.cfg.hidden_size),
        )
        x = x + pos[None, :s].astype(dtype)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32, name="embed_ln")(x)

        attend = attention_mask.astype(bool)[:, None, None, :]
        for i in range(self.cfg.num_layers):
            x = BertBlock(self.cfg, dtype, name=f"block_{i}")(x, attend)

        # masked mean pooling
        weights = attention_mask.astype(jnp.float32)[..., None]
        pooled = (x.astype(jnp.float32) * weights).sum(axis=1) / (
            weights.sum(axis=1) + 1e-9
        )
        return pooled / (
            jnp.linalg.norm(pooled, axis=-1, keepdims=True) + 1e-9
        )
