"""Shared Flax building blocks for the model zoo.

TPU-first conventions used throughout:
- channels-last NHWC for all image tensors (XLA's native TPU conv layout);
- matmuls sized to MXU tiles (model dims are all multiples of 128 at
  production scale) and computed in the module dtype (bf16 on TPU) with
  fp32 softmax/normalization accumulations;
- attention goes through ops.attention so the Pallas flash kernel applies
  everywhere at once.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.ops.attention import multi_head_attention


def timestep_embedding(
    timesteps: jax.Array, dim: int, max_period: float = 10000.0
) -> jax.Array:
    """Sinusoidal diffusion-timestep embedding, fp32. (B,) -> (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class MultiHeadAttention(nn.Module):
    """Projection + ops.attention + out-projection.

    Self-attention when ``context`` is None, cross-attention otherwise.
    """

    num_heads: int
    head_dim: Optional[int] = None
    out_dim: Optional[int] = None
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context=None, mask=None, kv_cache=None,
                 return_kv: bool = False):
        """Attention with optional KV-cache decode.

        - Full mode: returns out, or (out, (k, v)) if ``return_kv`` (used by
          prefill to seed a decode cache).
        - Decode mode (``kv_cache=(cache_k, cache_v, index)``): writes this
          call's k/v into the cache at ``index`` along the sequence axis and
          attends over the whole cache; the caller supplies ``mask`` marking
          valid cache positions. Returns (out, (new_k, new_v)).
        """
        features = x.shape[-1]
        head_dim = self.head_dim or features // self.num_heads
        inner = self.num_heads * head_dim
        out_dim = self.out_dim or features
        ctx = x if context is None else context

        dense = lambda name: nn.Dense(  # noqa: E731
            inner, use_bias=self.use_bias, dtype=self.dtype, name=name
        )
        q = dense("q")(x)
        k = dense("k")(ctx)
        v = dense("v")(ctx)

        split = lambda t: t.reshape(  # noqa: E731
            t.shape[:-1] + (self.num_heads, head_dim)
        )
        q, k, v = split(q), split(k), split(v)

        kv_out = None
        if kv_cache is not None:
            cache_k, cache_v, index = kv_cache
            cache_k = jax.lax.dynamic_update_slice_in_dim(
                cache_k, k.astype(cache_k.dtype), index, axis=-3
            )
            cache_v = jax.lax.dynamic_update_slice_in_dim(
                cache_v, v.astype(cache_v.dtype), index, axis=-3
            )
            k, v = cache_k, cache_v
            kv_out = (cache_k, cache_v)
        elif return_kv:
            kv_out = (k, v)

        out = multi_head_attention(q, k, v, mask=mask)
        out = out.reshape(out.shape[:-2] + (inner,))
        out = nn.Dense(
            out_dim, use_bias=self.use_bias, dtype=self.dtype, name="out"
        )(out)
        if kv_out is not None:
            return out, kv_out
        return out


class TransformerMLP(nn.Module):
    """Standard 2-layer MLP with configurable activation."""

    intermediate: int
    activation: Callable = nn.gelu
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        h = nn.Dense(self.intermediate, dtype=self.dtype, name="fc1")(x)
        h = self.activation(h)
        return nn.Dense(features, dtype=self.dtype, name="fc2")(h)


class GEGLU(nn.Module):
    """Gated-GELU feed-forward used by SD's transformer blocks."""

    intermediate: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        h = nn.Dense(self.intermediate * 2, dtype=self.dtype, name="proj")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gate)
        return nn.Dense(features, dtype=self.dtype, name="out")(h)


def quick_gelu(x):
    """CLIP's activation: x * sigmoid(1.702 x)."""
    return x * jax.nn.sigmoid(1.702 * x)


class GroupNorm32(nn.Module):
    """GroupNorm computed in fp32 regardless of module dtype (diffusion
    UNets are numerically sensitive here)."""

    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        out = nn.GroupNorm(
            num_groups=self.num_groups, epsilon=self.epsilon,
            dtype=jnp.float32, name="norm",
        )(x.astype(jnp.float32))
        return out.astype(orig_dtype)
