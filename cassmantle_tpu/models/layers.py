"""Shared Flax building blocks for the model zoo.

TPU-first conventions used throughout:
- channels-last NHWC for all image tensors (XLA's native TPU conv layout);
- matmuls sized to MXU tiles (model dims are all multiples of 128 at
  production scale) and computed in the module dtype (bf16 on TPU) with
  fp32 softmax/normalization accumulations;
- attention goes through ops.attention so the Pallas flash kernel applies
  everywhere at once.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cassmantle_tpu.ops import quant
from cassmantle_tpu.ops.attention import multi_head_attention


def nearest_upsample_2x(x: jax.Array) -> jax.Array:
    """2x nearest-neighbor upsample via broadcast+reshape (pure data
    movement XLA fuses well; jax.image.resize lowers to gathers, which
    the TPU executes much more slowly)."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, h * 2, w * 2, c)


def timestep_embedding(
    timesteps: jax.Array, dim: int, max_period: float = 10000.0
) -> jax.Array:
    """Sinusoidal diffusion-timestep embedding, fp32. (B,) -> (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


def chunk_causal_mask(valid: jax.Array, index: jax.Array, length: int,
                      window: Optional[int] = None) -> jax.Array:
    """Causal mask for a multi-token decode chunk appended at ``index``.

    ``valid`` (B, max_len) is the caller's cache-validity mask (the same
    convention single-token ``decode_step`` takes, covering the prompt
    and every chunk position); query j of the chunk sits at cache
    position ``index + j`` and may additionally attend only positions
    ``<= index + j`` — the within-chunk causal triangle a single-step
    decode gets for free. With ``window`` the Mistral sliding band is
    enforced per query on top. Returns (B, 1, length, max_len), ready
    for the attention op's (B, H, Sq, Sk) broadcast.

    This is the one definition of the chunk-mask convention the
    speculative-decode verify forward (ops/decode.py) relies on: cache
    positions past the accepted prefix are *rolled back* simply by the
    next chunk's ``valid`` excluding them before the kv chunk-append
    overwrites them.
    """
    max_len = valid.shape[-1]
    cache_pos = jnp.arange(max_len)
    q_pos = index + jnp.arange(length)
    ok = cache_pos[None, :] <= q_pos[:, None]            # (length, max_len)
    if window is not None:
        ok = ok & (cache_pos[None, :] > q_pos[:, None] - window)
    return valid[:, None, None, :] & ok[None, None, :, :]


class QDense(nn.Module):
    """Param-twin of ``nn.Dense`` whose kernel leaf may be quantized.

    Declares kernel/bias with nn.Dense's exact names, shapes,
    initializers, and RNG fold path, so checkpoints, the init cache, and
    every converter see one tree. At apply time it branches on the leaf:

    - plain array → nn.Dense's exact computation (same promote_dtype +
      dot_general + bias reshape), bit-identical to the module it
      replaces — which is what lets the w8a8 kill switch revert
      bit-exactly by simply not quantizing at load;
    - :class:`~cassmantle_tpu.ops.quant.ActQTensor` (the W8A8 serving
      tree, ops/quant.py ``w8a8_tree_host``) → the int8 Pallas matmul
      with scales folded into the int32→fp epilogue
      (ops/quant_matmul.py ``w8a8_dense``), per-token activation scales
      when ``act_per_token`` (the LM decode path).

    Also the calibration tap: when a ``collect_act_stats`` pass is
    active (eager, parallel/calibrate.py) it records this site's input
    absmax under its flax path — zero traced ops otherwise.

    Used at every w8a8-capable site (attention projections, transformer
    MLPs, GEGLU); plain ``nn.Dense`` remains at quality-sensitive or
    tiny sites (time embeds, heads, proj_in/out), which the w8a8
    predicate whitelist (ops/quant.py) therefore must never select.
    """

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    act_per_token: bool = False

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,)) if self.use_bias else None
        if quant.act_stats_active():
            quant.note_act_stat("/".join(self.path), x)
        if isinstance(kernel, quant.ActQTensor):
            from cassmantle_tpu.ops.quant_matmul import w8a8_dense

            return w8a8_dense(x, kernel, bias,
                              out_dtype=self.dtype or x.dtype,
                              per_token=self.act_per_token)
        from flax.linen.dtypes import promote_dtype

        x, kernel, bias = promote_dtype(x, kernel, bias,
                                        dtype=self.dtype)
        y = jax.lax.dot_general(
            x, kernel, (((x.ndim - 1,), (0,)), ((), ())))
        if bias is not None:
            y = y + jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
        return y


class MultiHeadAttention(nn.Module):
    """Projection + ops.attention + out-projection.

    Self-attention when ``context`` is None, cross-attention otherwise.
    """

    num_heads: int
    head_dim: Optional[int] = None
    out_dim: Optional[int] = None
    use_bias: bool = True
    # The published SD UNet (data/manifests/unet_*.json) is bias-free on
    # to_q/to_k/to_v but carries a bias on to_out.0 — the two knobs must
    # be independent or real weights can't load faithfully. None -> same
    # as use_bias.
    out_bias: Optional[bool] = None
    # Fuse q/k/v (self-attn) or k/v (cross-attn) into one projection
    # dot — full-forward sites only (UNet); incompatible with the
    # kv-cache decode path, which updates k/v separately.
    fused_qkv: bool = False
    # W8A8 activation-scale granularity for the projection QDenses:
    # per-token on the LM path (models/gpt2.py), per-tensor elsewhere.
    act_per_token: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context=None, mask=None, kv_cache=None,
                 return_kv: bool = False, causal: bool = False):
        """Attention with optional KV-cache decode.

        - Full mode: returns out, or (out, (k, v)) if ``return_kv`` (used by
          prefill to seed a decode cache).
        - Decode mode (``kv_cache=(cache_k, cache_v, index)``): writes this
          call's k/v into the cache at ``index`` along the sequence axis and
          attends over the whole cache; the caller supplies ``mask`` marking
          valid cache positions. Returns (out, (new_k, new_v)). The write
          is a chunk-append: ``x`` may carry S > 1 positions (speculative
          verify, ops/decode.py) and the S-wide k/v slab lands at
          ``index..index+S-1`` in one ``dynamic_update_slice`` — the caller
          then owes a per-query causal mask (``chunk_causal_mask``), since
          with S > 1 a plain validity mask would let early chunk positions
          see later ones.
        """
        features = x.shape[-1]
        head_dim = self.head_dim or features // self.num_heads
        inner = self.num_heads * head_dim
        out_dim = self.out_dim or features
        ctx = x if context is None else context

        dense = lambda name, mult=1: QDense(  # noqa: E731
            mult * inner, use_bias=self.use_bias, dtype=self.dtype,
            name=name, act_per_token=self.act_per_token
        )
        if self.fused_qkv:
            # One projection dot instead of three: the input activation
            # streams from HBM once (the q/k/v kernels read the same x),
            # and the MXU sees one (M, C)x(C, 3C) matmul whose wider N
            # pads the 128-lane tile boundary once, not three times —
            # the optimization the UNet cost table indicates
            # (docs/PERF_NOTES.md): projection dots are ~17% of UNet
            # FLOPs across 32 attention sites. Checkpoint layout is
            # unchanged — the converters concatenate the published
            # to_q/to_k/to_v tensors at load (weights.py dense_fused).
            assert kv_cache is None and not return_kv, (
                "fused_qkv is a full-forward optimization; decode "
                "caching uses the separate-projection layout")
            if context is None:
                q, k, v = jnp.split(dense("qkv", 3)(x), 3, axis=-1)
            else:
                q = dense("q")(x)
                k, v = jnp.split(dense("kv", 2)(ctx), 2, axis=-1)
        else:
            q = dense("q")(x)
            k = dense("k")(ctx)
            v = dense("v")(ctx)

        split = lambda t: t.reshape(  # noqa: E731
            t.shape[:-1] + (self.num_heads, head_dim)
        )
        q, k, v = split(q), split(k), split(v)

        kv_out = None
        if kv_cache is not None:
            cache_k, cache_v, index = kv_cache
            cache_k = jax.lax.dynamic_update_slice_in_dim(
                cache_k, k.astype(cache_k.dtype), index, axis=-3
            )
            cache_v = jax.lax.dynamic_update_slice_in_dim(
                cache_v, v.astype(cache_v.dtype), index, axis=-3
            )
            k, v = cache_k, cache_v
            kv_out = (cache_k, cache_v)
        elif return_kv:
            kv_out = (k, v)

        out = multi_head_attention(q, k, v, mask=mask, causal=causal)
        out = out.reshape(out.shape[:-2] + (inner,))
        out = QDense(
            out_dim,
            use_bias=(self.use_bias if self.out_bias is None
                      else self.out_bias),
            dtype=self.dtype, name="out",
            act_per_token=self.act_per_token,
        )(out)
        if kv_out is not None:
            return out, kv_out
        return out


class TransformerMLP(nn.Module):
    """Standard 2-layer MLP with configurable activation."""

    intermediate: int
    activation: Callable = nn.gelu
    act_per_token: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        h = QDense(self.intermediate, dtype=self.dtype, name="fc1",
                   act_per_token=self.act_per_token)(x)
        h = self.activation(h)
        return QDense(features, dtype=self.dtype, name="fc2",
                      act_per_token=self.act_per_token)(h)


class GEGLU(nn.Module):
    """Gated-GELU feed-forward used by SD's transformer blocks."""

    intermediate: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        h = QDense(self.intermediate * 2, dtype=self.dtype, name="proj")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gate)
        return QDense(features, dtype=self.dtype, name="out")(h)


def quick_gelu(x):
    """OpenAI CLIP's activation: x * sigmoid(1.702 x)."""
    return x * jax.nn.sigmoid(1.702 * x)


def exact_gelu(x):
    """Erf-based GELU — the published BERT and OpenCLIP-bigG activation
    (jax.nn.gelu defaults to the tanh approximation, which is GPT-2's
    gelu_new but NOT what those checkpoints were trained with)."""
    return jax.nn.gelu(x, approximate=False)


class LayerNorm32(nn.Module):
    """LayerNorm with fp32 statistics applied in the activation dtype.

    ``nn.LayerNorm(dtype=fp32)`` on a bf16 tensor casts the whole tensor
    up and back, doubling elementwise HBM traffic per norm — with 3 norms
    per transformer block this is real money on the UNet's token tensors.
    Stats (mean/var) reduce in fp32; the affine applies as one FMA in the
    input dtype. Param layout matches nn.LayerNorm (scale/bias (C,)).
    """

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True) \
            - jnp.square(mean)
        inv = jax.lax.rsqrt(var + self.epsilon)
        scale32 = scale.astype(jnp.float32)
        a = (inv * scale32).astype(x.dtype)
        b = (bias.astype(jnp.float32) - (mean * inv) * scale32
             ).astype(x.dtype)
        return x * a + b


class _GroupNormCore(nn.Module):
    """GroupNorm with fp32 statistics and activation-dtype application.

    The straightforward ``cast-to-fp32 -> nn.GroupNorm -> cast-back``
    doubles elementwise HBM traffic on the UNet's biggest tensors and the
    cast boundaries block XLA fusion; at SD1.5-512 the UNet step is
    memory-bound (23 GB accessed/step), so this matters. Here only the
    mean/var *reductions* run in fp32; the normalize folds into one
    multiply-add applied in the input dtype:

        out = x * a + b,  a = inv*scale,  b = bias - mean*inv*scale

    with ``a``/``b`` computed in fp32 at (B, G|C) size — numerically the
    sensitive part — then cast once. Param layout matches nn.GroupNorm
    (scale/bias of shape (C,)) so checkpoints load unchanged.
    """

    num_groups: int
    epsilon: float

    @nn.compact
    def __call__(self, x, return_affine: bool = False):
        """Normalize ``x`` — or, with ``return_affine``, return the
        per-(batch, channel) fp32 affine ``(a, b)`` with
        ``out = x * a + b`` instead of applying it. The affine form
        feeds the fused GroupNorm+SiLU+conv3x3 Pallas path
        (ops/fused_conv.py): the sensitive fp32 statistics stay here,
        the cheap FMA moves into the kernel."""
        c = x.shape[-1]
        g = self.num_groups
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))

        spatial = x.shape[1:-1]
        # Reduce in the tensor's native channels-last layout: per-channel
        # sum and sum-of-squares over the spatial axis — the minor (lane)
        # dimension stays C (a few hundred, tiles well), not C/G (10-80,
        # which pads each 128-lane vector op mostly empty). The tiny
        # (B, C) moments then fold into (B, G) group stats exactly
        # (groups are equal-sized, so the group mean is the mean of its
        # channel means).
        x2 = x.reshape(x.shape[0], -1, c).astype(jnp.float32)
        n_spatial = x2.shape[1]
        sum_c = jnp.sum(x2, axis=1)                          # (B, C)
        sumsq_c = jnp.sum(jnp.square(x2), axis=1)            # (B, C)
        n_group = n_spatial * (c // g)
        mean = jnp.sum(sum_c.reshape(-1, g, c // g), axis=-1) / n_group
        ex2 = jnp.sum(sumsq_c.reshape(-1, g, c // g), axis=-1) / n_group
        var = ex2 - jnp.square(mean)                         # (B, G)
        inv = jax.lax.rsqrt(var + self.epsilon)              # (B, G)

        # per-(batch, channel) affine in fp32, one cast, one fused FMA
        inv_c = jnp.repeat(inv, c // g, axis=-1)             # (B, C)
        mean_c = jnp.repeat(mean, c // g, axis=-1)
        a = inv_c * scale.astype(jnp.float32)[None, :]
        b = bias.astype(jnp.float32)[None, :] - mean_c * a
        if return_affine:
            return a, b                                      # (B, C) fp32
        shape = (x.shape[0],) + (1,) * len(spatial) + (c,)
        a = a.reshape(shape).astype(x.dtype)
        b = b.reshape(shape).astype(x.dtype)
        return x * a + b


class GroupNorm32(nn.Module):
    """GroupNorm with fp32 statistics (diffusion UNets are numerically
    sensitive here) applied in the activation dtype — see _GroupNormCore.
    Nests the core under ``norm`` to keep the nn.GroupNorm param paths."""

    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, return_affine: bool = False):
        return _GroupNormCore(
            num_groups=self.num_groups, epsilon=self.epsilon, name="norm"
        )(x, return_affine=return_affine)


def fused_gn_silu_conv3x3(x, out_channels: int, dtype,
                          norm_name: str, conv_name: str,
                          epsilon: float = 1e-5, pad_to: int = 0):
    """The fused-conv dispatch glue shared by the UNet and VAE
    ResBlocks: fp32 GroupNorm statistics here (``return_affine``),
    param declaration via :class:`Conv3x3Params` (nn.Conv's exact
    tree), then the one-pass GN-affine+SiLU+conv3x3 Pallas kernel
    (ops/fused_conv.py). Must be called inside the parent module's
    ``@nn.compact`` ``__call__`` — the explicit submodule names keep
    the param paths identical to the unfused ``GroupNorm32``/
    ``nn.Conv`` layout. ``epsilon`` is the GroupNorm epsilon (UNet
    resblocks 1e-5, VAE 1e-6); ``pad_to`` the MXU channel padding."""
    from cassmantle_tpu.ops.fused_conv import gn_silu_conv3x3

    a, b = GroupNorm32(epsilon=epsilon, name=norm_name)(
        x, return_affine=True)
    act_stat_of = None
    if quant.act_stats_active():
        # calibration probe: the conv's actual input is silu(x*a+b),
        # which only the kernel normally materializes — reproduce it
        # lazily here (eager calibration pass only; never traced)
        act_stat_of = lambda: jax.nn.silu(  # noqa: E731
            x * a[:, None, None, :].astype(x.dtype)
            + b[:, None, None, :].astype(x.dtype))
    kernel, bias = Conv3x3Params(out_channels, name=conv_name)(
        x.shape[-1], act_stat_of=act_stat_of)
    if isinstance(kernel, quant.ActQTensor):
        from cassmantle_tpu.ops.quant_matmul import gn_silu_conv3x3_w8a8

        return gn_silu_conv3x3_w8a8(x, a, b, kernel, bias,
                                    pad_to=pad_to)
    return gn_silu_conv3x3(x, a, b, kernel.astype(dtype),
                           bias.astype(dtype), pad_to=pad_to)


class Conv3x3Params(nn.Module):
    """Parameter twin of ``nn.Conv(features, (3, 3))`` that DECLARES the
    kernel/bias without running the convolution.

    The fused GroupNorm+SiLU+conv path (ops/fused_conv.py) computes the
    conv inside a Pallas kernel, but the param tree must stay identical
    to the unfused ``nn.Conv`` layout — same names ("kernel"/"bias"),
    same HWIO shape, same initializers, same RNG fold path — so
    checkpoints (models/weights.py Converter.conv), the init cache, and
    the fused/unfused A/B all share one tree. Returns the raw params;
    dtype casting happens at the use site like ``nn.Conv(dtype=...)``.
    """

    features: int

    @nn.compact
    def __call__(self, in_features: int, act_stat_of=None):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, in_features, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        if act_stat_of is not None and quant.act_stats_active():
            # w8a8 calibration tap (ops/quant.py): records this site's
            # conv-input absmax under the module path — the same key
            # the w8a8 tree transform looks up
            quant.note_act_stat("/".join(self.path), act_stat_of())
        return kernel, bias
