"""Checkpoint loading: safetensors (torch naming) -> Flax param trees.

The reference fetches its "weights" by pointing at hosted HF endpoints
(backend.py:24-25) plus a one-shot gensim artifact download
(download_model.py:9-10). Here model weights are first-class: each model in
the zoo has a converter mapping the published safetensors naming (diffusers
for UNet/VAE, transformers for CLIP/GPT-2/BERT-MiniLM) onto our module tree,
with layout fixes (torch conv OIHW -> flax HWIO, linear (out,in) ->
(in,out)). When no checkpoint is on disk, ``init_params`` gives
deterministic random params (fixed PRNG) so the full pipeline runs — shapes,
jit, sharding, and benchmarks are weight-independent.

Conversion fidelity is SURVEY.md §7 hard part (a); converters are exercised
by tests that fabricate synthetic torch-layout checkpoints and assert
numerical equality after mapping.
"""

from __future__ import annotations

import fnmatch
import os
import re
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.utils.logging import get_logger

log = get_logger("weights")

Tensors = Dict[str, np.ndarray]


def load_safetensors(path: str) -> Tensors:
    from safetensors import numpy as st_numpy

    return dict(st_numpy.load_file(path))


def _t(w: np.ndarray) -> np.ndarray:
    """torch linear (out, in) -> flax dense kernel (in, out)."""
    return np.ascontiguousarray(w.T)


def _conv(w: np.ndarray) -> np.ndarray:
    """torch conv OIHW -> flax HWIO."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _conv1x1_to_dense(w: np.ndarray) -> np.ndarray:
    """torch 1x1 conv (O, I, 1, 1) -> dense kernel (I, O)."""
    return np.ascontiguousarray(w[:, :, 0, 0].T)


def set_in_tree(tree: dict, path: str, value: np.ndarray) -> None:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


class Converter:
    """Accumulates {flax_path: array} then materializes a param tree.

    ``ignore``: fnmatch patterns for source keys expected to go unused —
    the OTHER tower of a full CLIPModel checkpoint, buffers persisted by
    older library versions (``embeddings.position_ids``, GPT-2's causal
    mask), the encoder half of a VAE file feeding the decoder converter.
    Each converter's patterns are mirrored in its checkpoint manifest
    (data/manifests/, tools/make_manifests.py), and the manifest tests
    require consume-or-ignore to cover the authentic inventory exactly;
    at load time they keep the unused-tensors warning from firing
    spuriously and drowning genuine missing-tensor signals."""

    def __init__(self, tensors: Tensors, model_name: str,
                 ignore: tuple = ()) -> None:
        self.src = tensors
        self.model_name = model_name
        self.ignore = ignore
        self.out: Dict[str, np.ndarray] = {}
        self.used = set()

    def take(self, key: str) -> np.ndarray:
        self.used.add(key)
        return self.src[key]

    def has(self, key: str) -> bool:
        return key in self.src

    def put(self, path: str, value: np.ndarray) -> None:
        self.out[path] = value

    def dense(self, src: str, dst: str) -> None:
        self.put(f"{dst}/kernel", _t(self.take(f"{src}.weight")))
        if self.has(f"{src}.bias"):
            self.put(f"{dst}/bias", self.take(f"{src}.bias"))

    def dense_fused(self, srcs, dst: str) -> None:
        """Concatenate several published projections into ONE Dense
        (kernel axis 1 = output features): the load-time half of the
        fused-QKV optimization (layers.MultiHeadAttention fused_qkv) —
        checkpoints keep their authentic separate to_q/to_k/to_v
        tensors; the in-memory tree holds them as one matmul."""
        kernels = [_t(self.take(f"{s}.weight")) for s in srcs]
        self.put(f"{dst}/kernel", np.concatenate(kernels, axis=1))
        if self.has(f"{srcs[0]}.bias"):
            self.put(f"{dst}/bias", np.concatenate(
                [self.take(f"{s}.bias") for s in srcs], axis=0))

    def conv(self, src: str, dst: str) -> None:
        self.put(f"{dst}/kernel", _conv(self.take(f"{src}.weight")))
        if self.has(f"{src}.bias"):
            self.put(f"{dst}/bias", self.take(f"{src}.bias"))

    def conv1x1_dense(self, src: str, dst: str) -> None:
        w = self.take(f"{src}.weight")
        if w.ndim == 4:
            self.put(f"{dst}/kernel", _conv1x1_to_dense(w))
        else:
            self.put(f"{dst}/kernel", _t(w))
        if self.has(f"{src}.bias"):
            self.put(f"{dst}/bias", self.take(f"{src}.bias"))

    def norm(self, src: str, dst: str) -> None:
        self.put(f"{dst}/scale", self.take(f"{src}.weight"))
        self.put(f"{dst}/bias", self.take(f"{src}.bias"))

    def groupnorm(self, src: str, dst: str) -> None:
        # GroupNorm32 nests an nn.GroupNorm called "norm"
        self.norm(src, f"{dst}/norm")

    def embed(self, src: str, dst: str) -> None:
        self.put(f"{dst}/embedding", self.take(f"{src}.weight"))

    def ignored(self, key: str) -> bool:
        return any(fnmatch.fnmatchcase(key, p) for p in self.ignore)

    def tree(self) -> dict:
        n_ignored = 0
        unused = set()
        for k in set(self.src) - self.used:
            if self.ignored(k):
                n_ignored += 1
            else:
                unused.add(k)
        # per-stage key-match coverage: the one-line audit trail that a
        # real-weights boot actually consumed its checkpoint (a silent
        # partial match is how a boot degrades to random init unnoticed)
        log.info("%s: consumed %d/%d checkpoint tensors "
                 "(%d ignored-by-design) -> %d param arrays",
                 self.model_name, len(self.used), len(self.src),
                 n_ignored, len(self.out))
        if unused:
            log.warning("%s: %d source tensors unused (e.g. %s)",
                        self.model_name, len(unused),
                        sorted(unused)[:3])
        tree: dict = {}
        for path, value in self.out.items():
            set_in_tree(tree, path, value)
        return {"params": tree}


# ---------------------------------------------------------------------------
# CLIP text encoder (transformers naming, prefix "text_model.")
# ---------------------------------------------------------------------------

# A full CLIPModel checkpoint carries both towers + projections; each
# single-tower converter expects the other side's tensors to go unused.
# position_ids: arange buffers persisted by the save-era transformers
# (<4.31) — present in the published files, carried as "optional" in
# data/manifests/clip_full.json.
_CLIP_FULL_EXTRAS = ("logit_scale", "*.embeddings.position_ids")


def convert_clip_text(tensors: Tensors, num_layers: int) -> dict:
    c = Converter(tensors, "clip_text", ignore=(
        "vision_model.*", "visual_projection.*", "text_projection.*",
    ) + _CLIP_FULL_EXTRAS)
    p = "text_model."
    c.embed(f"{p}embeddings.token_embedding", "token_embedding")
    c.put("position_embedding",
          c.take(f"{p}embeddings.position_embedding.weight"))
    for i in range(num_layers):
        src = f"{p}encoder.layers.{i}"
        dst = f"block_{i}"
        c.norm(f"{src}.layer_norm1", f"{dst}/ln1")
        c.dense_fused((f"{src}.self_attn.q_proj",
                       f"{src}.self_attn.k_proj",
                       f"{src}.self_attn.v_proj"), f"{dst}/attn/qkv")
        c.dense(f"{src}.self_attn.out_proj", f"{dst}/attn/out")
        c.norm(f"{src}.layer_norm2", f"{dst}/ln2")
        c.dense(f"{src}.mlp.fc1", f"{dst}/mlp/fc1")
        c.dense(f"{src}.mlp.fc2", f"{dst}/mlp/fc2")
    c.norm(f"{p}final_layer_norm", "ln_final")
    return c.tree()


def convert_clip_vision(tensors: Tensors, num_layers: int) -> dict:
    """CLIP vision tower (transformers CLIPModel naming, prefix
    "vision_model.") -> ClipVisionEncoder tree. The SAME full-model
    checkpoint that feeds convert_clip_text carries these tensors plus
    ``visual_projection`` — the parity harness (eval/clip_parity.py)
    loads both towers from one file. Mirrors the reference's image-side
    quality check role (/root/reference/src/backend.py:270-295 trusts a
    hosted SDXL endpoint; we score images against prompts locally)."""
    c = Converter(tensors, "clip_vision", ignore=(
        "text_model.*", "text_projection.*",
    ) + _CLIP_FULL_EXTRAS)
    p = "vision_model."
    c.put("class_embedding", c.take(f"{p}embeddings.class_embedding"))
    c.put("position_embedding",
          c.take(f"{p}embeddings.position_embedding.weight"))
    c.put("patch_embed/kernel",
          _conv(c.take(f"{p}embeddings.patch_embedding.weight")))
    # transformers ships this layer under a historically typo'd name
    # ("pre_layrnorm"); accept the corrected spelling too
    pre = (f"{p}pre_layrnorm" if c.has(f"{p}pre_layrnorm.weight")
           else f"{p}pre_layernorm")
    c.norm(pre, "pre_ln")
    for i in range(num_layers):
        src = f"{p}encoder.layers.{i}"
        dst = f"block_{i}"
        c.norm(f"{src}.layer_norm1", f"{dst}/ln1")
        c.dense_fused((f"{src}.self_attn.q_proj",
                       f"{src}.self_attn.k_proj",
                       f"{src}.self_attn.v_proj"), f"{dst}/attn/qkv")
        c.dense(f"{src}.self_attn.out_proj", f"{dst}/attn/out")
        c.norm(f"{src}.layer_norm2", f"{dst}/ln2")
        c.dense(f"{src}.mlp.fc1", f"{dst}/mlp/fc1")
        c.dense(f"{src}.mlp.fc2", f"{dst}/mlp/fc2")
    c.norm(f"{p}post_layernorm", "post_ln")
    c.put("projection", _t(c.take("visual_projection.weight")))
    return c.tree()


def convert_clip_text_projection(tensors: Tensors) -> np.ndarray:
    """(hidden, projection_dim) text->shared-space matrix from the full
    CLIPModel checkpoint (torch stores it (out, in))."""
    return _t(tensors["text_projection.weight"])


# ---------------------------------------------------------------------------
# GPT-2 (transformers naming; Conv1D stores (in, out) -> no transpose)
# ---------------------------------------------------------------------------

def convert_gpt2(tensors: Tensors, num_layers: int, hidden: int) -> dict:
    # the published gpt2 file persists the (re-derivable) causal-mask
    # buffers of its save era (data/manifests/gpt2.json "optional")
    c = Converter(tensors, "gpt2", ignore=(
        "h.*.attn.bias", "h.*.attn.masked_bias"))

    def conv1d(src: str, dst: str) -> None:
        c.put(f"{dst}/kernel", c.take(f"{src}.weight"))
        c.put(f"{dst}/bias", c.take(f"{src}.bias"))

    c.embed("wte", "wte")
    c.embed("wpe", "wpe")
    for i in range(num_layers):
        src, dst = f"h.{i}", f"block_{i}"
        c.norm(f"{src}.ln_1", f"{dst}/ln1")
        qkv_w = c.take(f"{src}.attn.c_attn.weight")  # (in, 3*hidden)
        qkv_b = c.take(f"{src}.attn.c_attn.bias")
        for j, name in enumerate(("q", "k", "v")):
            c.put(f"{dst}/attn/{name}/kernel",
                  qkv_w[:, j * hidden:(j + 1) * hidden])
            c.put(f"{dst}/attn/{name}/bias",
                  qkv_b[j * hidden:(j + 1) * hidden])
        conv1d(f"{src}.attn.c_proj", f"{dst}/attn/out")
        c.norm(f"{src}.ln_2", f"{dst}/ln2")
        conv1d(f"{src}.mlp.c_fc", f"{dst}/mlp/fc1")
        conv1d(f"{src}.mlp.c_proj", f"{dst}/mlp/fc2")
    c.norm("ln_f", "ln_f")
    return c.tree()


# ---------------------------------------------------------------------------
# Mistral (transformers Llama-family naming: model.layers.N.*)
# ---------------------------------------------------------------------------

def convert_mistral(tensors: Tensors, num_layers: int) -> dict:
    """Mistral-7B-Instruct safetensors -> models/mistral.py tree.

    RMSNorm has scale only (no bias); all projections are bias-free.
    """
    # some save eras persist per-layer RoPE tables (manifest "optional")
    c = Converter(tensors, "mistral", ignore=(
        "model.layers.*.self_attn.rotary_emb.inv_freq",))

    def rmsnorm(src: str, dst: str) -> None:
        c.put(f"{dst}/scale", c.take(f"{src}.weight"))

    c.embed("model.embed_tokens", "embed")
    for i in range(num_layers):
        src, dst = f"model.layers.{i}", f"block_{i}"
        rmsnorm(f"{src}.input_layernorm", f"{dst}/ln1")
        c.dense(f"{src}.self_attn.q_proj", f"{dst}/attn/q")
        c.dense(f"{src}.self_attn.k_proj", f"{dst}/attn/k")
        c.dense(f"{src}.self_attn.v_proj", f"{dst}/attn/v")
        c.dense(f"{src}.self_attn.o_proj", f"{dst}/attn/out")
        rmsnorm(f"{src}.post_attention_layernorm", f"{dst}/ln2")
        c.dense(f"{src}.mlp.gate_proj", f"{dst}/mlp/gate")
        c.dense(f"{src}.mlp.up_proj", f"{dst}/mlp/up")
        c.dense(f"{src}.mlp.down_proj", f"{dst}/mlp/down")
    rmsnorm("model.norm", "ln_f")
    if c.has("lm_head.weight"):
        c.dense("lm_head", "lm_head")
    else:  # tied-embedding checkpoints
        c.put("lm_head/kernel", _t(c.take("model.embed_tokens.weight")))
    return c.tree()


# ---------------------------------------------------------------------------
# MiniLM / BERT encoder (sentence-transformers all-MiniLM-L6-v2 naming)
# ---------------------------------------------------------------------------

def convert_minilm(tensors: Tensors, num_layers: int) -> dict:
    # pooler: BertModel ships one, sentence-embedding scoring (mean
    # pooling, ops/scorer.py) never runs it; position_ids: persisted
    # buffer of the save era (data/manifests/minilm.json "optional")
    c = Converter(tensors, "minilm", ignore=(
        "pooler.*", "embeddings.position_ids"))
    c.embed("embeddings.word_embeddings", "word_embeddings")
    pos = c.take("embeddings.position_embeddings.weight")
    if c.has("embeddings.token_type_embeddings.weight"):
        # token_type_ids are all zero at inference -> fold type-0 row into
        # the position table (exactly equivalent pre-LayerNorm sum).
        pos = pos + c.take("embeddings.token_type_embeddings.weight")[0]
    c.put("position_embeddings", pos)
    c.norm("embeddings.LayerNorm", "embed_ln")
    for i in range(num_layers):
        src = f"encoder.layer.{i}"
        dst = f"block_{i}"
        c.dense_fused((f"{src}.attention.self.query",
                       f"{src}.attention.self.key",
                       f"{src}.attention.self.value"), f"{dst}/attn/qkv")
        c.dense(f"{src}.attention.output.dense", f"{dst}/attn/out")
        c.norm(f"{src}.attention.output.LayerNorm", f"{dst}/ln1")
        c.dense(f"{src}.intermediate.dense", f"{dst}/mlp/fc1")
        c.dense(f"{src}.output.dense", f"{dst}/mlp/fc2")
        c.norm(f"{src}.output.LayerNorm", f"{dst}/ln2")
    return c.tree()


# ---------------------------------------------------------------------------
# SD UNet (diffusers naming)
# ---------------------------------------------------------------------------

def _convert_resblock(c: Converter, src: str, dst: str) -> None:
    c.groupnorm(f"{src}.norm1", f"{dst}/norm1")
    c.conv(f"{src}.conv1", f"{dst}/conv1")
    c.dense(f"{src}.time_emb_proj", f"{dst}/time_proj")
    c.groupnorm(f"{src}.norm2", f"{dst}/norm2")
    c.conv(f"{src}.conv2", f"{dst}/conv2")
    if c.has(f"{src}.conv_shortcut.weight"):
        c.conv(f"{src}.conv_shortcut", f"{dst}/skip")  # ours: 1x1 Conv


def _convert_spatial_transformer(c: Converter, src: str, dst: str,
                                 depth: int) -> None:
    c.groupnorm(f"{src}.norm", f"{dst}/norm")
    c.conv1x1_dense(f"{src}.proj_in", f"{dst}/proj_in")
    for k in range(depth):
        tsrc = f"{src}.transformer_blocks.{k}"
        tdst = f"{dst}/block_{k}"
        c.norm(f"{tsrc}.norm1", f"{tdst}/ln1")
        c.dense_fused((f"{tsrc}.attn1.to_q", f"{tsrc}.attn1.to_k",
                       f"{tsrc}.attn1.to_v"), f"{tdst}/self_attn/qkv")
        c.dense(f"{tsrc}.attn1.to_out.0", f"{tdst}/self_attn/out")
        c.norm(f"{tsrc}.norm2", f"{tdst}/ln2")
        c.dense(f"{tsrc}.attn2.to_q", f"{tdst}/cross_attn/q")
        c.dense_fused((f"{tsrc}.attn2.to_k", f"{tsrc}.attn2.to_v"),
                      f"{tdst}/cross_attn/kv")
        c.dense(f"{tsrc}.attn2.to_out.0", f"{tdst}/cross_attn/out")
        c.norm(f"{tsrc}.norm3", f"{tdst}/ln3")
        c.dense(f"{tsrc}.ff.net.0.proj", f"{tdst}/ff/proj")
        c.dense(f"{tsrc}.ff.net.2", f"{tdst}/ff/out")
    c.conv1x1_dense(f"{src}.proj_out", f"{dst}/proj_out")


def convert_unet(tensors: Tensors, cfg) -> dict:
    """diffusers UNet2DConditionModel -> our UNet tree."""
    c = Converter(tensors, "unet")
    c.conv("conv_in", "conv_in")
    c.dense("time_embedding.linear_1", "time_fc1")
    c.dense("time_embedding.linear_2", "time_fc2")
    if c.has("add_embedding.linear_1.weight"):
        c.dense("add_embedding.linear_1", "add_fc1")
        c.dense("add_embedding.linear_2", "add_fc2")

    levels = len(cfg.channel_mults)
    for lvl in range(levels):
        for blk in range(cfg.blocks_per_level):
            _convert_resblock(
                c, f"down_blocks.{lvl}.resnets.{blk}",
                f"down_{lvl}_res_{blk}")
            if cfg.attention_levels[lvl] and cfg.transformer_depth[lvl]:
                _convert_spatial_transformer(
                    c, f"down_blocks.{lvl}.attentions.{blk}",
                    f"down_{lvl}_attn_{blk}", cfg.transformer_depth[lvl])
        if lvl != levels - 1:
            c.conv(f"down_blocks.{lvl}.downsamplers.0.conv",
                   f"down_{lvl}_downsample")

    _convert_resblock(c, "mid_block.resnets.0", "mid_res_0")
    mid_depth = max(
        [d for lvl, d in enumerate(cfg.transformer_depth)
         if cfg.attention_levels[lvl]] or [1])
    _convert_spatial_transformer(c, "mid_block.attentions.0", "mid_attn",
                                 mid_depth)
    _convert_resblock(c, "mid_block.resnets.1", "mid_res_1")

    for i in range(levels):
        lvl = levels - 1 - i  # diffusers up_blocks[0] = lowest resolution
        for blk in range(cfg.blocks_per_level + 1):
            _convert_resblock(
                c, f"up_blocks.{i}.resnets.{blk}", f"up_{lvl}_res_{blk}")
            if cfg.attention_levels[lvl] and cfg.transformer_depth[lvl]:
                _convert_spatial_transformer(
                    c, f"up_blocks.{i}.attentions.{blk}",
                    f"up_{lvl}_attn_{blk}", cfg.transformer_depth[lvl])
        if lvl != 0:
            c.conv(f"up_blocks.{i}.upsamplers.0.conv", f"up_{lvl}_upsample")

    c.groupnorm("conv_norm_out", "norm_out")
    c.conv("conv_out", "conv_out")
    return c.tree()


# ---------------------------------------------------------------------------
# VAE decoder (diffusers AutoencoderKL naming)
# ---------------------------------------------------------------------------

def _convert_vae_resblock(c: Converter, src: str, dst: str) -> None:
    c.groupnorm(f"{src}.norm1", f"{dst}/norm1")
    c.conv(f"{src}.conv1", f"{dst}/conv1")
    c.groupnorm(f"{src}.norm2", f"{dst}/norm2")
    c.conv(f"{src}.conv2", f"{dst}/conv2")
    if c.has(f"{src}.conv_shortcut.weight"):
        c.conv(f"{src}.conv_shortcut", f"{dst}/skip")


def _convert_vae_attn(c: Converter, src: str, dst: str) -> None:
    """Mid-block attention under EITHER published naming era.

    The SD1.5-era VAE file (saved before the diffusers Attention
    refactor) names these ``query/key/value/proj_attn``; the SDXL-era
    file uses ``to_q/to_k/to_v/to_out.0``. Both inventories are pinned
    in data/manifests/vae_{sd15,sdxl}.json — a converter that read only
    the modern names would silently random-init on the actual SD1.5
    artifact."""
    c.groupnorm(f"{src}.group_norm", f"{dst}/norm")
    legacy = c.has(f"{src}.query.weight")
    names = (("query", "key", "value", "proj_attn") if legacy
             else ("to_q", "to_k", "to_v", "to_out.0"))
    for theirs, ours in zip(names, ("q", "k", "v", "out")):
        c.dense(f"{src}.{theirs}", f"{dst}/attn/{ours}")


def convert_vae_decoder(tensors: Tensors, cfg) -> dict:
    # the full AutoencoderKL file also carries the encoder half + its
    # quant_conv; this converter serves the decode hot path only
    c = Converter(tensors, "vae_decoder", ignore=(
        "encoder.*", "quant_conv.*"))
    c.conv("post_quant_conv", "post_quant_conv")  # ours: 1x1 Conv
    c.conv("decoder.conv_in", "conv_in")
    _convert_vae_resblock(c, "decoder.mid_block.resnets.0", "mid_res_0")
    _convert_vae_attn(c, "decoder.mid_block.attentions.0", "mid_attn")
    _convert_vae_resblock(c, "decoder.mid_block.resnets.1", "mid_res_1")
    levels = len(cfg.channel_mults)
    for i in range(levels):
        lvl = levels - 1 - i
        for blk in range(cfg.blocks_per_level + 1):
            _convert_vae_resblock(
                c, f"decoder.up_blocks.{i}.resnets.{blk}",
                f"up_{lvl}_res_{blk}")
        if lvl != 0:
            c.conv(f"decoder.up_blocks.{i}.upsamplers.0.conv",
                   f"up_{lvl}_upsample")
    c.groupnorm("decoder.conv_norm_out", "norm_out")
    c.conv("decoder.conv_out", "conv_out")
    return c.tree()


def convert_vae_encoder(tensors: Tensors, cfg) -> dict:
    """Encoder half of the same AutoencoderKL checkpoint (img2img path)."""
    c = Converter(tensors, "vae_encoder", ignore=(
        "decoder.*", "post_quant_conv.*"))
    c.conv("quant_conv", "quant_conv")
    c.conv("encoder.conv_in", "conv_in")
    levels = len(cfg.channel_mults)
    for lvl in range(levels):
        for blk in range(cfg.blocks_per_level):
            _convert_vae_resblock(
                c, f"encoder.down_blocks.{lvl}.resnets.{blk}",
                f"down_{lvl}_res_{blk}")
        if lvl != levels - 1:
            c.conv(f"encoder.down_blocks.{lvl}.downsamplers.0.conv",
                   f"down_{lvl}_downsample")
    _convert_vae_resblock(c, "encoder.mid_block.resnets.0", "mid_res_0")
    _convert_vae_attn(c, "encoder.mid_block.attentions.0", "mid_attn")
    _convert_vae_resblock(c, "encoder.mid_block.resnets.1", "mid_res_1")
    c.groupnorm("encoder.conv_norm_out", "norm_out")
    c.conv("encoder.conv_out", "conv_out")
    return c.tree()


# ---------------------------------------------------------------------------
# Init + loading entry points
# ---------------------------------------------------------------------------

def init_params(model, rng_seed: int, *sample_args, method=None) -> dict:
    """Deterministic random init (fixed PRNG) for any zoo model."""
    rng = jax.random.PRNGKey(rng_seed)
    kwargs = {"method": method} if method is not None else {}
    return model.init(rng, *sample_args, **kwargs)


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        out[key] = np.asarray(leaf)
    return out


def save_params(params, path: str) -> None:
    """Persist a param tree as flat safetensors ('/'-joined paths)."""
    from safetensors import numpy as st_numpy

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    st_numpy.save_file(_flatten_with_paths(params), path)


def load_params(path: str) -> dict:
    tree: dict = {}
    for key, value in load_safetensors(path).items():
        set_in_tree(tree, key, value)
    return tree


def init_params_cached(model, rng_seed: int, *sample_args,
                       cache_path: Optional[str] = None,
                       cast_to: Optional[str] = None,
                       transform=None) -> dict:
    """Big-model init: run the init program on CPU (the on-device init
    graph for an 860M-param UNet takes minutes through a TPU tunnel, the
    CPU path ~1 min), cache to disk, and push the tree to the default
    device in one transfer. Subsequent constructions load from cache.

    ``cast_to`` applies the storage dtype (e.g. bf16 serving layout) at
    this single production point so no caller ships a forgotten tree in
    fp32. The disk cache stays fp32. ``transform``: host-side tree
    transform applied before the device transfer (see maybe_load)."""
    if cache_path and os.path.exists(cache_path):
        log.info("loading cached init params from %s", cache_path)
        tree = load_params(cache_path)
    else:
        from cassmantle_tpu.ops.attention import xla_only

        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu), xla_only():
            tree = model.init(jax.random.PRNGKey(rng_seed), *sample_args)
        if cache_path:
            log.info("caching init params to %s", cache_path)
            save_params(tree, cache_path)
    if cast_to:
        tree = cast_params(tree, cast_to)
    if transform is not None:
        tree = transform(tree)
    return jax.tree_util.tree_map(jnp.asarray, tree)


def load_checkpoint_tensors(
    weights_dir: Optional[str], filename: str, model_name: str = "weights",
) -> Optional[Tensors]:
    """Read a checkpoint's flat tensor dict, or None (-> random init).

    Handles missing files, sharded checkpoints (``<stem>-*.safetensors``
    merged into one dict), and unreadable/truncated files (logged, not
    raised). Callers converting SEVERAL models from one file (the full
    CLIP checkpoint feeds the text tower, vision tower, and projection)
    read once here and run each converter via :func:`convert_tensors`."""
    from cassmantle_tpu.utils.checkpoint import verify_or_record

    if not weights_dir:
        return None
    path = os.path.join(weights_dir, filename)
    if os.path.exists(path):
        log.info("%s: loading %s", model_name, path)
        # fingerprint check FIRST (utils/checkpoint.py, ISSUE 17): a
        # file that changed since its first load raises
        # CheckpointCorrupt — loudly, naming the path — instead of
        # riding the unreadable-file random-init fallback below. A
        # corrupt re-read during device-loss recovery must fail the
        # rebuild attempt, not silently swap weights mid-incident.
        verify_or_record(path)
        try:
            return load_safetensors(path)
        except Exception:
            # truncated/corrupt download: degrade to the documented
            # random-init fallback instead of crashing the server boot
            log.exception("%s: checkpoint at %s is unreadable; "
                          "falling back to random init", model_name, path)
            return None
    # sharded checkpoints: <stem>-*.safetensors merge into one dict
    import glob

    stem = filename.rsplit(".", 1)[0]
    shards = sorted(
        glob.glob(os.path.join(weights_dir, f"{stem}-*.safetensors"))
    )
    if not shards:
        log.info("%s: no checkpoint at %s; using random init",
                 model_name, path)
        return None
    log.info("%s: loading %d shards for %s", model_name, len(shards), stem)
    tensors: Tensors = {}
    for shard in shards:
        verify_or_record(shard)
        tensors.update(load_safetensors(shard))
    return tensors


def convert_tensors(
    tensors: Optional[Tensors], converter, model_name: str,
    cast_to: Optional[str] = None,
    transform=None,
) -> Optional[dict]:
    """Run a converter over an already-read tensor dict; None on an
    incomplete checkpoint (-> random init), mirroring maybe_load."""
    if tensors is None:
        return None
    try:
        params = converter(tensors)
    except KeyError as exc:
        # incomplete checkpoint (e.g. interrupted shard download): degrade
        # to the documented random-init fallback instead of crashing the
        # server deep inside conversion
        log.error("%s: checkpoint is missing tensors (%s); "
                  "falling back to random init", model_name, exc)
        return None
    if cast_to:
        params = cast_params(params, cast_to)
    if transform is not None:
        params = transform(params)
    return jax.tree_util.tree_map(jnp.asarray, params)


def maybe_load(
    weights_dir: Optional[str], filename: str, converter, model_name: str,
    cast_to: Optional[str] = None,
    transform=None,
) -> Optional[dict]:
    """Load+convert a checkpoint if present, else None (random init).

    ``cast_to``: storage dtype applied after conversion (see
    init_params_cached). ``transform``: host-side tree transform (e.g.
    ops.quant.quantize_tree_host) applied BEFORE device placement, so
    only the transformed tree ever occupies HBM."""
    tensors = load_checkpoint_tensors(weights_dir, filename, model_name)
    return convert_tensors(tensors, converter, model_name,
                           cast_to=cast_to, transform=transform)


def cast_params(params, dtype) -> dict:
    """Cast float params to a storage dtype (bf16 serving layout).

    Only floating leaves are cast; int leaves (e.g. embeddings indices,
    none today) pass through. Norm layers compute in fp32 internally
    (GroupNorm32 / LayerNorm(dtype=fp32)), so bf16 storage costs one
    upcast there and halves HBM weight reads everywhere else. Casting TO
    fp32 also works (upcasts a half-precision checkpoint).
    """
    dtype = jnp.dtype(dtype)

    def cast(leaf):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(cast, params)


def tree_shapes(tree) -> Dict[str, tuple]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = "/".join(re.sub(r"[\[\]'.]", "", str(p)) for p in path)
        out[key] = tuple(leaf.shape)
    return out
