"""Per-player session state.

Schema (kept from the reference, server.py:26-51, 78-94): one hash per
session id holding ``max`` (best mean score), ``won`` (0/1), ``attempts``,
and one field per mask index with that mask's best-known score; plus a
``sessions`` set for the live player count. Session hashes expire after one
round length (server.py:40) so abandoned sessions evaporate.

Fixed vs the reference (SURVEY.md §2.4): ``add_client`` checked membership of
the wrong key ('session' vs 'sessions', server.py:31) — here membership is
checked on the real set.
"""

from __future__ import annotations

from typing import Dict, List

from cassmantle_tpu.engine.store import StateStore

SESSIONS_KEY = "sessions"


class SessionManager:
    def __init__(self, store: StateStore, min_score: float,
                 time_per_prompt: float) -> None:
        self.store = store
        self.min_score = min_score
        self.time_per_prompt = time_per_prompt

    async def init_client(self, session: str, masks: List[int]) -> None:
        await self.reset_client(session, masks)
        await self.store.sadd(SESSIONS_KEY, session)

    async def add_client(self, session: str) -> None:
        if session and not await self.store.sismember(SESSIONS_KEY, session):
            await self.store.sadd(SESSIONS_KEY, session)

    async def reset_client(self, session: str, masks: List[int]) -> None:
        contents: Dict[str, object] = {
            "max": self.min_score, "won": 0, "attempts": 0,
        }
        for m in masks:
            contents[str(m)] = 0.0
        await self.store.delete(session)
        await self.store.hset(session, mapping=contents)
        await self.store.expire(session, self.time_per_prompt)

    async def remove_connection(self, session: str) -> None:
        await self.store.srem(SESSIONS_KEY, session)

    async def player_count(self) -> int:
        return len(await self.store.smembers(SESSIONS_KEY))

    async def exists(self, session: str) -> bool:
        return bool(session) and await self.store.exists(session)

    async def increment_attempt(self, session: str) -> None:
        await self.store.hincrby(session, "attempts", 1)

    async def fetch_scores(self, session: str) -> Dict[str, str]:
        raw = await self.store.hgetall(session)
        return {k: v.decode() for k, v in raw.items()}

    async def set_scores(
        self, session: str, scores: Dict[str, float]
    ) -> Dict[str, object]:
        """Record a guess outcome; returns scores + ``won`` flag.

        Win rule kept from the reference (server.py:78-89): mean of this
        attempt's scores == 1.0, i.e. every mask answered exactly.
        """
        current = await self.fetch_scores(session)
        mean_score = sum(scores.values()) / max(1, len(scores))
        if mean_score > float(current.get("max", self.min_score)):
            await self.store.hset(session, "max", mean_score)
        for key, val in scores.items():
            prev = float(current.get(key, 0.0))
            await self.store.hset(session, key, max(prev, val))
        won = int(mean_score == 1.0)
        if won:
            await self.store.hset(session, "won", 1)
        out: Dict[str, object] = {k: str(v) for k, v in scores.items()}
        out["won"] = won if won else int(current.get("won", 0) or 0)
        return out

    async def reset_all(self, masks: List[int]) -> None:
        for session in await self.store.smembers(SESSIONS_KEY):
            await self.reset_client(session, masks)
