"""Round lifecycle: story line, content double-buffer, global clock.

Keeps the reference's control loop shape (SURVEY.md §3.2):

- the countdown is a store key with a TTL; reading the clock = reading the
  TTL (server.py:139-147);
- at 70% of the round, the *next* round's content is generated into a buffer
  (server.py:162-163, backend.py:152-202);
- at 0, the buffer is atomically promoted, sessions reset, the clock
  restarts, and a 1 s ``reset`` flag tells clients to refetch
  (server.py:166-170, backend.py:204-238);
- every story runs ``episodes_per_story`` episodes, each episode's prompt
  continuing from the previous one, then a fresh seed starts a new story
  (backend.py:137-150);
- all generation/promotion runs under store locks with skip-don't-crash
  semantics: if generation fails, the round still rotates — the reference
  silently replays the same round (backend.py:211-215 — promotion is a
  no-op when the buffer is empty); here an empty buffer first falls back
  to the store-backed round reserve (engine/reserve.py), so a dark device
  serves *different* archived puzzles each cycle, and only an empty
  reserve degrades all the way to the reference's replay.

Generation itself is behind the :class:`ContentBackend` protocol — the TPU
serving pipeline in production, a deterministic fake in tests — optionally
guarded by a circuit breaker (utils/circuit.py): repeated failures trip it,
open-state rounds skip the backend dial (and its retry backoff) entirely,
and a half-open probe re-admits generation when the device heals.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import random
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from cassmantle_tpu.chaos import afault_point
from cassmantle_tpu.engine.masking import EmbedFn, build_prompt_state
from cassmantle_tpu.engine.reserve import RoundReserve
from cassmantle_tpu.engine.store import LockTimeout, StateStore
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import tracer
from cassmantle_tpu.serving.integrity import OutputInvalid
from cassmantle_tpu.utils.circuit import CircuitBreaker, CircuitOpen
from cassmantle_tpu.utils.codec import decode_jpeg, encode_jpeg
from cassmantle_tpu.utils.logging import get_logger, metrics
from cassmantle_tpu.utils.retry import linear_backoff, retry_async

log = get_logger("rounds")

PROMPT_KEY = "prompt"
IMAGE_KEY = "image"
STORY_KEY = "story"
COUNTDOWN_KEY = "countdown"
RESET_KEY = "reset"


@dataclasses.dataclass
class RoundContent:
    """One round's generated content."""

    prompt_text: str          # the two-sentence episode text
    image: np.ndarray         # uint8 HWC RGB


class ContentBackend:
    """Produces round content. ``seed`` is the story-so-far (or a fresh
    title when ``is_seed``); returns the episode text + rendered image."""

    async def generate(self, seed: str, is_seed: bool) -> RoundContent:
        raise NotImplementedError


class RoundManager:
    def __init__(
        self,
        store: StateStore,
        backend: ContentBackend,
        embed: EmbedFn,
        *,
        seeds: Sequence[str],
        time_per_prompt: float = 900.0,
        buffer_at_fraction: float = 0.7,
        num_masked: int = 2,
        episodes_per_story: int = 20,
        lock_timeout: float = 120.0,
        acquire_timeout: float = 2.0,
        max_retries: int = 5,
        retry_backoff_s: float = 2.0,
        rng: Optional[random.Random] = None,
        on_promote: Optional[Callable[[], object]] = None,
        on_answers: Optional[Callable[[Sequence[str]], object]] = None,
        reserve: Optional[RoundReserve] = None,
        breaker: Optional[CircuitBreaker] = None,
        metric_labels: Optional[Dict[str, str]] = None,
    ) -> None:
        self.store = store
        self.backend = backend
        self.embed = embed
        self.seeds = list(seeds)
        self.time_per_prompt = time_per_prompt
        self.buffer_at_fraction = buffer_at_fraction
        self.num_masked = num_masked
        self.episodes_per_story = episodes_per_story
        self.lock_timeout = lock_timeout
        self.acquire_timeout = acquire_timeout
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.rng = rng or random.Random()
        # async callback run after each promotion (the game layer resets
        # sessions there, mirroring server.py:168).
        self.on_promote = on_promote
        # sync hook fed the new round's masked answer words whenever a
        # round becomes current (startup, promotion, reserve rotation):
        # the serving layer pins them into the scorer's int8 embed
        # table off the guess path (ops/embed_table.py)
        self.on_answers = on_answers
        # supervision seam (ISSUE 2): archive every generated round into
        # the reserve ring; fail generation fast while the breaker is
        # open so a dark device costs nothing per round and promotion
        # rotates reserve content instead of replaying.
        self.reserve = reserve
        self.breaker = breaker
        # per-room series labels (ISSUE 9 satellite): None = the exact
        # historical unlabeled keys (legacy single-game callers)
        self.metric_labels = metric_labels
        self._timer_task: Optional[asyncio.Task] = None
        self._buffer_task: Optional[asyncio.Task] = None

    # -- story ------------------------------------------------------------
    def select_seed(self) -> str:
        return self.rng.choice(self.seeds)

    async def init_story(self, title: str) -> None:
        await self.store.hset(STORY_KEY, mapping={"title": title, "episode": 0})

    async def fetch_story(self) -> Dict[str, str]:
        raw = await self.store.hgetall(STORY_KEY)
        return {k: v.decode() for k, v in raw.items()}

    async def _next_seed(self) -> tuple:
        """(is_seed, seed): continue the story or start a new one
        (reference ``random_seed``, backend.py:137-150)."""
        eps_raw = await self.store.hget(STORY_KEY, "episode")
        episodes = int(eps_raw or 0)
        if episodes < self.episodes_per_story:
            prev = await self.store.hget(PROMPT_KEY, "seed")
            if prev is not None:
                return False, prev.decode()
        return True, self.select_seed()

    async def _attempt_generate(self, seed: str, is_seed: bool) -> RoundContent:
        """One guarded backend call: fail fast while the breaker is open
        (no device dial, no backoff burn), and record every attempt's
        outcome so repeated failures trip it."""
        if self.breaker is not None and not self.breaker.allow():
            raise CircuitOpen(self.breaker.name)
        try:
            # generation fault point, INSIDE the guarded attempt: an
            # injected failure counts toward the breaker and rides the
            # same retry/reserve degradation a real dark device does
            # (the chaos port of tests/test_fault_injection.py's
            # FlakyBackend/DeadBackend monkeypatching)
            await afault_point("round.generate")
            # a ROOT trace per generation attempt: round generation is
            # background work with no HTTP request to inherit from, and
            # the pipeline's stage spans (prompt decode, t2i) need an
            # ambient trace to land in
            with tracer.span("round.generate", root=True,
                             attrs={"is_seed": is_seed}):
                content = await self.backend.generate(seed, is_seed)
        except OutputInvalid as exc:
            # the integrity sentinel rejected device output (ISSUE 17):
            # retriable like any attempt failure, but counted apart so a
            # sick device is distinguishable from queue pressure in the
            # round-generation failure mix
            metrics.inc("rounds.generate_invalid",
                        labels=self.metric_labels)
            log.warning("round generation rejected invalid output: %s",
                        exc)
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        except Exception:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            self.breaker.record_success()
        return content

    async def _generate(self, seed: str, is_seed: bool) -> RoundContent:
        """Generation with regeneration-retry (reference retries failed API
        calls ≤5x, utils.py:43-61; here failed device generations retry the
        same way before the round falls back to a replay). Callers hold
        startup/buffer locks, so total retry time is deadline-bounded
        below the lock timeout — the lock can't lapse mid-retry and let a
        second worker interleave writes into the same slot. A breaker
        rejection aborts the retry loop outright: backing off against an
        open breaker is pure wasted lock time."""
        return await retry_async(
            lambda: self._attempt_generate(seed, is_seed),
            max_retries=self.max_retries,
            backoff=linear_backoff(self.retry_backoff_s),
            name="generate",
            deadline_s=0.8 * self.lock_timeout,
            give_up_on=(CircuitOpen,),
        )

    # -- content helpers --------------------------------------------------
    async def _store_content(self, slot: str, content: RoundContent) -> None:
        prompt_state = build_prompt_state(
            content.prompt_text, self.embed, self.num_masked
        )
        state_json = json.dumps(prompt_state)
        jpeg = encode_jpeg(content.image)
        await self.store.hset(PROMPT_KEY, "seed", content.prompt_text)
        await self.store.hset(PROMPT_KEY, slot, state_json)
        await self.store.hset(IMAGE_KEY, slot, jpeg)
        if slot == "next":
            # generation id for idempotent promotion (ISSUE 12): a
            # worker killed between the current-slot writes and the
            # buffer cleanup must not let the NEXT promote re-run the
            # whole promotion (double episode bump) — promote_buffer
            # compares this id against the last promoted one
            import uuid as _uuid

            await self.store.hset(PROMPT_KEY, "next_gen",
                                  _uuid.uuid4().hex)
        if slot == "current":
            await self._bump_image_version()
            await self._notify_answers(prompt_state)
        if self.reserve is not None:
            # archive exactly the bytes a promotion writes; a reserve
            # hiccup must never fail the generation that just succeeded
            try:
                await self.reserve.archive(
                    content.prompt_text, state_json, jpeg)
            except Exception:
                log.exception("reserve archive failed")
                metrics.inc("reserve.archive_failures")

    async def _notify_answers(self, prompt_state) -> None:
        """Feed the round's masked answer words to ``on_answers``
        (production: InferenceService.pin_answers → the scorer's int8
        table) so answers are embedded and pinned at promotion time,
        not on the first guess. The hook is sync and may device-embed,
        so it runs on a worker thread; any failure is swallowed
        (``rounds.answer_pin_failures``) — pinning is an optimization,
        never round-lifecycle-critical."""
        if self.on_answers is None or prompt_state is None:
            return
        try:
            if isinstance(prompt_state, bytes):
                prompt_state = json.loads(prompt_state.decode())
            elif isinstance(prompt_state, str):
                prompt_state = json.loads(prompt_state)
            tokens = prompt_state["tokens"]
            answers = [str(tokens[int(i)]) for i in prompt_state["masks"]]
            await asyncio.to_thread(self.on_answers, answers)
        except Exception:
            log.exception("answer pin hook failed")
            metrics.inc("rounds.answer_pin_failures",
                        labels=self.metric_labels)

    async def _bump_image_version(self) -> None:
        """Monotonic counter, bumped AFTER every current-image write (so
        a version implies its bytes are already in place) — readers use
        it as a cheap cross-worker cache-invalidation key instead of
        fetching and fingerprinting the full JPEG per request.

        The counter starts at a RANDOM offset: after a store flush the
        count would otherwise restart at 1 and collide with a version a
        worker already cached for the pre-flush round, serving stale
        images until the next promotion."""
        if await self.store.hget(IMAGE_KEY, "version") is None:
            await self.store.hset(
                IMAGE_KEY, "version",
                str(self.rng.getrandbits(48)),
            )
        await self.store.hincrby(IMAGE_KEY, "version", 1)

    async def current_image_version(self) -> int:
        """0 means a store written before versioning (legacy/fresh)."""
        raw = await self.store.hget(IMAGE_KEY, "version")
        return int(raw) if raw is not None else 0

    async def fetch_current_prompt(self) -> Dict[str, object]:
        raw = await self.store.hget(PROMPT_KEY, "current")
        assert raw is not None, "no current prompt available"
        return json.loads(raw.decode())

    async def fetch_current_image_bytes(self) -> bytes:
        raw = await self.store.hget(IMAGE_KEY, "current")
        assert raw is not None, "no current image available"
        return raw

    async def fetch_current_image(self) -> np.ndarray:
        return decode_jpeg(await self.fetch_current_image_bytes())

    async def current_masks(self) -> list:
        return list((await self.fetch_current_prompt())["masks"])

    # -- lifecycle --------------------------------------------------------
    async def startup(self) -> None:
        """Generate initial content unless a live round survives in the
        store (resume-on-restart, backend.py:93-97)."""
        await self.store.hset(PROMPT_KEY, "status", "idle")
        await self.store.hset(IMAGE_KEY, "status", "idle")
        try:
            async with self.store.lock(
                "startup_lock", timeout=self.lock_timeout,
                blocking_timeout=self.acquire_timeout,
            ):
                if await self.store.hget(PROMPT_KEY, "current") is not None \
                        and await self.store.hget(IMAGE_KEY, "current") is not None:
                    log.info("resuming in-flight round from store")
                    await self._notify_answers(
                        await self.store.hget(PROMPT_KEY, "current"))
                    return
                title = self.select_seed()
                await self.init_story(title)
                with metrics.timer("round.generate_s",
                                   labels=self.metric_labels):
                    content = await self._generate(title, is_seed=True)
                await self._store_content("current", content)
                await self.store.hincrby(STORY_KEY, "episode", 1)
                metrics.inc("rounds.generated", labels=self.metric_labels)
                log.info("content initialization complete")
        except LockTimeout:
            log.info("startup lock held elsewhere; waiting for content")

    async def buffer_contents(self) -> None:
        """Pre-generate next round into the buffer (backend.py:152-202)."""
        try:
            async with self.store.lock(
                "buffer_lock", timeout=self.lock_timeout,
                blocking_timeout=self.acquire_timeout,
            ):
                if await self.store.hget(PROMPT_KEY, "next") is not None:
                    return
                is_seed, seed = await self._next_seed()
                if is_seed:
                    log.info("restarting storyline")
                    await self.store.hset(STORY_KEY, "next", seed)
                with metrics.timer("round.generate_s",
                                   labels=self.metric_labels):
                    content = await self._generate(seed, is_seed)
                await self._store_content("next", content)
                metrics.inc("rounds.buffered", labels=self.metric_labels)
                log.info("content buffering complete")
        except LockTimeout:
            log.info("buffer lock held elsewhere; skipping")
        except Exception as exc:
            log.exception("buffering failed; old round will replay")
            metrics.inc("rounds.buffer_failures", labels=self.metric_labels)
            flight_recorder.record("round.buffer_failed",
                                   error=type(exc).__name__)

    async def promote_buffer(self) -> None:
        """Swap next→current if a buffer exists (backend.py:204-238)."""
        try:
            async with self.store.lock(
                "promotion_lock", timeout=self.lock_timeout,
                blocking_timeout=self.acquire_timeout,
            ):
                prompt_next = await self.store.hget(PROMPT_KEY, "next")
                image_next = await self.store.hget(IMAGE_KEY, "next")
                next_gen = await self.store.hget(PROMPT_KEY, "next_gen")
                promoted = await self.store.hget(PROMPT_KEY,
                                                 "promoted_gen")
                if next_gen is not None and next_gen == promoted:
                    # this buffer ALREADY promoted its current slots: a
                    # worker died after the current writes + marker but
                    # before the tail. FINISH the interrupted tail
                    # instead of re-promoting — clients must see the
                    # new image version (a skipped bump would pin the
                    # old round's cached image against the new prompt
                    # all round), a pending storyline restart must
                    # land, and the episode advances ONCE. The only
                    # repeatable piece is the version bump (a crash
                    # after it but before the hdel re-bumps: one extra
                    # cache invalidation, never a stale serve); story
                    # and episode sit after the hdel, so this branch is
                    # their first and only run.
                    await self._bump_image_version()
                    await self.store.hdel(PROMPT_KEY, "next",
                                          "next_gen")
                    await self.store.hdel(IMAGE_KEY, "next")
                    next_story = await self.store.hget(STORY_KEY,
                                                       "next")
                    if next_story is not None:
                        await self.init_story(next_story.decode())
                        await self.store.hdel(STORY_KEY, "next")
                    await self.store.hincrby(STORY_KEY, "episode", 1)
                    metrics.inc("rounds.promote_dedup",
                                labels=self.metric_labels)
                    flight_recorder.record("round.promote_dedup")
                    log.warning("buffer was already promoted by a "
                                "crashed worker; finished its cleanup "
                                "without re-promoting")
                    await self._notify_answers(
                        await self.store.hget(PROMPT_KEY, "current"))
                    return
                if prompt_next is None or image_next is None:
                    # generation is dark (breaker open / buffer failed):
                    # rotate a reserve round so players get a FRESH
                    # puzzle; replay only when the reserve is empty too
                    if await self._promote_from_reserve():
                        return
                    log.warning("no buffered content; replaying round")
                    metrics.inc("rounds.replays", labels=self.metric_labels)
                    flight_recorder.record("round.replayed")
                    return
                prompt_prev = await self.store.hget(PROMPT_KEY, "current")
                image_prev = await self.store.hget(IMAGE_KEY, "current")
                try:
                    await self.store.hset(PROMPT_KEY, "current", prompt_next)
                    await self.store.hset(IMAGE_KEY, "current", image_next)
                except Exception:
                    # the two current-slot writes span two store keys and
                    # are not atomic; a failure between them would serve a
                    # prompt that doesn't match the image for a whole
                    # round. Best-effort rollback to the consistent old
                    # pair keeps the replay contract true.
                    log.exception("promotion write failed; rolling back")
                    if prompt_prev is not None and image_prev is not None:
                        await self.store.hset(
                            PROMPT_KEY, "current", prompt_prev)
                        await self.store.hset(
                            IMAGE_KEY, "current", image_prev)
                        # the restore is also a current-image change
                        await self._bump_image_version()
                    raise
                if next_gen is not None:
                    # the promotion marker lands RIGHT AFTER the
                    # current-slot writes: the crash window where a
                    # retry would double-promote shrinks to the gap
                    # between these two writes (and a double there
                    # rewrites identical bytes; only the episode
                    # counter could run ahead by one)
                    await self.store.hset(PROMPT_KEY, "promoted_gen",
                                          next_gen)
                await self._bump_image_version()
                await self.store.hdel(PROMPT_KEY, "next", "next_gen")
                await self.store.hdel(IMAGE_KEY, "next")
                next_story = await self.store.hget(STORY_KEY, "next")
                if next_story is not None:
                    await self.init_story(next_story.decode())
                    await self.store.hdel(STORY_KEY, "next")
                await self.store.hincrby(STORY_KEY, "episode", 1)
                metrics.inc("rounds.promoted", labels=self.metric_labels)
                flight_recorder.record("round.promoted")
                await self._notify_answers(prompt_next)
                log.info("buffer promotion complete")
        except LockTimeout:
            log.info("promotion lock held elsewhere; skipping")
        except Exception:
            # reference semantics: promotion failures log and abandon the
            # round update (backend.py:236-238); the old round replays
            log.exception("promotion failed; old round will replay")
            metrics.inc("rounds.promote_failures", labels=self.metric_labels)

    async def _promote_from_reserve(self) -> bool:
        """Degraded promotion (runs under the promotion lock): pull the
        least-recently-played archived round that isn't the one on
        screen and make it current. Same rollback discipline as the
        normal promotion — the served (prompt, image) pair stays
        consistent or unchanged."""
        if self.reserve is None:
            return False
        prompt_prev = await self.store.hget(PROMPT_KEY, "current")
        picked = await self.reserve.pick(exclude=prompt_prev)
        if picked is None:
            return False
        text, prompt_state, image = picked
        image_prev = await self.store.hget(IMAGE_KEY, "current")
        try:
            await self.store.hset(PROMPT_KEY, "current", prompt_state)
            await self.store.hset(IMAGE_KEY, "current", image)
        except Exception:
            log.exception("reserve promotion write failed; rolling back")
            if prompt_prev is not None and image_prev is not None:
                await self.store.hset(PROMPT_KEY, "current", prompt_prev)
                await self.store.hset(IMAGE_KEY, "current", image_prev)
                await self._bump_image_version()
            raise
        await self._bump_image_version()
        # the reserve round becomes the story-so-far: when the backend
        # heals, the next episode continues from what players last saw
        await self.store.hset(PROMPT_KEY, "seed", text)
        metrics.inc("rounds.reserve_promotions", labels=self.metric_labels)
        flight_recorder.record("round.reserve_promotion")
        await self._notify_answers(prompt_state)
        log.warning("generation dark; promoted reserve round "
                    "(fresh-content degraded mode)")
        return True

    # -- clock ------------------------------------------------------------
    async def start_countdown(self) -> None:
        await self.store.setex(COUNTDOWN_KEY, self.time_per_prompt, "active")

    async def remaining(self) -> float:
        return max(0.0, await self.store.ttl(COUNTDOWN_KEY))

    async def reset_flag(self) -> bool:
        return await self.store.exists(RESET_KEY)

    async def rollover(self) -> None:
        """End-of-round sequence (server.py:166-170)."""
        await self.promote_buffer()
        if self.on_promote is not None:
            await self.on_promote()
        await self.start_countdown()
        await self.store.setex(RESET_KEY, 1.0, 1)

    async def global_timer(self, tick: float = 1.0) -> None:
        """1 Hz drive loop (server.py:152-172). Cancel the task to stop."""
        await self.start_countdown()
        buffer_trigger = self.time_per_prompt * self.buffer_at_fraction
        buffered_this_round = False
        while True:
            await asyncio.sleep(tick)
            try:
                remaining = await self.store.ttl(COUNTDOWN_KEY)
                metrics.gauge("round.remaining_s", remaining,
                              labels=self.metric_labels)
                if remaining <= 0:
                    # clear BEFORE rollover: if rollover partially fails
                    # (clock restarted, reset flag lost), the new round
                    # must still buffer rather than silently replay
                    buffered_this_round = False
                    await self.rollover()
                    continue
                if remaining <= buffer_trigger and not buffered_this_round:
                    buffered_this_round = True
                    # strong reference: the loop only weakly references
                    # tasks, and a GC'd task would vanish mid-generation
                    self._buffer_task = asyncio.ensure_future(
                        self.buffer_contents())
            except asyncio.CancelledError:
                raise
            except Exception:
                # the clock is the one task that must never die: a store
                # hiccup skips this tick and the next tick retries
                log.exception("timer tick failed; continuing")
                metrics.inc("rounds.timer_tick_failures",
                            labels=self.metric_labels)

    def start(self, tick: float = 1.0) -> asyncio.Task:
        self._timer_task = asyncio.ensure_future(self.global_timer(tick))
        return self._timer_task

    async def stop(self) -> None:
        for attr in ("_timer_task", "_buffer_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
                setattr(self, attr, None)
