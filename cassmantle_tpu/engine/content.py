"""Content backends: the generation seam.

All model compute funnels through :class:`ContentBackend.generate` — the
same seam the reference exposes via ``generate_prompt``/``generate_image``
(backend.py:240-295, SURVEY.md §4 "inference seam"). Production wires in
:class:`TPUContentBackend` (serving/pipeline.py); tests and the model-free
engine stage use :class:`FakeContentBackend`.
"""

from __future__ import annotations

import asyncio
import hashlib

import numpy as np

from cassmantle_tpu.engine.rounds import ContentBackend, RoundContent

_FAKE_SENTENCES = [
    "The {adj} {noun} drifted across the {place} under a {color} sky.",
    "A {adj} {noun} waited near the {place}, humming a {color} tune.",
    "Nobody expected the {adj} {noun} to appear beside the {place} at dusk.",
]
_ADJ = ["ancient", "glowing", "crooked", "silent", "restless", "gilded"]
_NOUN = ["lighthouse", "caravan", "automaton", "orchard", "archive", "comet"]
_PLACE = ["harbor", "observatory", "market", "glacier", "station", "canyon"]
_COLOR = ["crimson", "violet", "amber", "teal", "silver", "emerald"]


def template_text(seed: str) -> str:
    """Deterministic, always-maskable episode text derived from a seed
    hash. Used by the fake backend and as the production pipeline's
    fallback when a (e.g. randomly-initialized) LM emits degenerate text —
    the round must stay playable (skip-don't-crash, SURVEY.md §5.3)."""
    digest = hashlib.sha256(seed.encode()).digest()
    pick = lambda options, i: options[digest[i] % len(options)]  # noqa: E731
    return _FAKE_SENTENCES[digest[0] % len(_FAKE_SENTENCES)].format(
        adj=pick(_ADJ, 1), noun=pick(_NOUN, 2),
        place=pick(_PLACE, 3), color=pick(_COLOR, 4),
    )


class FakeContentBackend(ContentBackend):
    """Deterministic, instant content: text from a seed-hash template, image
    = a solid-pattern gradient keyed by the text. Lets the full game run
    with zero model compute (engine stage 1, SURVEY.md §7.1)."""

    def __init__(self, image_size: int = 64, delay_s: float = 0.0) -> None:
        self.image_size = image_size
        self.delay_s = delay_s
        self.calls = 0

    async def generate(self, seed: str, is_seed: bool) -> RoundContent:
        self.calls += 1
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        text = template_text(seed)
        digest = hashlib.sha256(seed.encode()).digest()
        size = self.image_size
        # brownout actuation (serving/overload.py, ISSUE 13): the fake
        # backend honors the resolution-downshift tier like the real
        # pipelines, so an overload drill against --fake workers can
        # observe quality degradation end to end (lazy import — the
        # engine layer must stay importable without serving)
        from cassmantle_tpu.serving.overload import quality_overrides

        tier = quality_overrides()
        if tier is not None and tier.image_size_scale != 1.0:
            size = max(16, int(size * tier.image_size_scale))
        y, x = np.mgrid[0:size, 0:size]
        r = (x * int(digest[5]) // size) % 256
        g = (y * int(digest[6]) // size) % 256
        b = ((x + y) * int(digest[7]) // (2 * size)) % 256
        image = np.stack([r, g, b], axis=-1).astype(np.uint8)
        return RoundContent(prompt_text=text, image=image)


def hash_embed(words, dim: int = 32) -> np.ndarray:
    """Deterministic stub embedding for tests: word -> unit vector derived
    from its sha256. Similar only to itself; stable across runs."""
    out = np.zeros((len(words), dim), dtype=np.float32)
    for i, w in enumerate(words):
        h = hashlib.sha256(w.lower().encode()).digest()
        vec = np.frombuffer((h * ((dim * 4) // len(h) + 1))[: dim * 4],
                            dtype=np.uint32).astype(np.float32)
        vec = (vec / np.float32(2**32)) - 0.5
        out[i] = vec / (np.linalg.norm(vec) + 1e-8)
    return out


async def hash_similarity(pairs) -> np.ndarray:
    """Stub similarity: cosine of hash_embed vectors (≈0 for distinct
    words, 1 for identical)."""
    guesses = hash_embed([g for g, _ in pairs])
    answers = hash_embed([a for _, a in pairs])
    return np.sum(guesses * answers, axis=-1)
