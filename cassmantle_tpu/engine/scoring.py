"""Guess scoring service + reveal (blur) curve.

Reference behavior being kept (backend.py:303-324, server.py:63-89):

- exact (case-insensitive) match scores 1.0;
- otherwise embedding cosine similarity, floored at ``min_score`` (also used
  for unknown words);
- a session's best *mean* score drives the blur radius
  ``min + (1 - score²)·(max - min)``;
- win = every mask solved exactly (mean score == 1.0).

The embedding backend is injectable: production uses the batched MiniLM TPU
scorer (ops/scorer.py); tests use deterministic stubs. Unlike the
reference's per-word synchronous gensim lookups, `score_pairs` is async and
vectorized so 1k concurrent guesses coalesce into one device batch.
"""

from __future__ import annotations

from typing import Awaitable, Callable, Dict, List, Sequence, Tuple

import numpy as np

# (guess, answer) pairs -> cosine similarities in [-1, 1]
SimilarityFn = Callable[[Sequence[Tuple[str, str]]], Awaitable[np.ndarray]]


class GuessScorer:
    def __init__(self, similarity: SimilarityFn, min_score: float = 0.01):
        self._similarity = similarity
        self.min_score = min_score

    async def score_pairs(
        self, pairs: Dict[str, Dict[str, str]]
    ) -> Dict[str, float]:
        """{mask_idx: {input, answer}} -> {mask_idx: score}.

        Mirrors reference ``compute_scores`` (backend.py:312-317) but in one
        batched similarity call.
        """
        keys: List[str] = []
        todo: List[Tuple[str, str]] = []
        out: Dict[str, float] = {}
        for key, pair in pairs.items():
            guess = pair["input"].strip().lower()
            answer = pair["answer"].strip().lower()
            if guess == answer:
                out[key] = 1.0
            else:
                keys.append(key)
                todo.append((guess, answer))
        if todo:
            sims = np.asarray(await self._similarity(todo), dtype=np.float32)
            for key, sim in zip(keys, sims):
                out[key] = float(max(self.min_score, min(float(sim), 0.999)))
        return out


def score_to_blur(
    score: float, min_blur: float = 0.0, max_blur: float = 15.0
) -> float:
    """Reveal curve (reference backend.py:319-320): quadratic in score."""
    score = float(np.clip(score, 0.0, 1.0))
    return min_blur + (1.0 - score**2) * (max_blur - min_blur)
