"""Store-backed round reserve: fresh puzzles while the device path is dark.

The reference's only degradation mode is the silent replay: when generation
fails, promotion is a no-op and the *same* puzzle loops until the backend
heals (reference backend.py:211-215). The reserve upgrades that floor —
every successfully generated round is archived into a capped ring in the
state store, and when the content breaker is open the round manager
promotes the least-recently-played archived round instead of replaying the
current one. Players keep getting a *different* puzzle every cycle even
with the TPU wedged.

Each slot's (text, prompt state, image) is ONE pickled hash field, so a
slot is written atomically per the store contract (single-command hashes on
both MemoryStore and the single-threaded native store) — a crash mid-
archive can never leave a slot pairing one round's prompt with another
round's image, the consistency invariant promotion defends. A small
prompt-only index hash keeps slot *selection* cheap (no JPEG transfer to
choose a slot); the blob's own prompt is authoritative at pickup.

Living in the store (not process memory) keeps the two store properties
the engine is built on: reserve rounds survive worker restarts, and in a
multi-worker fleet every worker draws from (and play-stamps) one shared
rotation instead of N private ones.

Concurrency contract (docs/STATIC_ANALYSIS.md lock hierarchy): the
reserve holds **no thread locks of its own** — ``archive`` runs after
generation under the buffer/startup store locks and ``pick`` runs under
the promotion store lock (level 0 of the hierarchy, the cross-worker
TTL locks), and every slot write is a single atomic store command. Any
future in-process caching here must take an ``OrderedLock`` ranked
inside the store-lock tier per that table.
"""

from __future__ import annotations

import pickle
from typing import Optional, Tuple

from cassmantle_tpu.engine.store import StateStore
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("reserve")

ROUNDS_KEY = "reserve:rounds"    # slot -> pickle((text, prompt_json, jpeg))
INDEX_KEY = "reserve:prompt"     # slot -> prompt_json (selection only)
META_KEY = "reserve:meta"        # counters + per-slot seq/played stamps


def _field(name) -> str:
    return name.decode() if isinstance(name, bytes) else str(name)


class RoundReserve:
    """Capped ring of archived rounds with least-recently-played pickup.

    ``archive`` runs on every successful generation; ``pick`` runs under
    the promotion lock when the buffer is empty. Play stamps are set at
    archive time too (an archived round is about to be the live round),
    so the rotation orders by least-recently-*on-screen*, not merely
    least-recently-picked-from-reserve.
    """

    def __init__(self, store: StateStore, capacity: int = 8) -> None:
        assert capacity > 0, "reserve capacity must be positive"
        self.store = store
        self.capacity = capacity

    @staticmethod
    def _digest(text: str) -> str:
        import hashlib

        return hashlib.md5(text.encode()).hexdigest()

    async def archive(self, text: str, prompt_state_json: str,
                      image_bytes: bytes) -> None:
        """Append one generated round; overwrites the oldest past capacity.
        Consecutive duplicates (a restarted story landing on the same seed)
        are skipped, and re-archiving a text the ring already holds
        REFRESHES that slot in place (idempotent archive, ISSUE 12): a
        generation retried after a mid-flight worker death must not
        consume a second ring slot for the same puzzle."""
        archived = int(await self.store.hget(META_KEY, "archived") or 0)
        if archived > 0:
            last_slot = str((archived - 1) % self.capacity)
            last = await self.store.hget(ROUNDS_KEY, last_slot)
            if last is not None and pickle.loads(last)[0] == text:
                return
        held = await self.store.hget(META_KEY,
                                     f"slot_of:{self._digest(text)}")
        if held is not None:
            slot = held.decode()
            blob = await self.store.hget(ROUNDS_KEY, slot)
            # the blob is authoritative (the slot_of entry can go stale
            # when ring wraparound evicted the text): refresh in place
            # only when the slot still holds THIS text
            if blob is not None and pickle.loads(blob)[0] == text:
                await self.store.hset(
                    ROUNDS_KEY, slot,
                    pickle.dumps((text, prompt_state_json, image_bytes)))
                await self.store.hset(INDEX_KEY, slot, prompt_state_json)
                metrics.inc("reserve.refreshed")
                return
        seq = await self.store.hincrby(META_KEY, "archived", 1)
        slot = str((seq - 1) % self.capacity)
        # ring wraparound evicts whatever the slot held: drop the
        # evicted text's slot_of entry so the digest index stays
        # bounded by capacity instead of growing per unique text
        old_blob = await self.store.hget(ROUNDS_KEY, slot)
        if old_blob is not None:
            old_text = pickle.loads(old_blob)[0]
            await self.store.hdel(META_KEY,
                                  f"slot_of:{self._digest(old_text)}")
        # the payload is one atomic field; the index is written after, so
        # a crash between the two leaves a stale index entry at worst —
        # pick() re-verifies against the blob before serving
        await self.store.hset(
            ROUNDS_KEY, slot,
            pickle.dumps((text, prompt_state_json, image_bytes)))
        await self.store.hset(INDEX_KEY, slot, prompt_state_json)
        await self.store.hset(META_KEY, f"seq:{slot}", seq)
        await self.store.hset(META_KEY, f"slot_of:{self._digest(text)}",
                              slot)
        # archived == about to be played: stamp now so degraded pickup
        # starts from the round the players saw longest ago
        stamp = await self.store.hincrby(META_KEY, "plays", 1)
        await self.store.hset(META_KEY, f"played:{slot}", stamp)
        metrics.inc("reserve.archived")
        metrics.gauge("reserve.size", await self.size())
        flight_recorder.record("reserve.archived", slot=slot)

    async def size(self) -> int:
        return len(await self.store.hgetall(ROUNDS_KEY))

    async def pick(self, exclude: Optional[bytes] = None,
                   ) -> Optional[Tuple[str, bytes, bytes]]:
        """Least-recently-played (text, prompt_state_json, image) — or
        None if the reserve is empty / only holds the excluded round.
        ``exclude`` is the current round's prompt-state bytes, so degraded
        promotion never re-serves the puzzle already on screen."""
        index = {_field(k): v
                 for k, v in (await self.store.hgetall(INDEX_KEY)).items()}
        meta = {_field(k): v
                for k, v in (await self.store.hgetall(META_KEY)).items()}
        candidates = [
            (int(meta.get(f"played:{slot}", b"0") or 0), slot)
            for slot, prompt_json in index.items()
            if exclude is None or prompt_json != exclude
        ]
        candidates.sort()
        for _, slot in candidates:
            blob = await self.store.hget(ROUNDS_KEY, slot)
            if blob is None:
                continue
            text, prompt_json, image = pickle.loads(blob)
            prompt_bytes = prompt_json.encode() \
                if isinstance(prompt_json, str) else prompt_json
            # the blob is authoritative: a stale index entry (crash
            # between blob and index writes) must not sneak the
            # on-screen round back in
            if exclude is not None and prompt_bytes == exclude:
                continue
            stamp = await self.store.hincrby(META_KEY, "plays", 1)
            await self.store.hset(META_KEY, f"played:{slot}", stamp)
            metrics.inc("reserve.picks")
            flight_recorder.record("reserve.picked", slot=slot)
            return text, prompt_bytes, image
        return None
