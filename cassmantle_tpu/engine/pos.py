"""Lightweight POS classification for mask candidacy.

The reference filters mask candidates by NLTK POS tag: a word is
eligible only when tagged JJ/JJR/JJS, RB/RBR/RBS, or NN/NNS — verbs
(VB*), proper nouns (NNP*), numbers (CD) and function words never mask
(reference src/utils.py:81-88, ``descriptive_tags``). NLTK's perceptron
tagger needs a downloaded model (zero-egress here), so this module
approximates the same decision with a vendored verb lexicon plus
morphology and left-context rules — self-contained, deterministic, no
corpus files.

The only decision that matters downstream is MASKABLE vs NOT (all of
JJ/RB/NN are treated identically by the selector), so the classifier
targets exactly the reference's exclusion classes:

- function words and number words (closed class);
- proper nouns — capitalized tokens that are not sentence-initial;
- verbs, by form class:
  - ``-ing`` forms whose stem is a known verb base are VBG (excluded —
    NLTK tags even attributive participles like "the humming lamp" as
    VBG, and VBG is not in ``descriptive_tags``); ``-ing`` nouns with
    non-verb stems ("railing", "morning") stay maskable;
  - ``-ed`` forms and irregular pasts/participles are verbs EXCEPT in
    attributive position, where NLTK reads them as JJ ("the gilded
    caravan", "under striped awnings", "gathered fallen fruit"):
    attributive = preceded by a determiner/preposition/verb (the start
    of a noun phrase) or sentence-initial;
  - bare verb bases are verbs after infinitive "to" or a modal
    ("to return"), after a plural-noun subject ("Birds sing" — VBP),
    or opening an imperative whose object follows ("Gather the
    fallen branches" — VB); elsewhere the noun reading wins
    ("promised rest", sentence-initial noun subjects like "Rain
    tapped...");
  - ``-s`` forms are treated as plural nouns: in past-tense story
    prose a 3rd-person-singular present verb is rare, while plural
    nouns after adjectives ("black rocks") are everywhere. Known
    gap (quantified per-class by eval/masking_agreement.py): VBZ in
    present-tense prompts ("the light fades") reads as NNS.

Accuracy against hand-annotated NLTK-convention tags and end-to-end
mask-selection agreement with the reference algorithm are measured by
eval/masking_agreement.py over data/pos_gold.txt; the numbers are
recorded in PARITY.md.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from cassmantle_tpu.utils.text import is_wordlike

# Determiners/possessives: a verb-homograph right after one is a noun
# ("the saw", "a rose", "their set"), and an -ed participle right
# after one is attributive ("the gilded caravan").
DETERMINERS = frozenset(
    """a an the this that these those my your his her its our their no
    some any each every either neither another such both all few
    several many most much""".split()
)

# Prepositions absent from masking.STOPWORDS (IN tags — excluded by
# the reference's filter, and the left-context of an attributive
# participle: "under STRIPED awnings", "into CHIPPED cups").
PREPOSITIONS = frozenset(
    """across along around behind beneath beside besides beyond near
    past toward towards upon within despite except like unlike amid
    amidst atop inside outside underneath throughout alongside""".split()
)

# Full preposition class for LEFT-context tests (PREPOSITIONS above
# only lists the ones masking.STOPWORDS lacks; an attributive
# participle can follow any of them: "UNDER striped awnings").
_ALL_PREPOSITIONS = PREPOSITIONS | frozenset(
    """in on at by of to from with without into onto over under above
    below between among through during before after against about
    while until""".split()
)

MODALS = frozenset(
    """will would can could may might must shall should do does did
    to""".split()
)

# Number words: CD tags, not in descriptive_tags.
NUMBERS = frozenset(
    """one two three four five six seven eight nine ten eleven twelve
    twenty thirty forty fifty hundred thousand million""".split()
)

# Sentence terminators: a capitalized token right after one is
# sentence-initial, not a proper noun.
_SENT_END = frozenset({".", "!", "?"})

# Irregular simple-past forms common in narrative prose (VBD).
IRREGULAR_PAST = frozenset(
    """went came saw took gave found left stood told sold became began
    brought built bought caught chose drew drove fell felt fought flew
    forgot grew heard held kept knew laid led lost made meant met paid
    ran rang rose said sang sat sent set shone shook slept spoke spent
    stole swam swept swung taught thought threw understood woke wore
    won wrote blew broke crept dealt dug drank froze hid hung knelt
    lay lent lit rode sought shot shrank slid spun sprang stuck stung
    strode struck swore tore wept wound bent bound bled fled sank
    stank clung leapt shod""".split()
)

# Participle forms that read as adjectives when attributive
# ("the broken clock") — same positional rule as -ed forms.
PARTICIPLE_ADJ = frozenset(
    """broken stolen worn torn hidden frozen woven sunken fallen
    forgotten shrunken swollen molten sworn shaken beaten written
    driven given risen chosen known grown thrown drawn flown borne
    bitten forbidden rotten""".split()
)

# Lexicalized -ed adjectives with no live verb reading in prose.
ED_ADJECTIVES = frozenset(
    """crooked wicked rugged naked sacred jagged wretched aged beloved
    learned dogged ragged blessed gifted fabled storied wooded
    left-handed hundred""".split()
)

# -ing nouns whose stem IS a verb base but whose noun reading
# dominates ("the building", "a painting").
ING_NOUNS = frozenset(
    """building painting drawing meaning feeling beginning ending
    wedding morning evening clothing ceiling railing lightning
    opening crossing landing setting gathering""".split()
)

# Common verb BASES whose inflections appear as main verbs in story
# prose. Bases are listed once; -s/-ed/-ing forms derive
# morphologically. Deliberately excludes heavy noun-homograph bases
# (light, sound, water, place, hand, spring, pass, sail, fish...).
VERB_BASES = frozenset(
    """drift wait hum appear seem remain arrive descend ascend wander
    linger gather scatter tremble shimmer flicker glow fade vanish
    emerge depart return follow carry cross climb crawl float settle
    whisper murmur echo stretch reach travel move turn stir lean
    pause happen begin continue cease expect believe notice watch
    listen stare gaze glance breathe sigh laugh weep smile frown nod
    shrug stumble hurry rush creep slip slide roll spin drip pour
    rain shine burn freeze melt crack shatter bloom wilt wither grow
    rise fall stand sit walk run fly swim sing dance speak talk call
    shout cry ask answer tell say know think feel hear see look come
    go get make take give find keep hold bring send leave meet pay
    play open close start stop end live die sleep wake dream hope
    wish want need try use work rest stay wear bear tear hide rock
    crumble flutter forget remember learn teach understand mean
    build buy catch choose deal dig draw drive eat fight lead lend
    lose read ride seek sell shake shoot show shut sink smell spend
    spread steal stick sting strike swear sweep swing throw wind
    write depict curl cool dry whistle complain calm""".split()
)


def _inflections(base: str) -> List[str]:
    """-s / -ed / -ing / doubled-consonant forms for one verb base."""
    forms = []
    if base.endswith("e"):
        stem = base[:-1]
        forms += [base + "s", stem + "ed", stem + "ing"]
    elif base.endswith("y") and len(base) > 2 and base[-2] not in "aeiou":
        forms += [base[:-1] + "ies", base[:-1] + "ied", base + "ing"]
    else:
        forms += [base + "s", base + "ed", base + "ing"]
        if (len(base) >= 3 and base[-1] not in "aeiouwxy"
                and base[-2] in "aeiou" and base[-3] not in "aeiou"):
            forms += [base + base[-1] + "ed", base + base[-1] + "ing"]
    return forms


_INFLECTED_VERB_FORMS = frozenset(
    form for b in VERB_BASES for form in _inflections(b)
)


def _ing_stems(low: str) -> List[str]:
    """Candidate bases for an -ing form: strip, restore -e, undouble."""
    stem = low[: -len("ing")]
    out = [stem, stem + "e"]
    if len(stem) >= 2 and stem[-1] == stem[-2]:
        out.append(stem[:-1])
    return out


def _is_verb_ing(low: str) -> bool:
    return (low.endswith("ing") and low not in ING_NOUNS
            and any(s in VERB_BASES for s in _ing_stems(low)))


def _is_verbish(low: Optional[str]) -> bool:
    """Loose test used for LEFT context: does this word look like a
    verb form (so the next word starts an object noun phrase)? -ing
    forms route through ``_is_verb_ing`` ONLY, so lexicalized -ing
    nouns that happen to inflect a known base ("the gathering ended")
    don't read as verbs."""
    if low is None:
        return False
    return (low in IRREGULAR_PAST
            or (low in _INFLECTED_VERB_FORMS
                and not low.endswith(("s", "ing")))
            or (low.endswith("ed") and low not in ED_ADJECTIVES)
            or _is_verb_ing(low))


def _prev_word(tokens: Sequence[str], i: int) -> Optional[str]:
    for j in range(i - 1, -1, -1):
        if is_wordlike(tokens[j]):
            return tokens[j].lower()
        if tokens[j] in _SENT_END:
            return None
    return None


def _next_word(tokens: Sequence[str], i: int) -> Optional[str]:
    for j in range(i + 1, len(tokens)):
        if is_wordlike(tokens[j]):
            return tokens[j].lower()
        if tokens[j] in _SENT_END:
            return None
    return None


# -s adverbs/misc that would otherwise pass the plural-noun surface
# test below ("Winters are always cool" must not read "cool" as VBP).
_S_ADVERBS = frozenset(
    """always sometimes perhaps besides towards upwards downwards
    backwards forwards afterwards nowadays indoors outdoors overseas
    alas thus""".split()
)


def _plural_nounish(low: Optional[str]) -> bool:
    """Loose plural-noun test for the VBP rule: an -s word that isn't a
    mass/abstract -ss noun, a function word ("across"), or an -s adverb
    ("always") — leaving "birds", "waves", "sentries"."""
    return (low is not None and len(low) > 3 and low.endswith("s")
            and not low.endswith("ss") and not _is_function_word(low)
            and low not in _S_ADVERBS
            and low not in _INFLECTED_VERB_FORMS)


def _sentence_initial(tokens: Sequence[str], i: int) -> bool:
    for j in range(i - 1, -1, -1):
        if tokens[j] in _SENT_END:
            return True
        if is_wordlike(tokens[j]):
            return False
    return True


def _is_function_word(low: str) -> bool:
    from cassmantle_tpu.engine.masking import STOPWORDS

    return (low in STOPWORDS or low in DETERMINERS
            or low in PREPOSITIONS or low in NUMBERS)


def _attributive(tokens: Sequence[str], i: int) -> bool:
    """True when token i sits at/inside the start of a noun phrase —
    right after a determiner, preposition, or verb, or opening a
    sentence — where NLTK reads a participle as JJ."""
    prev = _prev_word(tokens, i)
    if prev is None:
        return True
    # "to" before a participle is always prepositional ("to tired
    # sailors") — infinitive "to" takes a bare form, never -ed
    return (prev in DETERMINERS or prev in _ALL_PREPOSITIONS
            or _is_verbish(prev))


def is_maskable(tokens: Sequence[str], i: int) -> bool:
    """Approximate ``pos_tag(tokens)[i] in descriptive_tags`` — the
    reference's candidacy test (src/utils.py:86-88) — without NLTK."""
    tok = tokens[i]
    if not is_wordlike(tok):
        return False
    low = tok.lower()
    if _is_function_word(low):
        return False
    # proper noun (NNP): capitalized mid-sentence
    if tok[0].isupper() and not _sentence_initial(tokens, i):
        return False
    # VBG: -ing with a verb stem (NLTK excludes even attributive ones).
    # Verb BASES that merely end in -ing ("sing", "bring", "swing")
    # fall through to the bare-base rules below instead.
    if low.endswith("ing") and low not in VERB_BASES:
        return not _is_verb_ing(low)
    prev = _prev_word(tokens, i)
    # a verb-homograph right after a determiner is a noun ("the rose")
    if prev in DETERMINERS:
        return True
    # -ly adverbs are RB — maskable (as are the few -ly adjectives)
    if low.endswith("ly"):
        return True
    # past/participle forms: JJ in attributive position, else VBD/VBN
    if (low in PARTICIPLE_ADJ or low in IRREGULAR_PAST
            or low.endswith("ed")):
        if low in ED_ADJECTIVES:
            return True
        if low.endswith("ed") and len(low) <= 4:
            # too short to be an inflected verb: "red", "bed", "seed"
            return True
        return _attributive(tokens, i)
    # bare verb base: a verb as an infinitive/modal complement, as a
    # present-tense main verb after a plural-noun subject ("Birds sing
    # at dawn" — VBP), or opening an imperative whose object follows
    # ("Gather the fallen branches" — VB). Elsewhere the noun reading
    # wins ("promised rest", "Rain tapped...").
    if low in VERB_BASES:
        if prev in MODALS:
            return False
        if _plural_nounish(prev):
            return False
        if (_sentence_initial(tokens, i)
                and _next_word(tokens, i) in _IMPERATIVE_OBJECTS):
            return False
        return True
    return True


# What can open an imperative's object: a determiner/possessive or an
# object pronoun ("Gather the branches", "Pay him with dried figs").
_IMPERATIVE_OBJECTS = DETERMINERS | frozenset(
    "them it him her us me you nothing something everything".split()
)


# ---------------------------------------------------------------------------
# Register-drift detection (VERDICT r5 weak #3)
# ---------------------------------------------------------------------------
# The classifier above is tuned to PAST-NARRATIVE story prose — the
# production register — where mask-selection agreement with the NLTK
# reference measures 100% (PARITY.md). On present-tense prose agreement
# collapses to ~40% (3sg -s verbs read as plural nouns) and on
# imperatives to ~47%. Nothing used to consume that documented gap at
# runtime: a drifted LM would degrade mask quality silently. These
# helpers detect the drifted registers so the mask selector
# (engine/masking.py) can fall back to a conservative candidate set
# instead.

# "is/are/seems"-style copulas and auxiliaries that mark present-tense
# predication when followed by a verbal -ing form ("the light is
# fading").
_PRESENT_AUX = frozenset("is are am has have".split())


def _is_verb_s_form(low: str) -> bool:
    """An -s surface form that inflects a known verb base ("fades",
    "hums") — the VBZ shapes the maskability rules above deliberately
    read as plural nouns (the documented present-tense gap)."""
    return (low.endswith("s") and not low.endswith("ss")
            and low in _INFLECTED_VERB_FORMS)


def register_evidence(tokens: Sequence[str]) -> dict:
    """Count per-register verb evidence in a token stream.

    - ``past``: irregular simple pasts and -ed verb inflections — the
      register the classifier is calibrated for;
    - ``present``: 3sg -s verb forms after a singular/dt subject, and
      aux+V-ing progressives;
    - ``imperative``: sentence-initial bare verb bases with a
      determiner/pronoun object following (the existing imperative
      surface rule).
    """
    past = present = imperative = 0
    for i, tok in enumerate(tokens):
        if not is_wordlike(tok):
            continue
        low = tok.lower()
        if low in IRREGULAR_PAST or (
                low.endswith("ed") and len(low) > 4
                and low in _INFLECTED_VERB_FORMS
                and low not in ED_ADJECTIVES):
            past += 1
            continue
        prev = _prev_word(tokens, i)
        if _is_verb_s_form(low) and prev is not None \
                and not _plural_nounish(prev) and prev not in MODALS:
            # "the light fadeS", "she hums" — 3sg present
            present += 1
            continue
        if _is_verb_ing(low) and prev in _PRESENT_AUX:
            # "the tide is riSING" — present progressive
            present += 1
            continue
        if (low in VERB_BASES and _sentence_initial(tokens, i)
                and _next_word(tokens, i) in _IMPERATIVE_OBJECTS):
            imperative += 1
    return {"past": past, "present": present, "imperative": imperative}


def register_drift(tokens: Sequence[str]) -> bool:
    """True when the prose looks present-tense or imperative — the
    registers where mask agreement collapses (40-47%, PARITY.md) — so
    the caller should not trust positional verb disambiguation."""
    ev = register_evidence(tokens)
    non_past = ev["present"] + ev["imperative"]
    if non_past == 0:
        return False
    # any imperative opener is decisive (story prose never opens
    # sentences with object-taking bare verbs); present-tense needs to
    # outweigh the past evidence to avoid flagging mixed narration
    return ev["imperative"] > 0 or ev["present"] > ev["past"]


# Surface forms that could be verbs in ANY position — the conservative
# exclusion set used when the register has drifted: with positional
# rules untrustworthy, every verb-homograph is dropped from mask
# candidacy rather than risk masking a verb (the reference's filter
# never masks verbs).
def could_be_verb(low: str) -> bool:
    return (low in VERB_BASES
            or low in IRREGULAR_PAST
            or low in PARTICIPLE_ADJ
            or (low in _INFLECTED_VERB_FORMS and low not in ING_NOUNS)
            or (low.endswith("ed") and len(low) > 4
                and low not in ED_ADJECTIVES)
            or _is_verb_ing(low))
