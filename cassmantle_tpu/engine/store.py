"""Async game-state store: the framework's coordination plane.

The reference keeps ALL shared state in Redis — session hashes, round
content hashes, the countdown-as-TTL clock, player set, and the
startup/buffer/promotion distributed locks (SURVEY.md §1 L0, §5.8;
backend.py:70-71, server.py:139-147). That buys it two properties the
framework must keep:

1. **Resume-on-restart**: a worker reboot re-attaches to the in-flight round
   (backend.py:93-97).
2. **Multi-worker exclusion**: generation/promotion run once per round even
   with N workers (locks, backend.py:83-87, 155-159, 206-210).

This module defines the abstract :class:`StateStore` contract (the redis
subset the game actually uses) and two implementations:

- :class:`MemoryStore` — in-process asyncio store with real TTL semantics and
  lock timeouts; the default for single-host serving and all tests. Supports
  snapshot/restore to disk for the resume property.
- a client for the native C++ store lives in ``cassmantle_tpu/native``
  (optional, same contract) for multi-process deployments.

Keys hold either a string/bytes value, a hash (dict), or a set. TTLs follow
redis semantics: ``ttl`` returns -2 for missing keys, -1 for keys without
expiry. All times come from an injectable monotonic clock so round-lifecycle
tests can run at 2 s/round (SURVEY.md §4 "clock seam").
"""

from __future__ import annotations

import asyncio
import contextlib
import pickle
import time
import uuid
from typing import AsyncIterator, Callable, Dict, Optional, Set, Union

Value = Union[str, bytes, int, float]


class LockTimeout(Exception):
    """Raised when a distributed lock cannot be acquired in time."""


class StateStore:
    """Abstract async KV/hash/set store with TTLs and distributed locks."""

    # -- plain keys -------------------------------------------------------
    async def set(self, key: str, value: Value) -> None: raise NotImplementedError
    async def get(self, key: str) -> Optional[bytes]: raise NotImplementedError
    async def setex(self, key: str, ttl: float, value: Value) -> None: raise NotImplementedError
    async def delete(self, *keys: str) -> None: raise NotImplementedError
    async def exists(self, key: str) -> bool: raise NotImplementedError
    async def expire(self, key: str, ttl: float) -> None: raise NotImplementedError
    async def ttl(self, key: str) -> float: raise NotImplementedError

    # -- hashes -----------------------------------------------------------
    async def hset(self, key: str, field: Optional[str] = None,
                   value: Optional[Value] = None,
                   mapping: Optional[Dict[str, Value]] = None) -> None:
        raise NotImplementedError

    async def hget(self, key: str, field: str) -> Optional[bytes]: raise NotImplementedError
    async def hgetall(self, key: str) -> Dict[str, bytes]: raise NotImplementedError
    async def hdel(self, key: str, *fields: str) -> None: raise NotImplementedError
    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        raise NotImplementedError

    # -- sets -------------------------------------------------------------
    async def sadd(self, key: str, *members: str) -> None: raise NotImplementedError
    async def srem(self, key: str, *members: str) -> None: raise NotImplementedError
    async def smembers(self, key: str) -> Set[str]: raise NotImplementedError
    async def sismember(self, key: str, member: str) -> bool: raise NotImplementedError

    # -- locks ------------------------------------------------------------
    def lock(self, name: str, timeout: float = 120.0,
             blocking_timeout: float = 2.0):
        """Async context manager; raises LockTimeout if not acquired."""
        raise NotImplementedError

    async def close(self) -> None:  # pragma: no cover - trivial
        pass


def _to_bytes(v: Value) -> bytes:
    if isinstance(v, bytes):
        return v
    return str(v).encode()


def _strtoll(raw: bytes) -> int:
    """C ``strtoll`` semantics: parse an optional-signed leading integer,
    0 when none. The native store's HINCRBY reads counters this way, so
    the in-process store must agree — replication replay depends on the
    two backends computing identical results for the same command script
    (tests/test_store_parity.py)."""
    import re

    m = re.match(rb"\s*[+-]?\d+", raw)
    return int(m.group()) if m else 0


def _report_lock_hazard(kind: str, name: str) -> None:
    """Lock-TTL hazard telemetry: a hold that outlived its timeout means
    mutual exclusion was NOT guaranteed (another worker may have entered
    the critical section). Counted at ``store.lock_{kind}`` and logged —
    turning the reference's silent failure window into a signal."""
    from cassmantle_tpu.utils.logging import get_logger, metrics

    metrics.inc(f"store.lock_{kind}")
    get_logger("store").warning(
        "lock %r %s: hold exceeded its TTL — mutual exclusion was not "
        "guaranteed; raise the lock timeout above the slowest critical "
        "section", name, kind.replace("_", " "))


@contextlib.asynccontextmanager
async def polled_store_lock(send, name: str, timeout: float,
                            blocking_timeout: float) -> AsyncIterator[None]:
    """The client-side LOCK/UNLOCK polling protocol against a
    mantlestore-speaking backend, shared by :class:`MantleStore
    <cassmantle_tpu.native.client.MantleStore>` and
    :class:`ReplicatedStore` so lock semantics (poll cadence, timeout,
    and the ``:2`` overrun / ``:0`` expired-in-hold hazard taxonomy)
    can never drift between the two transports. ``send(*args: bytes)``
    performs one command round trip."""
    token = uuid.uuid4().hex.encode()
    deadline = time.monotonic() + blocking_timeout
    ttl_ms = str(int(timeout * 1000)).encode()
    acquired = False
    while True:
        reply = await send(b"LOCK", name.encode(), token, ttl_ms)
        if reply == b"OK":
            acquired = True
            break
        if time.monotonic() >= deadline:
            break
        await asyncio.sleep(0.05)
    if not acquired:
        raise LockTimeout(name)
    try:
        yield
    finally:
        with contextlib.suppress(Exception):
            released = await send(b"UNLOCK", name.encode(), token)
            if released == 2:
                _report_lock_hazard("overrun", name)
            elif released == 0:
                _report_lock_hazard("expired_in_hold", name)


class MemoryStore(StateStore):
    """In-process store with redis-like TTL + lock semantics."""

    def __init__(self, clock: Optional[Callable[[], float]] = None) -> None:
        self._data: Dict[str, object] = {}
        self._deadlines: Dict[str, float] = {}
        self._clock = clock or time.monotonic
        # Lock table: name -> (owner token, expiry deadline).
        self._locks: Dict[str, tuple] = {}
        self._lock_cond = asyncio.Condition()

    # -- expiry helpers ---------------------------------------------------
    def _alive(self, key: str) -> bool:
        if key not in self._data:
            return False
        deadline = self._deadlines.get(key)
        if deadline is not None and self._clock() >= deadline:
            del self._data[key]
            del self._deadlines[key]
            return False
        return True

    # -- plain keys -------------------------------------------------------
    async def set(self, key: str, value: Value) -> None:
        self._data[key] = _to_bytes(value)
        self._deadlines.pop(key, None)

    async def get(self, key: str) -> Optional[bytes]:
        if not self._alive(key):
            return None
        v = self._data[key]
        return v if isinstance(v, bytes) else None

    async def setex(self, key: str, ttl: float, value: Value) -> None:
        self._data[key] = _to_bytes(value)
        self._deadlines[key] = self._clock() + ttl

    async def delete(self, *keys: str) -> None:
        for key in keys:
            self._data.pop(key, None)
            self._deadlines.pop(key, None)

    async def exists(self, key: str) -> bool:
        return self._alive(key)

    async def expire(self, key: str, ttl: float) -> None:
        if self._alive(key):
            self._deadlines[key] = self._clock() + ttl

    async def ttl(self, key: str) -> float:
        if not self._alive(key):
            return -2.0
        deadline = self._deadlines.get(key)
        if deadline is None:
            return -1.0
        return max(0.0, deadline - self._clock())

    # -- hashes -----------------------------------------------------------
    def _hash(self, key: str, create: bool = False) -> Optional[Dict[str, bytes]]:
        """Wrong-type discipline (pinned by tests/test_store_parity.py so
        replication replay can rely on identical semantics across
        backends): reads of a live key of another kind behave like a
        missing key; writes REPLACE the entry with a fresh one of the
        new kind (TTL cleared — a fresh entry has no expiry)."""
        if not self._alive(key) or not isinstance(self._data[key], dict):
            if not create:
                return None
            self._data[key] = {}
            self._deadlines.pop(key, None)
        return self._data[key]

    async def hset(self, key: str, field: Optional[str] = None,
                   value: Optional[Value] = None,
                   mapping: Optional[Dict[str, Value]] = None) -> None:
        h = self._hash(key, create=True)
        if field is not None:
            h[field] = _to_bytes(value)
        if mapping:
            for k, v in mapping.items():
                h[k] = _to_bytes(v)

    async def hget(self, key: str, field: str) -> Optional[bytes]:
        h = self._hash(key)
        return None if h is None else h.get(field)

    async def hgetall(self, key: str) -> Dict[str, bytes]:
        h = self._hash(key)
        return {} if h is None else dict(h)

    async def hdel(self, key: str, *fields: str) -> None:
        h = self._hash(key)
        if h is not None:
            for f in fields:
                h.pop(f, None)

    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        h = self._hash(key, create=True)
        new = _strtoll(h.get(field, b"0")) + amount
        h[field] = str(new).encode()
        return new

    # -- sets -------------------------------------------------------------
    def _set(self, key: str, create: bool = False) -> Optional[Set[str]]:
        # same wrong-type discipline as _hash (tests/test_store_parity.py)
        if not self._alive(key) or not isinstance(self._data[key], set):
            if not create:
                return None
            self._data[key] = set()
            self._deadlines.pop(key, None)
        return self._data[key]

    async def sadd(self, key: str, *members: str) -> None:
        self._set(key, create=True).update(members)

    async def srem(self, key: str, *members: str) -> None:
        s = self._set(key)
        if s is not None:
            s.difference_update(members)

    async def smembers(self, key: str) -> Set[str]:
        s = self._set(key)
        return set() if s is None else set(s)

    async def sismember(self, key: str, member: str) -> bool:
        s = self._set(key)
        return s is not None and member in s

    # -- locks ------------------------------------------------------------
    @contextlib.asynccontextmanager
    async def lock(self, name: str, timeout: float = 120.0,
                   blocking_timeout: float = 2.0) -> AsyncIterator[None]:
        """Mutual exclusion with hold-timeout (a crashed holder's lock
        self-expires after ``timeout``, like a redis lock's TTL)."""
        token = uuid.uuid4().hex
        deadline = self._clock() + blocking_timeout
        acquired = False
        while True:
            async with self._lock_cond:
                held = self._locks.get(name)
                if held is None or self._clock() >= held[1]:
                    self._locks[name] = (token, self._clock() + timeout)
                    acquired = True
                    break
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        self._lock_cond.wait(), timeout=min(remaining, 0.05)
                    )
        if not acquired:
            raise LockTimeout(name)
        try:
            yield
        finally:
            async with self._lock_cond:
                held = self._locks.get(name)
                now = self._clock()
                if held is not None and held[0] == token:
                    if now >= held[1]:
                        # race DETECTION (SURVEY.md §5.2 — the
                        # reference only avoids): we held past the TTL,
                        # so exclusion was not guaranteed for the tail
                        # of this critical section. Size lock timeouts
                        # to the slowest holder, or this becomes the
                        # double-generation bug the locks exist to stop.
                        _report_lock_hazard("overrun", name)
                    del self._locks[name]
                else:
                    # expired mid-hold and (possibly) reacquired by
                    # another worker — two holders may have overlapped
                    _report_lock_hazard("expired_in_hold", name)
                self._lock_cond.notify_all()

    # -- durability (the reference gets this from redis persistence) ------
    def snapshot(self, path: str) -> None:
        """Persist non-expired state so a restart resumes the round."""
        now = self._clock()
        state = {
            "data": {k: v for k, v in self._data.items() if self._alive(k)},
            "ttl_remaining": {
                k: self._deadlines[k] - now
                for k in self._deadlines
                if k in self._data
            },
        }
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        now = self._clock()
        self._data = state["data"]
        self._deadlines = {
            k: now + rem
            for k, rem in state["ttl_remaining"].items()
            if rem > 0
        }
        for k, rem in state["ttl_remaining"].items():
            if rem <= 0:
                self._data.pop(k, None)


class ReplicatedStore(StateStore):
    """Replicated mantlestore client: leader writes + log-shipping pump.

    The cluster is a static set of mantlestore endpoints (one leader,
    N followers — ``--repl`` / ``--follower`` roles, native/mantlestore.cc).
    Every operation routes to the current leader; a background pump tails
    the leader's mutation log (``REPL TAIL``) and applies it to each
    follower (``REPL APPLY``) with acked offsets, so follower state is a
    deterministic replay of the leader's command stream (exactly-once:
    APPLY is conditional on the follower's applied offset, so racing
    pumps from several workers are safe).

    Failover: when the leader stops answering (connection refused, a
    timed-out round trip, or a ``READONLY`` rejection after a promotion
    elsewhere), the store probes the endpoint set, prefers any live
    node already in the leader role, and otherwise promotes the
    most-caught-up follower with ``REPL PROMOTE`` — which the follower
    accepts only once the replicated leader lease (a ``LOCK`` entry the
    leader heartbeats through its own log) has expired in its local
    lock table. Reads and writes block through the failover and resume
    against the new leader; round state survives because it was already
    shipped (tests/test_fabric.py leader-kill fault injection).

    Concurrency contract (docs/STATIC_ANALYSIS.md): all I/O runs on the
    event loop; the ``fabric.replication`` OrderedLock (rank 5) guards
    only the in-process status snapshot (leader index, lag, counters)
    read by sync ``/readyz`` reporting — never held across an await or
    a store round trip.
    """

    def __init__(self, endpoints, *, poll_interval_s: float = 0.05,
                 op_timeout_s: float = 2.0, lease_timeout_s: float = 3.0,
                 failover_grace_s: Optional[float] = None,
                 pump: bool = True) -> None:
        from cassmantle_tpu.utils.locks import OrderedLock

        assert endpoints, "ReplicatedStore needs at least one endpoint"
        self.endpoints = [self._parse_endpoint(e) for e in endpoints]
        self.poll_interval_s = poll_interval_s
        self.op_timeout_s = op_timeout_s
        self.lease_timeout_s = lease_timeout_s
        # how long ops keep retrying for a promotable leader: the lease
        # must lapse on a follower before PROMOTE succeeds, so the grace
        # covers one full lease plus probe slack
        self.failover_grace_s = (
            failover_grace_s if failover_grace_s is not None
            else 2.0 * lease_timeout_s + 3.0)
        self._pump_enabled = pump
        self._clients: Dict[int, object] = {}
        # the pump gets its OWN connections: a pump timeout can cancel a
        # round trip mid-reply, and a desynchronized connection must
        # never be the one game reads ride on (the next reader would
        # receive the stale replication reply as its value)
        self._pump_clients: Dict[int, object] = {}
        self._pump_task: Optional[asyncio.Task] = None
        self._state_lock = OrderedLock("fabric.replication", rank=5)
        self._leader: Optional[int] = None
        self._lag: int = 0
        self._failovers: int = 0
        self._shipped: int = 0
        # last applied offset seen per follower: a DOWN follower must
        # pin the reported lag to its last-known position (or the full
        # log), not silently drop out of the worst-lag calculation
        self._follower_applied: Dict[int, int] = {}

    @staticmethod
    def _parse_endpoint(ep) -> tuple:
        if isinstance(ep, tuple):
            return ep
        if isinstance(ep, int):
            return ("127.0.0.1", ep)
        host, _, port = str(ep).rpartition(":")
        return (host or "127.0.0.1", int(port))

    # -- client plumbing ---------------------------------------------------
    def _client(self, idx: int, pump: bool = False):
        table = self._pump_clients if pump else self._clients
        client = table.get(idx)
        if client is None:
            from cassmantle_tpu.native.client import MantleStore

            host, port = self.endpoints[idx]
            client = table[idx] = MantleStore(host=host, port=port)
        return client

    async def _drop(self, idx: int, pump: bool = False) -> None:
        """Forget a (possibly dead or desynchronized) connection so the
        next use redials on a clean stream."""
        table = self._pump_clients if pump else self._clients
        client = table.pop(idx, None)
        if client is not None:
            with contextlib.suppress(Exception):
                await client.close()

    def _leader_idx(self) -> Optional[int]:
        with self._state_lock:
            return self._leader

    def _set_leader(self, idx: Optional[int]) -> None:
        with self._state_lock:
            self._leader = idx

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "ReplicatedStore":
        await self._ensure_leader()
        if self._pump_enabled and len(self.endpoints) > 1 \
                and self._pump_task is None:
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump_loop())
        return self

    async def close(self) -> None:
        task, self._pump_task = self._pump_task, None
        if task is not None:
            # re-deliver the cancel until it lands: py3.10's wait_for
            # can SWALLOW a cancellation that races the inner future's
            # completion (gh-86296), leaving the pump loop alive after
            # a single cancel() — close() would then await it forever
            # (reproduced under CPU contention; see tests/test_fabric.py
            # test_replicated_store_close_lands_under_cancel_swallow)
            deadline = time.monotonic() + 5.0
            while not task.done() and time.monotonic() < deadline:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await asyncio.wait_for(asyncio.shield(task),
                                           timeout=0.05)
            if task.done():
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task
            else:  # pragma: no cover - defensive
                from cassmantle_tpu.utils.logging import get_logger

                get_logger("store").error(
                    "replication pump refused cancellation; abandoning")
        for idx in list(self._clients):
            await self._drop(idx)
        for idx in list(self._pump_clients):
            await self._drop(idx, pump=True)

    # -- leader election ---------------------------------------------------
    async def _probe(self, idx: int) -> Optional[tuple]:
        """(role, applied) of one endpoint, None when unreachable."""
        client = self._client(idx)
        try:
            role = await asyncio.wait_for(
                client.repl_role(), timeout=self.op_timeout_s)
            _, _, applied = await asyncio.wait_for(
                client.repl_offset(), timeout=self.op_timeout_s)
            return role, applied
        # lint: ignore[swallowed-error] — unreachable is the probed-for outcome: _drop resets the connection and the election proceeds on the survivors
        except (Exception, asyncio.TimeoutError):
            await self._drop(idx)
            return None

    async def _ensure_leader(self, grace_s: Optional[float] = None) -> int:
        """Index of the current leader, electing one if needed. Prefers a
        live node already in the leader role; otherwise promotes the
        most-caught-up reachable follower (max applied offset — promoting
        a lagged one would discard shipped-but-unapplied suffix)."""
        idx = self._leader_idx()
        if idx is not None:
            return idx
        deadline = time.monotonic() + (
            self.failover_grace_s if grace_s is None else grace_s)
        while True:
            # probe concurrently: one election pass costs one probe
            # timeout, not one per dead node — serial probing could eat
            # the whole failover grace before reaching the live follower
            probes = await asyncio.gather(
                *(self._probe(i) for i in range(len(self.endpoints))))
            states = {i: p for i, p in enumerate(probes) if p is not None}
            leaders = [i for i, (role, _) in states.items()
                       if role == "leader"]
            if leaders:
                # two live leaders = a stalled ex-leader resumed after
                # its lease lapsed and a follower was promoted. Prefer
                # the most-caught-up one (the promoted node holds the
                # old leader's history PLUS post-failover writes);
                # operators must still retire the stale node (DEPLOY
                # §3a drill) — it keeps calling itself leader
                best = max(leaders, key=lambda i: states[i][1])
                self._set_leader(best)
                return best
            if states:
                best = max(states, key=lambda i: states[i][1])
                try:
                    promoted = await asyncio.wait_for(
                        self._client(best).repl_promote(),
                        timeout=self.op_timeout_s)
                except (Exception, asyncio.TimeoutError):
                    from cassmantle_tpu.utils.logging import metrics

                    # a failed promotion is an election that found a
                    # winner and could not seat it — the cluster stays
                    # leaderless another round; that must be countable,
                    # not just a longer outage
                    metrics.inc("repl.promote_failures")
                    promoted = False
                    await self._drop(best)
                if promoted:
                    with self._state_lock:
                        self._failovers += 1
                    self._set_leader(best)
                    from cassmantle_tpu.obs.recorder import flight_recorder
                    from cassmantle_tpu.utils.logging import metrics

                    metrics.inc("repl.failovers")
                    flight_recorder.record(
                        "fabric.failover",
                        leader="%s:%d" % self.endpoints[best])
                    return best
            if time.monotonic() >= deadline:
                raise ConnectionError(
                    "replicated store: no promotable leader among "
                    f"{self.endpoints}")
            await asyncio.sleep(min(0.05, self.poll_interval_s))

    async def _call(self, invoke):
        """Run one client operation against the leader, failing over on
        connection loss / timeout / READONLY rejection."""
        from cassmantle_tpu.chaos import afault_point

        deadline = time.monotonic() + self.failover_grace_s
        while True:
            idx = await self._ensure_leader(
                grace_s=max(0.0, deadline - time.monotonic()))
            client = self._client(idx)
            try:
                # leader-boundary fault point: a peer-scoped partition
                # (host:port) raises ConnectionError and drives the SAME
                # drop + re-elect path a real leader cut does
                await afault_point("repl.leader_call",
                                   peer="%s:%d" % self.endpoints[idx])
                return await asyncio.wait_for(
                    invoke(client), timeout=self.op_timeout_s)
            except RuntimeError as exc:
                # -READONLY: the node lost leadership (promoted elsewhere)
                if "READONLY" not in str(exc):
                    raise
                self._set_leader(None)
            except (ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, asyncio.IncompleteReadError):
                await self._drop(idx)
                self._set_leader(None)
            if time.monotonic() >= deadline:
                raise ConnectionError(
                    "replicated store: leader unreachable past the "
                    f"failover grace ({self.failover_grace_s:.1f}s)")

    # -- log-shipping pump -------------------------------------------------
    async def _pump_loop(self) -> None:
        from cassmantle_tpu.utils.logging import metrics

        while True:
            try:
                await self._pump_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                metrics.inc("repl.pump_errors")
            await asyncio.sleep(self.poll_interval_s)

    async def _pump_once(self) -> None:
        from cassmantle_tpu.chaos import afault_point
        from cassmantle_tpu.utils.logging import metrics

        # pump fault point: a raise lands in the loop's except (counted
        # repl.pump_errors, next tick retries); latency models a slow
        # shipping pass (repl.lag growth the drills can watch)
        await afault_point("repl.pump")
        leader_idx = self._leader_idx()
        if leader_idx is None:
            return
        leader = self._client(leader_idx, pump=True)
        # bounded like everything else in the pump: a black-holed leader
        # (no RST, no reply) must wedge THIS tick, not the coroutine —
        # the loop's except path counts it and the next tick retries
        # against whatever leader _call-level failover elected meanwhile
        try:
            _, log_end, _ = await asyncio.wait_for(
                leader.repl_offset(), timeout=self.op_timeout_s)
        except (Exception, asyncio.TimeoutError):
            await self._drop(leader_idx, pump=True)
            raise
        max_lag = 0
        for i in range(len(self.endpoints)):
            if i == leader_idx:
                continue
            follower = self._client(i, pump=True)
            try:
                # bounded per pass: a black-holed follower must not
                # stall shipping to the healthy ones; progress persists
                # across passes, so a far-behind follower just resumes
                # next tick
                applied = await asyncio.wait_for(
                    self._ship_to(leader, follower),
                    timeout=max(5.0, 4.0 * self.op_timeout_s))
                self._follower_applied[i] = applied
            except (Exception, asyncio.TimeoutError):
                # the timeout may have cancelled a round trip mid-reply
                # on EITHER side: drop both pump connections so the next
                # tick starts on clean streams (the game-op clients are
                # a separate table and stay untouched). The dead
                # follower still counts toward lag at its last-known
                # offset — an outage must read as lag GROWTH, not as a
                # healthy caught-up cluster. Counted too: lag growth
                # says "behind", the counter says "the pump is failing"
                metrics.inc("repl.ship_failures")
                await self._drop(i, pump=True)
                await self._drop(leader_idx, pump=True)
                applied = self._follower_applied.get(i, 0)
            max_lag = max(max_lag, log_end - applied)
        with self._state_lock:
            self._lag = max_lag
        metrics.gauge("repl.lag", float(max_lag))

    async def _ship_to(self, leader, follower, batch: int = 256) -> int:
        """Tail the leader's log into one follower until caught up;
        returns the follower's applied offset."""
        from cassmantle_tpu.utils.logging import metrics

        _, _, applied = await follower.repl_offset()
        while True:
            _, log_end, _ = await leader.repl_offset()
            if applied >= log_end:
                return applied
            tailed = await leader.repl_tail(applied, batch)
            if tailed is None:
                # the leader trimmed past this follower: full resync
                end, dump = await leader.repl_dump()
                applied = await follower.repl_reset(end, dump)
                metrics.inc("repl.resyncs")
                continue
            next_offset, stream = tailed
            if next_offset <= applied:
                return applied
            new_applied = await follower.repl_apply(applied, stream)
            if new_applied >= next_offset:
                shipped = next_offset - applied
                with self._state_lock:
                    self._shipped += shipped
                metrics.inc("repl.shipped", shipped)
            # a racing pump (another worker) may have advanced it; either
            # way re-read and continue from the follower's truth
            applied = new_applied

    # -- status ------------------------------------------------------------
    def status(self) -> Dict[str, object]:
        """Sync snapshot for `/readyz` fabric reporting: leader identity,
        worst follower lag (commands), failover + shipped counters."""
        with self._state_lock:
            leader = self._leader
            lag = self._lag
            failovers = self._failovers
            shipped = self._shipped
        return {
            "endpoints": ["%s:%d" % ep for ep in self.endpoints],
            "leader": ("%s:%d" % self.endpoints[leader]
                       if leader is not None else None),
            "lag": lag,
            "failovers": failovers,
            "shipped": shipped,
        }

    # -- StateStore delegation --------------------------------------------
    async def set(self, key, value):
        return await self._call(lambda c: c.set(key, value))

    async def get(self, key):
        return await self._call(lambda c: c.get(key))

    async def setex(self, key, ttl, value):
        return await self._call(lambda c: c.setex(key, ttl, value))

    async def delete(self, *keys):
        return await self._call(lambda c: c.delete(*keys))

    async def exists(self, key):
        return await self._call(lambda c: c.exists(key))

    async def expire(self, key, ttl):
        return await self._call(lambda c: c.expire(key, ttl))

    async def ttl(self, key):
        return await self._call(lambda c: c.ttl(key))

    async def hset(self, key, field=None, value=None, mapping=None):
        return await self._call(
            lambda c: c.hset(key, field=field, value=value, mapping=mapping))

    async def hget(self, key, field):
        return await self._call(lambda c: c.hget(key, field))

    async def hgetall(self, key):
        return await self._call(lambda c: c.hgetall(key))

    async def hdel(self, key, *fields):
        return await self._call(lambda c: c.hdel(key, *fields))

    async def hincrby(self, key, field, amount: int = 1):
        return await self._call(lambda c: c.hincrby(key, field, amount))

    async def sadd(self, key, *members):
        return await self._call(lambda c: c.sadd(key, *members))

    async def srem(self, key, *members):
        return await self._call(lambda c: c.srem(key, *members))

    async def smembers(self, key):
        return await self._call(lambda c: c.smembers(key))

    async def sismember(self, key, member):
        return await self._call(lambda c: c.sismember(key, member))

    # -- locks ------------------------------------------------------------
    def lock(self, name: str, timeout: float = 120.0,
             blocking_timeout: float = 2.0):
        """The shared polled lock protocol with each round trip routed
        through leader failover. A failover mid-hold keeps exclusion:
        the lease-replicated lock table means the new leader already
        knows the holder's token."""

        async def send(*args: bytes):
            return await self._call(lambda c: c.raw_command(*args))

        return polled_store_lock(send, name, timeout, blocking_timeout)
