"""Async game-state store: the framework's coordination plane.

The reference keeps ALL shared state in Redis — session hashes, round
content hashes, the countdown-as-TTL clock, player set, and the
startup/buffer/promotion distributed locks (SURVEY.md §1 L0, §5.8;
backend.py:70-71, server.py:139-147). That buys it two properties the
framework must keep:

1. **Resume-on-restart**: a worker reboot re-attaches to the in-flight round
   (backend.py:93-97).
2. **Multi-worker exclusion**: generation/promotion run once per round even
   with N workers (locks, backend.py:83-87, 155-159, 206-210).

This module defines the abstract :class:`StateStore` contract (the redis
subset the game actually uses) and two implementations:

- :class:`MemoryStore` — in-process asyncio store with real TTL semantics and
  lock timeouts; the default for single-host serving and all tests. Supports
  snapshot/restore to disk for the resume property.
- a client for the native C++ store lives in ``cassmantle_tpu/native``
  (optional, same contract) for multi-process deployments.

Keys hold either a string/bytes value, a hash (dict), or a set. TTLs follow
redis semantics: ``ttl`` returns -2 for missing keys, -1 for keys without
expiry. All times come from an injectable monotonic clock so round-lifecycle
tests can run at 2 s/round (SURVEY.md §4 "clock seam").
"""

from __future__ import annotations

import asyncio
import contextlib
import pickle
import time
import uuid
from typing import AsyncIterator, Callable, Dict, Optional, Set, Union

Value = Union[str, bytes, int, float]


class LockTimeout(Exception):
    """Raised when a distributed lock cannot be acquired in time."""


class StateStore:
    """Abstract async KV/hash/set store with TTLs and distributed locks."""

    # -- plain keys -------------------------------------------------------
    async def set(self, key: str, value: Value) -> None: raise NotImplementedError
    async def get(self, key: str) -> Optional[bytes]: raise NotImplementedError
    async def setex(self, key: str, ttl: float, value: Value) -> None: raise NotImplementedError
    async def delete(self, *keys: str) -> None: raise NotImplementedError
    async def exists(self, key: str) -> bool: raise NotImplementedError
    async def expire(self, key: str, ttl: float) -> None: raise NotImplementedError
    async def ttl(self, key: str) -> float: raise NotImplementedError

    # -- hashes -----------------------------------------------------------
    async def hset(self, key: str, field: Optional[str] = None,
                   value: Optional[Value] = None,
                   mapping: Optional[Dict[str, Value]] = None) -> None:
        raise NotImplementedError

    async def hget(self, key: str, field: str) -> Optional[bytes]: raise NotImplementedError
    async def hgetall(self, key: str) -> Dict[str, bytes]: raise NotImplementedError
    async def hdel(self, key: str, *fields: str) -> None: raise NotImplementedError
    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        raise NotImplementedError

    # -- sets -------------------------------------------------------------
    async def sadd(self, key: str, *members: str) -> None: raise NotImplementedError
    async def srem(self, key: str, *members: str) -> None: raise NotImplementedError
    async def smembers(self, key: str) -> Set[str]: raise NotImplementedError
    async def sismember(self, key: str, member: str) -> bool: raise NotImplementedError

    # -- locks ------------------------------------------------------------
    def lock(self, name: str, timeout: float = 120.0,
             blocking_timeout: float = 2.0):
        """Async context manager; raises LockTimeout if not acquired."""
        raise NotImplementedError

    async def close(self) -> None:  # pragma: no cover - trivial
        pass


def _to_bytes(v: Value) -> bytes:
    if isinstance(v, bytes):
        return v
    return str(v).encode()


def _report_lock_hazard(kind: str, name: str) -> None:
    """Lock-TTL hazard telemetry: a hold that outlived its timeout means
    mutual exclusion was NOT guaranteed (another worker may have entered
    the critical section). Counted at ``store.lock_{kind}`` and logged —
    turning the reference's silent failure window into a signal."""
    from cassmantle_tpu.utils.logging import get_logger, metrics

    metrics.inc(f"store.lock_{kind}")
    get_logger("store").warning(
        "lock %r %s: hold exceeded its TTL — mutual exclusion was not "
        "guaranteed; raise the lock timeout above the slowest critical "
        "section", name, kind.replace("_", " "))


class MemoryStore(StateStore):
    """In-process store with redis-like TTL + lock semantics."""

    def __init__(self, clock: Optional[Callable[[], float]] = None) -> None:
        self._data: Dict[str, object] = {}
        self._deadlines: Dict[str, float] = {}
        self._clock = clock or time.monotonic
        # Lock table: name -> (owner token, expiry deadline).
        self._locks: Dict[str, tuple] = {}
        self._lock_cond = asyncio.Condition()

    # -- expiry helpers ---------------------------------------------------
    def _alive(self, key: str) -> bool:
        if key not in self._data:
            return False
        deadline = self._deadlines.get(key)
        if deadline is not None and self._clock() >= deadline:
            del self._data[key]
            del self._deadlines[key]
            return False
        return True

    # -- plain keys -------------------------------------------------------
    async def set(self, key: str, value: Value) -> None:
        self._data[key] = _to_bytes(value)
        self._deadlines.pop(key, None)

    async def get(self, key: str) -> Optional[bytes]:
        if not self._alive(key):
            return None
        v = self._data[key]
        return v if isinstance(v, bytes) else None

    async def setex(self, key: str, ttl: float, value: Value) -> None:
        self._data[key] = _to_bytes(value)
        self._deadlines[key] = self._clock() + ttl

    async def delete(self, *keys: str) -> None:
        for key in keys:
            self._data.pop(key, None)
            self._deadlines.pop(key, None)

    async def exists(self, key: str) -> bool:
        return self._alive(key)

    async def expire(self, key: str, ttl: float) -> None:
        if self._alive(key):
            self._deadlines[key] = self._clock() + ttl

    async def ttl(self, key: str) -> float:
        if not self._alive(key):
            return -2.0
        deadline = self._deadlines.get(key)
        if deadline is None:
            return -1.0
        return max(0.0, deadline - self._clock())

    # -- hashes -----------------------------------------------------------
    def _hash(self, key: str, create: bool = False) -> Optional[Dict[str, bytes]]:
        if not self._alive(key):
            if not create:
                return None
            self._data[key] = {}
        h = self._data[key]
        assert isinstance(h, dict), f"{key} is not a hash"
        return h

    async def hset(self, key: str, field: Optional[str] = None,
                   value: Optional[Value] = None,
                   mapping: Optional[Dict[str, Value]] = None) -> None:
        h = self._hash(key, create=True)
        if field is not None:
            h[field] = _to_bytes(value)
        if mapping:
            for k, v in mapping.items():
                h[k] = _to_bytes(v)

    async def hget(self, key: str, field: str) -> Optional[bytes]:
        h = self._hash(key)
        return None if h is None else h.get(field)

    async def hgetall(self, key: str) -> Dict[str, bytes]:
        h = self._hash(key)
        return {} if h is None else dict(h)

    async def hdel(self, key: str, *fields: str) -> None:
        h = self._hash(key)
        if h is not None:
            for f in fields:
                h.pop(f, None)

    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        h = self._hash(key, create=True)
        new = int(h.get(field, b"0")) + amount
        h[field] = str(new).encode()
        return new

    # -- sets -------------------------------------------------------------
    def _set(self, key: str, create: bool = False) -> Optional[Set[str]]:
        if not self._alive(key):
            if not create:
                return None
            self._data[key] = set()
        s = self._data[key]
        assert isinstance(s, set), f"{key} is not a set"
        return s

    async def sadd(self, key: str, *members: str) -> None:
        self._set(key, create=True).update(members)

    async def srem(self, key: str, *members: str) -> None:
        s = self._set(key)
        if s is not None:
            s.difference_update(members)

    async def smembers(self, key: str) -> Set[str]:
        s = self._set(key)
        return set() if s is None else set(s)

    async def sismember(self, key: str, member: str) -> bool:
        s = self._set(key)
        return s is not None and member in s

    # -- locks ------------------------------------------------------------
    @contextlib.asynccontextmanager
    async def lock(self, name: str, timeout: float = 120.0,
                   blocking_timeout: float = 2.0) -> AsyncIterator[None]:
        """Mutual exclusion with hold-timeout (a crashed holder's lock
        self-expires after ``timeout``, like a redis lock's TTL)."""
        token = uuid.uuid4().hex
        deadline = self._clock() + blocking_timeout
        acquired = False
        while True:
            async with self._lock_cond:
                held = self._locks.get(name)
                if held is None or self._clock() >= held[1]:
                    self._locks[name] = (token, self._clock() + timeout)
                    acquired = True
                    break
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        self._lock_cond.wait(), timeout=min(remaining, 0.05)
                    )
        if not acquired:
            raise LockTimeout(name)
        try:
            yield
        finally:
            async with self._lock_cond:
                held = self._locks.get(name)
                now = self._clock()
                if held is not None and held[0] == token:
                    if now >= held[1]:
                        # race DETECTION (SURVEY.md §5.2 — the
                        # reference only avoids): we held past the TTL,
                        # so exclusion was not guaranteed for the tail
                        # of this critical section. Size lock timeouts
                        # to the slowest holder, or this becomes the
                        # double-generation bug the locks exist to stop.
                        _report_lock_hazard("overrun", name)
                    del self._locks[name]
                else:
                    # expired mid-hold and (possibly) reacquired by
                    # another worker — two holders may have overlapped
                    _report_lock_hazard("expired_in_hold", name)
                self._lock_cond.notify_all()

    # -- durability (the reference gets this from redis persistence) ------
    def snapshot(self, path: str) -> None:
        """Persist non-expired state so a restart resumes the round."""
        now = self._clock()
        state = {
            "data": {k: v for k, v in self._data.items() if self._alive(k)},
            "ttl_remaining": {
                k: self._deadlines[k] - now
                for k in self._deadlines
                if k in self._data
            },
        }
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        now = self._clock()
        self._data = state["data"]
        self._deadlines = {
            k: now + rem
            for k, rem in state["ttl_remaining"].items()
            if rem > 0
        }
        for k, rem in state["ttl_remaining"].items():
            if rem <= 0:
                self._data.pop(k, None)
