from cassmantle_tpu.engine.store import (  # noqa: F401
    LockTimeout,
    MemoryStore,
    StateStore,
)
