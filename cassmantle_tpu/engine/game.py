"""Game facade: the engine API the HTTP layer talks to.

Composes sessions + rounds + scoring over one state store — the same public
surface the reference's ``Server`` class exposes to its FastAPI routes
(SURVEY.md §1 L3: init_client, add_client, remove_connection, player_count,
fetch_clock, fetch_client_scores, fetch_masked_image, fetch_prompt_json,
fetch_story, compute_client_scores) but composed instead of inherited, and
with the blur applied on device (ops/blur.py) instead of per-request PIL.
"""

from __future__ import annotations

import asyncio
import zlib
from typing import Callable, Dict, Optional

import numpy as np

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.engine.masking import EmbedFn
from cassmantle_tpu.engine.reserve import RoundReserve
from cassmantle_tpu.engine.rounds import ContentBackend, RoundManager
from cassmantle_tpu.engine.scoring import GuessScorer, SimilarityFn, score_to_blur
from cassmantle_tpu.engine.sessions import SessionManager
from cassmantle_tpu.engine.store import StateStore
from cassmantle_tpu.obs.trace import tracer
from cassmantle_tpu.serving.supervisor import ServingSupervisor
from cassmantle_tpu.utils.logging import NULL_METRICS, metrics
from cassmantle_tpu.utils.text import format_clock

# (image uint8 HWC, blur_radius) -> blurred uint8 HWC
BlurFn = Callable[[np.ndarray, float], np.ndarray]

# The synthetic-canary probe room (ISSUE 18). A game built for this
# room plays the full engine surface but emits NO engine metrics:
# probe traffic must never pollute game.guesses, cache-hit ratios, or
# latency histograms that feed capacity estimation and SLO burn.
PROBE_ROOM = "__probe__"


def _pil_blur(image: np.ndarray, radius: float) -> np.ndarray:
    """Host fallback blur; production injects the TPU blur op."""
    from PIL import Image, ImageFilter

    if radius <= 0:
        return image
    pil = Image.fromarray(image).filter(ImageFilter.GaussianBlur(radius))
    return np.asarray(pil)


class Game:
    def __init__(
        self,
        cfg: FrameworkConfig,
        store: StateStore,
        backend: ContentBackend,
        embed: EmbedFn,
        similarity: SimilarityFn,
        blur_fn: Optional[BlurFn] = None,
        supervisor: Optional[ServingSupervisor] = None,
        room: Optional[str] = None,
        pin_answers=None,
    ) -> None:
        game_cfg = cfg.game
        self.cfg = cfg
        self.store = store
        # per-room metric labels (ISSUE 9 satellite): a fabric-built
        # game labels its engine series with its room so N rooms on one
        # worker stay distinguishable series instead of blending into
        # one. None (legacy single-game callers) keeps every series'
        # exact historical unlabeled key.
        self.room = room
        self._metric_labels: Optional[Dict[str, str]] = (
            {"room": room} if room else None
        )
        # probe-room games swap the registry for a no-op sink: canary
        # traffic exercises the real code paths without contributing a
        # single engine series (ISSUE 18)
        self._metrics = NULL_METRICS if room == PROBE_ROOM else metrics
        # the degradation control plane: production shares one supervisor
        # between the InferenceService and the engine (server/app.py
        # build_game); standalone/fake games get their own
        self.supervisor = supervisor or ServingSupervisor()
        self.reserve = (
            RoundReserve(store, capacity=game_cfg.reserve_capacity)
            if game_cfg.reserve_capacity > 0 else None
        )
        self.sessions = SessionManager(
            store, game_cfg.min_score, game_cfg.time_per_prompt
        )
        self.scorer = GuessScorer(similarity, game_cfg.min_score)
        self.rounds = RoundManager(
            store,
            backend,
            embed,
            seeds=self._load_seeds(),
            time_per_prompt=game_cfg.time_per_prompt,
            buffer_at_fraction=game_cfg.buffer_at_fraction,
            num_masked=game_cfg.num_masked,
            episodes_per_story=game_cfg.episodes_per_story,
            lock_timeout=game_cfg.lock_timeout,
            acquire_timeout=game_cfg.acquire_timeout,
            on_promote=self._reset_sessions,
            # answer pin hook (ops/embed_table.py): production wires
            # InferenceService.pin_answers; fake fabrics wire the
            # hash-table pin; None keeps rounds pin-free
            on_answers=pin_answers,
            reserve=self.reserve,
            breaker=self.supervisor.content_breaker,
            metric_labels=self._metric_labels,
        )
        self.blur_fn = blur_fn or _pil_blur
        # blur bucket -> base64 JPEG, all for one round image identified
        # by _image_cache_key (int version, or a byte fingerprint tuple
        # for legacy stores)
        self._image_cache: Dict[float, str] = {}
        self._image_cache_key: object = None
        # bucket -> in-flight render task (single-flight misses)
        self._image_renders: Dict[float, asyncio.Task] = {}

    def _load_seeds(self) -> list:
        from cassmantle_tpu.server.assets import load_seeds

        return load_seeds()

    async def _reset_sessions(self) -> None:
        await self.sessions.reset_all(await self.rounds.current_masks())

    # -- lifecycle --------------------------------------------------------
    async def startup(self) -> None:
        await self.rounds.startup()

    def start_timer(self, tick: float = 1.0) -> asyncio.Task:
        return self.rounds.start(tick)

    async def shutdown(self) -> None:
        await self.rounds.stop()
        await self.store.close()

    # -- client API -------------------------------------------------------
    async def init_client(self, session: str) -> None:
        await self.sessions.init_client(
            session, await self.rounds.current_masks()
        )

    async def client_status(self, session: Optional[str]) -> Dict[str, object]:
        if not session or not await self.sessions.exists(session):
            return {"needInitialization": True}
        scores = await self.sessions.fetch_scores(session)
        return {
            "won": int(scores.get("won", 0) or 0),
            "needInitialization": False,
        }

    async def ensure_client(self, session: str) -> None:
        if not await self.sessions.exists(session):
            await self.init_client(session)

    async def _reveal_radius(self, session: str) -> float:
        """The one place the score -> blur-radius curve is applied."""
        scores = await self.sessions.fetch_scores(session)
        best = float(scores.get("max", self.cfg.game.min_score))
        return score_to_blur(
            best, self.cfg.game.min_blur, self.cfg.game.max_blur
        )

    async def fetch_masked_image(self, session: str) -> np.ndarray:
        """Per-session progressive reveal (server.py:129-133)."""
        radius = await self._reveal_radius(session)
        image = await self.rounds.fetch_current_image()

        def render() -> np.ndarray:
            # same off-loop rule as _render_bucket: blur is CPU/device
            # work that must not stall the event loop (to_thread copies
            # contextvars, so the span lands in the request trace)
            with tracer.span("game.blur"), \
                    self._metrics.timer("game.blur_s",
                                        labels=self._metric_labels):
                return self.blur_fn(image, radius)

        return await asyncio.to_thread(render)

    async def fetch_masked_image_b64(self, session: str) -> str:
        """The hot-request form of the reveal: blur radii quantize to
        0.5-px buckets and each (round image, bucket) renders ONCE —
        later requests reuse the cached base64 JPEG. The reference
        decoded, blurred (PIL), and re-encoded per request (SURVEY.md
        §3.3 'CPU hot spot'); with ≤31 buckets a round's entire blur
        ladder amortizes to 31 renders regardless of player count.

        Invalidation keys on the round's monotonic image version
        (rounds.py bumps it after every current-image write), so cache
        hits cost a few store bytes, not the full JPEG — and promotions
        by OTHER workers through a shared store invalidate too. The
        version is read BEFORE the bytes and re-read AFTER rendering:
        versions bump only after bytes land, so equality across the
        render proves the bytes belonged to that version — a render
        that straddles a promotion is served but never cached. Misses
        are single-flight per bucket: the reset-flag refetch stampede
        (every client at once, right after invalidation) coalesces to
        one decode+blur+encode. (Version 0 = legacy store: fall back to
        fingerprinting the bytes.)"""
        radius = await self._reveal_radius(session)
        # blur-ladder quantum: 0.5 px normally; a brownout tier
        # coarsens it (serving/overload.py) so a degraded round renders
        # FEWER distinct decode+blur+encode buckets — coarse buckets
        # round UP, so degradation only ever adds blur (ISSUE 13; lazy
        # import, engine stays importable without serving)
        from cassmantle_tpu.serving.overload import quantize_blur_radius

        bucket = quantize_blur_radius(radius)
        ver: object = await self.rounds.current_image_version()
        legacy_raw: Optional[bytes] = None
        if ver == 0:
            legacy_raw = await self.rounds.fetch_current_image_bytes()
            ver = (len(legacy_raw), zlib.crc32(legacy_raw))
        if ver != self._image_cache_key:
            self._image_cache_key = ver
            self._image_cache.clear()
            self._image_renders = {}
        cached = self._image_cache.get(bucket)
        if cached is not None:
            self._metrics.inc("game.image_cache_hits",
                              labels=self._metric_labels)
            return cached
        task = self._image_renders.get(bucket)
        if task is not None:
            self._metrics.inc("game.image_cache_hits",
                              labels=self._metric_labels)
        else:
            self._metrics.inc("game.image_cache_misses",
                              labels=self._metric_labels)
            # the render runs as its OWN task: a waiter's cancellation
            # (client disconnect) must not cancel the shared render or
            # propagate to the other coalesced waiters
            task = asyncio.get_running_loop().create_task(
                self._render_bucket(bucket, ver, legacy_raw)
            )
            self._image_renders[bucket] = task

            def _cleanup(t: asyncio.Task, b=bucket) -> None:
                if self._image_renders.get(b) is t:
                    del self._image_renders[b]
                if not t.cancelled():
                    t.exception()   # mark retrieved (waiters re-raise it)

            task.add_done_callback(_cleanup)
        return await asyncio.shield(task)

    async def _render_bucket(self, bucket: float, ver: object,
                             raw: Optional[bytes]) -> str:
        from cassmantle_tpu.utils.codec import decode_jpeg, image_to_base64

        if raw is None:
            raw = await self.rounds.fetch_current_image_bytes()

        def render() -> str:
            # CPU-bound decode+blur+encode runs OFF the event loop: a
            # bucket miss must not stall the 1 Hz WS clock pushes or
            # concurrent requests for the tens of ms it takes (PIL and
            # JPEG codecs release the GIL; the TPU blur op just blocks
            # this worker thread on device dispatch)
            image = decode_jpeg(raw)
            with tracer.span("game.blur"), \
                    self._metrics.timer("game.blur_s",
                                        labels=self._metric_labels):
                blurred = self.blur_fn(image, bucket)
            return image_to_base64(np.asarray(blurred))

        encoded = await asyncio.to_thread(render)
        # cache only if the version is provably still current: bumps
        # happen after bytes land, so unchanged version == our bytes
        # belong to it (isinstance check skips the re-read for legacy
        # fingerprint keys, which are derived from the bytes anyway)
        if not isinstance(ver, int) or \
                ver == await self.rounds.current_image_version():
            if ver == self._image_cache_key:
                self._image_cache[bucket] = encoded
        return encoded

    async def fetch_prompt_json(self, session: str) -> Dict[str, object]:
        """Client-visible prompt state (server.py:96-123): solved masks are
        flagged -1 + listed in ``correct``; unsolved mask tokens are '*'."""
        prompt = await self.rounds.fetch_current_prompt()
        await self.ensure_client(session)
        scores = await self.sessions.fetch_scores(session)
        attempts = int(scores.get("attempts", 0) or 0)
        prompt = {
            "tokens": list(prompt["tokens"]),
            "masks": list(prompt["masks"]),
            "correct": [],
        }
        if int(scores.get("won", 0) or 0) == 1:
            prompt["masks"] = []
        else:
            for i, mask in enumerate(list(prompt["masks"])):
                score = scores.get(str(mask))
                if score is not None and float(score) == 1.0:
                    prompt["masks"][i] = -1
                    prompt["correct"].append(mask)
                else:
                    prompt["tokens"][mask] = "*"
        prompt["scores"] = scores
        prompt["attempts"] = attempts
        return prompt

    async def fetch_story(self) -> Dict[str, str]:
        return await self.rounds.fetch_story()

    async def compute_client_scores(
        self, session: str, inputs: Dict[str, str]
    ) -> Dict[str, object]:
        """Guess path (server.py:63-76): score inputs against the masked
        answer tokens, update the session, bump attempts."""
        await self.ensure_client(session)
        prompt = await self.rounds.fetch_current_prompt()
        tokens = prompt["tokens"]
        valid_masks = {str(m) for m in prompt["masks"]}
        pairs = {}
        for mask_idx, guess in inputs.items():
            if str(mask_idx) not in valid_masks:
                continue  # stale or hostile input; reference would KeyError
            pairs[str(mask_idx)] = {
                "input": str(guess),
                "answer": tokens[int(mask_idx)],
            }
        if not pairs:
            return {"won": 0}
        with tracer.span("game.score", attrs={"pairs": len(pairs)}), \
                self._metrics.timer("game.score_s",
                                    labels=self._metric_labels):
            scores = await self.scorer.score_pairs(pairs)
        result = await self.sessions.set_scores(session, scores)
        await self.sessions.increment_attempt(session)
        self._metrics.inc("game.guesses", len(pairs),
                          labels=self._metric_labels)
        return result

    # -- clock / presence -------------------------------------------------
    async def fetch_clock(self) -> str:
        return format_clock(await self.rounds.remaining())

    async def clock_payload(self) -> Dict[str, object]:
        """One WS /clock tick (main.py:61-67)."""
        return {
            "time": await self.fetch_clock(),
            "reset": await self.rounds.reset_flag(),
            "conns": await self.sessions.player_count(),
        }
