"""Descriptive-word mask selection.

The reference picks the ``num_masked`` most "descriptive" words of the prompt
via NLTK POS-tagging (keep adjectives/adverbs/nouns), word2vec L2 distance
from the mean vector, and a TF-IDF weight that is provably a no-op (fit on a
single sentence → idf ≡ 1; reference utils.py:74-110, SURVEY.md §2 #9).

This implementation is self-contained (no NLTK corpus downloads at runtime):

- candidate filter = the vendored POS classifier (engine/pos.py): word-like
  tokens that are not function words, not verbs (lexicon + morphology +
  attributive-position rules), and not mid-sentence capitalized proper
  nouns — the reference's JJ*/RB*/NN/NNS tag filter re-derived without
  NLTK model downloads; agreement with hand-annotated NLTK-convention
  tags is measured by eval/masking_agreement.py (see PARITY.md);
- descriptiveness = L2 distance of the word's embedding from the mean
  embedding of all candidates, exactly the reference's ``semantic_distance``
  signal (utils.py:74-79) but computed with the framework's batched TPU
  embedding backend rather than per-word gensim lookups;
- duplicate words keep their own positions (the reference's
  ``words.index(...)`` first-occurrence bug, utils.py:102, is fixed).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import numpy as np

from cassmantle_tpu.utils.text import is_wordlike, tokenize_words

# Function words & other non-descriptive tokens, lowercased. Compact on
# purpose: the embedding-distance signal does the heavy lifting.
STOPWORDS = frozenset(
    """a an the and or but nor so yet for of in on at by to from with without
    into onto over under above below between among through during before
    after again further then once here there all any both each few more most
    other some such no not only own same than too very can will just should
    now i you he she it we they me him her us them my your his its our their
    this that these those am is are was were be been being have has had
    having do does did doing would could shall may might must ought as if
    while because until about against what which who whom whose when where
    why how out up down off
    """.split()
)

_MIN_WORD_LEN = 3

EmbedFn = Callable[[Sequence[str]], np.ndarray]


def candidate_indices(tokens: Sequence[str]) -> List[int]:
    """Indices of tokens eligible for masking: POS-maskable (JJ*/RB*/
    NN/NNS by the vendored classifier) and not too short to guess."""
    from cassmantle_tpu.engine.pos import is_maskable

    return [
        i for i, tok in enumerate(tokens)
        if len(tok) >= _MIN_WORD_LEN and is_maskable(tokens, i)
    ]


def conservative_candidate_indices(tokens: Sequence[str]) -> List[int]:
    """Mask candidacy for DRIFTED registers (present-tense/imperative
    prose — engine/pos.register_drift): the classifier's positional
    verb disambiguation is untrustworthy there (40-47% agreement,
    PARITY.md), so instead of trusting position, drop EVERY
    verb-homograph surface form. Conservative in the direction that
    matters — the reference's filter never masks verbs; a too-small
    candidate set just falls through to select_masks' longest-word
    backfill."""
    from cassmantle_tpu.engine.pos import could_be_verb

    return [i for i in candidate_indices(tokens)
            if not could_be_verb(tokens[i].lower())]


def select_masks(
    tokens: Sequence[str],
    embed: EmbedFn,
    num_masked: int = 2,
) -> List[int]:
    """Pick ``num_masked`` token indices to mask, sorted ascending.

    ``embed`` maps a list of words to an (n, d) float array — in production
    the MiniLM TPU scorer's embedding function, in tests any deterministic
    stub. Falls back to the longest candidates if fewer than ``num_masked``
    distinct embeddable words exist.

    Runtime register guard (VERDICT r5 weak #3): generated prose that
    reads present-tense or imperative — where the vendored POS
    classifier's mask agreement collapses to 40-47% — switches to the
    conservative candidate set (every verb-homograph dropped) instead
    of degrading silently; the swap is counted at
    ``masking.register_drift`` on /metrics.
    """
    from cassmantle_tpu.engine.pos import register_drift

    if register_drift(tokens):
        from cassmantle_tpu.utils.logging import metrics

        metrics.inc("masking.register_drift")
        cands = conservative_candidate_indices(tokens)
    else:
        cands = candidate_indices(tokens)
    if not cands:
        # degenerate prompt: mask the longest word-like tokens
        wordy = [i for i, t in enumerate(tokens) if is_wordlike(t)]
        wordy.sort(key=lambda i: len(tokens[i]), reverse=True)
        return sorted(wordy[:num_masked])
    words = [tokens[i].lower() for i in cands]
    vecs = np.asarray(embed(words), dtype=np.float32)
    if vecs.ndim != 2 or vecs.shape[0] != len(words):
        raise ValueError(
            f"embed returned shape {vecs.shape} for {len(words)} words"
        )
    mean = vecs.mean(axis=0, keepdims=True)
    dist = np.linalg.norm(vecs - mean, axis=1)
    # Prefer distinct words: among duplicates keep the first position so two
    # masks never share an answer.
    order = np.argsort(-dist, kind="stable")
    chosen: List[int] = []
    seen_words = set()
    for j in order:
        w = words[j]
        if w in seen_words:
            continue
        seen_words.add(w)
        chosen.append(cands[j])
        if len(chosen) == num_masked:
            break
    # backfill with duplicates if the prompt had too few distinct words
    for j in order:
        if len(chosen) == num_masked:
            break
        if cands[j] not in chosen:
            chosen.append(cands[j])
    return sorted(chosen)


def build_prompt_state(
    prompt_text: str, embed: EmbedFn, num_masked: int = 2
) -> Dict[str, object]:
    """Prompt text -> the stored round-prompt dict (reference
    ``construct_prompt_dict``, utils.py:106-110): word tokens + mask indices.
    """
    tokens = tokenize_words(prompt_text)
    masks = select_masks(tokens, embed, num_masked)
    return {"tokens": list(tokens), "masks": masks}
