"""Benchmark: SD1.5-geometry 512x512 txt2img, 50-step DDIM, images/sec/chip.

The BASELINE.md north-star config: full serving pipeline (CLIP encode →
50-step CFG DDIM scan → VAE decode → uint8) on one chip. Weights are
deterministic random unless checkpoints exist under ``weights/`` —
throughput is weight-independent.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target: 4 images/sec/chip (BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_IMAGES_PER_SEC = 4.0
BATCH = 4
TIMED_ROUNDS = 3


def main() -> None:
    import jax

    # Persistent compile cache: first bench run pays the XLA compile, every
    # later run (and the driver's) reuses it.
    try:
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = FrameworkConfig()
    weights_dir = "weights" if len(sys.argv) < 2 else sys.argv[1]
    pipe = Text2ImagePipeline(cfg, weights_dir=weights_dir)

    prompts = [
        "A watercolor style piece depicting: a lighthouse over a stormy sea",
        "An art deco style piece depicting: a caravan crossing silver dunes",
        "A stained glass style piece depicting: an orchard under two moons",
        "A vaporwave style piece depicting: a night train between cities",
    ][:BATCH]

    # warmup / compile
    pipe.generate(prompts, seed=0)

    n_images = 0
    t0 = time.perf_counter()
    for i in range(TIMED_ROUNDS):
        images = pipe.generate(prompts, seed=i + 1)
        n_images += images.shape[0]
    elapsed = time.perf_counter() - t0

    n_chips = jax.local_device_count()
    ips_per_chip = n_images / elapsed / max(1, n_chips)
    print(json.dumps({
        "metric": "sd15_512px_ddim50_images_per_sec_per_chip",
        "value": round(ips_per_chip, 4),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / BASELINE_IMAGES_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
