"""Benchmark: SD1.5-geometry 512x512 txt2img, 50-step DDIM, images/sec/chip.

The BASELINE.md north-star config: full serving pipeline (CLIP encode →
50-step CFG DDIM scan → VAE decode → uint8) on one chip. Weights are
deterministic random unless checkpoints exist under ``weights/`` —
throughput is weight-independent.

Default run prints ONE JSON line: {"metric", "value", "unit",
"vs_baseline"} for the north-star metric. ``--suite`` additionally runs
the full BASELINE.md workload ladder (MiniLM scorer, GPT-2 greedy decode,
SD1.5-512, SDXL-1024 data-parallel, end-to-end round with 1k concurrent
guesses) and writes all results to BENCH_SUITE.json; the north-star line
is still the last stdout line.

Every suite entry snapshots the metrics registry before/after and
attaches the nonzero **counter deltas** of the diagnosis counters
(jit (re)compiles — the sentinel is armed per entry — cache
hits/misses, staged-serving preemptions, dispatch hangs/deadlines/
rejections) to its record, so a BENCH_SUITE.json trajectory explains
its own regressions without a rerun.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time

import os

BASELINE_IMAGES_PER_SEC = 4.0
BATCH = int(os.environ.get("BENCH_BATCH", "4"))
TIMED_ROUNDS = int(os.environ.get("BENCH_ROUNDS", "3"))


PROMPTS = [
    "A watercolor style piece depicting: a lighthouse over a stormy sea",
    "An art deco style piece depicting: a caravan crossing silver dunes",
    "A stained glass style piece depicting: an orchard under two moons",
    "A vaporwave style piece depicting: a night train between cities",
]


def probe_device(attempt_timeout_s: float = 90.0) -> None:
    """Wait for the accelerator, polling until ``BENCH_PROBE_DEADLINE_S``.

    A dead device tunnel makes the first jax backend init block
    indefinitely (not error); probing in a subprocess turns that into a
    timed, attributable failure. Tunnel outages last hours while the
    driver invokes this file exactly ONCE per round — a single one-shot
    probe forfeits the round's only externally-credible perf channel
    whenever that invocation lands inside an outage window. So: retry
    every ~60 s until the deadline (default 45 min, env-tunable),
    logging every attempt; a still-failing exit carries the attempt
    count and window, proving the outage spanned the whole window.

    A *deterministic* failure (import error, bad flag — fails fast with
    a nonzero exit rather than hanging) is not an outage and surfaces
    after two consecutive fast failures instead of burning the window.
    """
    import datetime
    import subprocess

    deadline_s = float(os.environ.get("BENCH_PROBE_DEADLINE_S", "2700"))
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((64, 64)); (x @ x).block_until_ready(); "
            "print(jax.devices())")

    def now() -> str:
        return datetime.datetime.now(
            datetime.timezone.utc).strftime("%H:%M:%SZ")

    t_start = time.monotonic()
    attempts = 0
    fast_failures = 0
    repeat_failures = 0
    last_stderr = None
    last_diag = ""
    while True:
        attempts += 1
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                timeout=attempt_timeout_s, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            proc = None
            last_diag = (f"attempt hung past {attempt_timeout_s:.0f}s "
                         f"(backend init blocked — tunnel down)")
            fast_failures = 0
            repeat_failures = 0
            last_stderr = None
        took = time.monotonic() - t0
        if proc is not None:
            if proc.returncode == 0:
                print(f"[probe] {now()} attempt {attempts}: device up "
                      f"({took:.1f}s)", file=sys.stderr)
                return
            last_diag = f"exit {proc.returncode}: {proc.stderr[-500:]}"
            # two strikes for fast failures, three for slow ones that
            # fail IDENTICALLY (e.g. a runtime version mismatch raised
            # after a slow init) — either way deterministic, not outage
            fast_failures = fast_failures + 1 if took < 10.0 else 0
            repeat_failures = (repeat_failures + 1
                               if proc.stderr == last_stderr else 1)
            last_stderr = proc.stderr
            if fast_failures >= 2 or repeat_failures >= 3:
                sys.exit("device probe failed deterministically "
                         f"({attempts} attempts, not an outage): "
                         f"{last_diag}")
        elapsed = time.monotonic() - t_start
        print(f"[probe] {now()} attempt {attempts} failed "
              f"({elapsed / 60:.1f}/{deadline_s / 60:.0f} min): "
              f"{last_diag}", file=sys.stderr)
        if elapsed + 5.0 >= deadline_s:
            sys.exit(
                f"device probe: {attempts} attempts over "
                f"{elapsed / 60:.1f} min, all failed — accelerator "
                f"tunnel down for the entire probe window; "
                f"last: {last_diag}")
        time.sleep(max(0.0, 60.0 - took))


def _setup_jax():
    import jax

    from cassmantle_tpu.utils.compile_cache import enable_compile_cache

    # Persistent compile cache: first bench run pays the XLA compile, every
    # later run (and the driver's) reuses it.
    enable_compile_cache()
    return jax


def _bench_txt2img(config_factory, metric: str, weights_dir: str,
                   batch: int = None) -> dict:
    """Shared txt2img harness (one timing methodology for every image
    preset): build pipeline, warmup compile, TIMED_ROUNDS batches,
    report images/sec/chip."""
    jax = _setup_jax()
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    batch = BATCH if batch is None else batch
    pipe = Text2ImagePipeline(config_factory(), weights_dir=weights_dir)
    prompts = (PROMPTS * ((batch + len(PROMPTS) - 1) // len(PROMPTS)))[:batch]
    pipe.generate(prompts, seed=0)  # warmup / compile

    n_images = 0
    t0 = time.perf_counter()
    for i in range(TIMED_ROUNDS):
        images = pipe.generate(prompts, seed=i + 1)
        n_images += images.shape[0]
    elapsed = time.perf_counter() - t0

    ips_per_chip = n_images / elapsed / max(1, jax.local_device_count())
    return {
        "metric": metric,
        "value": round(ips_per_chip, 4),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / BASELINE_IMAGES_PER_SEC, 4),
        "batch": batch,
        "timed_rounds": TIMED_ROUNDS,
    }


# Fixed-config physical ceiling for the SD1.5 DDIM-50 config: the
# FULL-PIPELINE analytic cost (82.87 TF/image — CLIP + 100 CFG UNet
# forwards + VAE decode, docs/PERF_NOTES.md "Full-pipeline accounting")
# on a ~197 TFLOP/s bf16 v5e chip = ~2.38 img/s at MFU 1.0. Earlier
# rounds used the UNet-only 2.51, which overstated headroom by ~6%
# (PERF_NOTES calls this out); BENCH_CEILING_IPS still overrides.
SD15_CEILING_IPS_DEFAULT = 2.38


def _sd15_ceiling_context(res: dict) -> dict:
    """Attach the fixed-config ceiling fraction to an SD1.5 DDIM-50
    entry (shared by the `sd15` north star and its `sd15_fusedconv`
    A/B arm so both report against the SAME ceiling)."""
    ceiling = float(os.environ.get("BENCH_CEILING_IPS",
                                   str(SD15_CEILING_IPS_DEFAULT)))
    if ceiling > 0 and "value" in res:
        res["fraction_of_fixed_config_ceiling"] = round(
            res["value"] / ceiling, 4)
    return res


def bench_sd15(weights_dir: str) -> dict:
    """North-star: SD1.5 512², 50-step CFG DDIM, images/sec/chip.
    Within the fixed DDIM-50 config, optimization is measured as
    fraction of the analytic full-pipeline ceiling
    (SD15_CEILING_IPS_DEFAULT), not of the workload-level 4.0."""
    from cassmantle_tpu.config import FrameworkConfig

    return _sd15_ceiling_context(_bench_txt2img(
        FrameworkConfig, "sd15_512px_ddim50_images_per_sec_per_chip",
        weights_dir))


def bench_sd15_b8(weights_dir: str) -> dict:
    """Batch-size A/B vs the `sd15` entry: same fixed DDIM-50 config at
    DOUBLE the batch (2x BENCH_BATCH, so the comparison survives an env
    override) — the cheapest MXU-utilization lever; if img/s/chip rises
    here, the serving batch should too. Both entries record ``batch``."""
    from cassmantle_tpu.config import FrameworkConfig

    return _bench_txt2img(
        FrameworkConfig, "sd15_512px_ddim50_2xbatch_images_per_sec_per_chip",
        weights_dir, batch=2 * BATCH)


def bench_sd15_fast(weights_dir: str) -> dict:
    """Fast-serving preset: DPM-Solver++(2M) @ 25 steps (the quality-
    equivalent low-latency sampler — BASELINE.md's workload-level path
    past the bf16 FLOP ceiling of the fixed 50-step DDIM config)."""
    from cassmantle_tpu.config import fast_serving_config

    return _bench_txt2img(
        fast_serving_config, "sd15_512px_dpmpp25_images_per_sec_per_chip",
        weights_dir)


def bench_sd15_deepcache(weights_dir: str) -> dict:
    """Deep-feature-reuse preset: full DDIM-50 trajectory, alternate
    steps reusing the previous step's deepest-level activations (~60%
    of the UNet compute; ops/ddim.py, models/unet.py)."""
    from cassmantle_tpu.config import deepcache_serving_config

    return _bench_txt2img(
        deepcache_serving_config,
        "sd15_512px_ddim50_deepcache_images_per_sec_per_chip",
        weights_dir)


def bench_sd15_turbo(weights_dir: str) -> dict:
    """Composed preset: DPM-Solver++(2M) @ 24 steps WITH deep-feature
    reuse (~3.3x fewer UNet-FLOPs/image than DDIM-50) — the workload-
    level route to the 4 img/s/chip target (turbo_serving_config)."""
    from cassmantle_tpu.config import turbo_serving_config

    return _bench_txt2img(
        turbo_serving_config,
        "sd15_512px_dpmpp24_deepcache_images_per_sec_per_chip",
        weights_dir)


def bench_sdxl_turbo(weights_dir: str) -> dict:
    """SDXL-1024 with the composed turbo path (DPM++(2M)@24 +
    deepcache) — the samplers/deepcache machinery is shared with SD1.5
    (serving/pipeline.py:run_cfg_denoise), so the workload-level
    speedups apply to the reference's actual image model too."""
    import dataclasses as _dc

    from cassmantle_tpu.config import sdxl_config

    def cfg():
        base = sdxl_config()
        return base.replace(sampler=_dc.replace(
            base.sampler, kind="dpmpp_2m", num_steps=24, deepcache=True))

    return _bench_sdxl_with(
        cfg, "sdxl_1024px_dpmpp24_deepcache_images_per_sec_per_chip",
        weights_dir)


def bench_sd15_fusedconv(weights_dir: str) -> dict:
    """A/B arm for the fused GroupNorm+SiLU+conv3x3 Pallas path on the
    fixed DDIM-50 config (config.fusedconv_serving_config): identical
    trajectory and param tree as the `sd15` entry — UNet ResBlock convs
    run through ops/fused_conv.py with 128-lane channel padding instead
    of the XLA norm->act->conv sequence. Compare directly against the
    `sd15` entry; the analytic case (one HBM round trip of the level
    activation saved per conv, full MXU tile fill at the 320/960
    levels, +3.4% padding FLOPs) is in docs/PERF_NOTES.md. Parity is
    pinned by tests/test_fused_conv.py; CASSMANTLE_NO_FUSED_CONV=1 is
    the kill switch if a TPU generation rejects the kernel."""
    from cassmantle_tpu.config import fusedconv_serving_config

    return _sd15_ceiling_context(_bench_txt2img(
        fusedconv_serving_config,
        "sd15_512px_ddim50_fusedconv_images_per_sec_per_chip",
        weights_dir))


def _poisson_mixed_schedule(n: int, rate_rps: float, seed: int = 0):
    """Deterministic Poisson arrival offsets + mixed request sizes for
    the staged-serving A/B: both arms replay the SAME schedule, so the
    comparison isolates the serving discipline, not the load draw.
    Sizes mix 2:1 single-image and two-image requests (the game's
    round-generation shape vs. a player-pair burst)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=n)
    arrivals = np.concatenate([[0.0], np.cumsum(gaps)[:-1]])
    sizes = rng.choice([1, 1, 2], size=n)
    return arrivals, sizes


def _mixed_load_arm(pipe, arrivals, sizes):
    """Replay one arm of the mixed-load A/B: request i enters at
    ``arrivals[i]`` (open-loop — late completions do NOT delay later
    arrivals, exactly how real traffic behaves) and its latency is
    submit → uint8 batch. Returns (elapsed_s, latencies_s, images)."""
    from concurrent.futures import ThreadPoolExecutor

    n = len(arrivals)
    lats = [0.0] * n
    images = [0] * n
    start = time.perf_counter()

    def one(i: int) -> None:
        delay = start + float(arrivals[i]) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        prompts = (PROMPTS * 2)[i % len(PROMPTS):][: int(sizes[i])]
        t0 = time.perf_counter()
        out = pipe.generate(prompts, seed=100 + i)
        lats[i] = time.perf_counter() - t0
        images[i] = out.shape[0]

    with ThreadPoolExecutor(max_workers=n) as ex:
        futs = [ex.submit(one, i) for i in range(n)]
        for f in futs:
            f.result()
    return time.perf_counter() - start, lats, sum(images)


def bench_sd15_staged(weights_dir: str) -> dict:
    """Mixed-load A/B for stage-disaggregated serving
    (serving/stages.py, config.staged_serving_config): Poisson arrivals
    of mixed-size requests through ONE pipeline, staged vs monolithic.
    The monolithic arm runs the SAME pipeline object with the
    CASSMANTLE_NO_STAGED_SERVING kill switch set, so params, tokenizer,
    and compiled monolithic jits are held constant — the A/B isolates
    the serving discipline (step-boundary admission vs whole-image
    dispatch-lock FIFO). Reports per-arm throughput and p50/p99
    REQUEST latency plus the staged arm's mean denoise-slot occupancy
    (slot_steps / steps x capacity). Solo outputs are bit-identical
    between arms (tests/test_stages.py), so quality needs no re-gate.

    Env: BENCH_STAGED_REQUESTS (default 12), BENCH_STAGED_RATE
    (arrivals/sec; default 0.6 ≈ 0.85 img/s offered at the 1.4
    images/request mix — ~70% of the measured v5e sd15 capacity, the
    regime where queueing exists but neither arm saturates; raise it
    toward capacity during the hardware window to map the knee),
    BENCH_STAGED_SLOTS (smoke-geometry slot count), and
    BENCH_STAGED_SMOKE_GEOMETRY=1 swaps in the 64px/4-step test
    geometry so the CPU harness smoke finishes — those numbers exercise
    the scheduler, not the MXU, and are NOT hardware evidence (the
    BENCH_SUITE.json annotation records this)."""
    import numpy as np

    _setup_jax()
    from cassmantle_tpu.config import staged_serving_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    n = int(os.environ.get("BENCH_STAGED_REQUESTS", "12"))
    rate = float(os.environ.get("BENCH_STAGED_RATE", "0.6"))
    if os.environ.get("BENCH_STAGED_SMOKE_GEOMETRY", "").lower() in (
            "1", "true", "yes", "on"):
        import dataclasses as _dc

        from cassmantle_tpu.config import test_config

        slots = int(os.environ.get("BENCH_STAGED_SLOTS", "4"))

        def config_factory():
            base = test_config()
            return base.replace(serving=_dc.replace(
                base.serving, staged_serving=True, denoise_slots=slots))
    else:
        config_factory = staged_serving_config

    pipe = Text2ImagePipeline(config_factory(), weights_dir=weights_dir)
    arrivals, sizes = _poisson_mixed_schedule(n, rate)

    base_stats = {}

    def run_arm(monolithic: bool):
        key = "CASSMANTLE_NO_STAGED_SERVING"
        prev = os.environ.pop(key, None)
        if monolithic:
            os.environ[key] = "1"
        try:
            # warmup compiles for both request sizes before timing
            pipe.generate(PROMPTS[:1], seed=0)
            pipe.generate(PROMPTS[:2], seed=0)
            if not monolithic:
                # snapshot AFTER warmup so the occupancy derivation
                # covers only the loaded phase, not two solo warmups
                base_stats.update(pipe._staged_server().stats)
            return _mixed_load_arm(pipe, arrivals, sizes)
        finally:
            os.environ.pop(key, None)
            if prev is not None:
                os.environ[key] = prev

    def arm_stats(elapsed, lats, images):
        s = np.sort(np.asarray(lats))
        return {
            "images_per_sec": round(images / elapsed, 4),
            "request_p50_s": round(float(s[len(s) // 2]), 3),
            "request_p99_s": round(float(s[int(len(s) * 0.99)]), 3),
        }

    mono = arm_stats(*run_arm(monolithic=True))
    staged = arm_stats(*run_arm(monolithic=False))
    srv = pipe._staged_server()
    d_steps = srv.stats["steps"] - base_stats["steps"]
    d_slot_steps = srv.stats["slot_steps"] - base_stats["slot_steps"]
    if d_steps > 0:
        staged["mean_slot_occupancy"] = round(
            d_slot_steps / (d_steps * srv.capacity), 4)
    srv.stop()
    return {
        "metric": "sd15_512px_ddim50_staged_mixedload_images_per_sec",
        "value": staged["images_per_sec"],
        "unit": "images/sec",
        "vs_baseline": None,
        "ab_versus": "monolithic (same pipeline, kill-switch arm)",
        "requests": n,
        "arrival_rate_rps": rate,
        "mixed_sizes": {str(k): int(v) for k, v in
                        zip(*np.unique(sizes, return_counts=True))},
        "staged": staged,
        "monolithic": mono,
    }


def bench_sd15_int8(weights_dir: str) -> dict:
    """A/B arm for weights-only int8 UNet on the fixed DDIM-50 config:
    same trajectory as `sd15`, int8 weight streaming (halved per-step
    HBM weight reads, dequant fused in-jit — ops/quant.py). Compare
    directly against the `sd15` entry; quality re-gated via
    tools/clip_report.py when enabled in serving."""
    import dataclasses as _dc

    from cassmantle_tpu.config import FrameworkConfig

    def cfg():
        base = FrameworkConfig()
        return base.replace(models=_dc.replace(base.models, unet_int8=True))

    return _bench_txt2img(
        cfg, "sd15_512px_ddim50_int8unet_images_per_sec_per_chip",
        weights_dir)


def _encprop_smoke_geometry() -> bool:
    return os.environ.get("BENCH_ENCPROP_SMOKE_GEOMETRY", "").lower() in (
        "1", "true", "yes", "on")


def _smoke_clip_harness(weights_dir: str, smoke: bool):
    """The quality-report harness the A/B entries share: real CLIP
    weights off-smoke, the tiny fixed test geometry on the CPU smoke
    (one definition so the encprop and lcm entries can never gate with
    different harnesses)."""
    from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness

    if not smoke:
        return ClipSimilarityHarness(weights_dir=weights_dir)

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.models.clip_vision import ClipVisionConfig

    return ClipSimilarityHarness(
        text_cfg=test_config().models.clip_text,
        vision_cfg=ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4,
            projection_dim=64),
        pad_len=16)


def _bench_encprop_ab(metric: str, weights_dir: str, sdxl: bool) -> dict:
    """Same-seed A/B for encoder propagation (the `sd15_encprop` /
    `sdxl_encprop` entries): ONE harness builds the full-forward arm
    and the encprop arm (full forwards only at key steps + batched
    propagated-decoder forwards + fused VAE ResBlocks), runs both on
    the SAME prompts and seeds, and reports img/s per arm plus the
    eval/clip_parity.py quality report between the two arms' same-seed
    outputs — throughput and the quality cost of the approximation in
    one record. The runner attaches the `pipeline.encprop_*` diagnosis
    counter deltas like every round-14+ entry.

    Env: BENCH_ENCPROP_SMOKE_GEOMETRY=1 swaps in the 64px test
    geometry at 12 steps (stride 4: 3 key + 9 propagated) so the CPU
    harness smoke exercises the real scan structure — those numbers
    exercise the scheduler and the batched decoder dispatch, not the
    MXU, and are NOT hardware evidence (the BENCH_SUITE.json
    annotation records this). BENCH_ENCPROP_REPS overrides the timed
    rep count."""
    import dataclasses as _dc

    jax = _setup_jax()
    from cassmantle_tpu.eval.clip_parity import encprop_quality_report
    from cassmantle_tpu.ops.ddim import encprop_key_indices

    smoke = _encprop_smoke_geometry()
    if smoke:
        from cassmantle_tpu.config import test_config, test_sdxl_config

        base = test_sdxl_config() if sdxl else test_config()
        base = base.replace(sampler=_dc.replace(base.sampler, num_steps=12))
        enc_sampler = _dc.replace(base.sampler, encprop=True,
                                  encprop_stride=4, encprop_dense_steps=0)
        enc_cfg = base.replace(sampler=enc_sampler)
    else:
        from cassmantle_tpu.config import FrameworkConfig, sdxl_config

        base = sdxl_config() if sdxl else FrameworkConfig()
        enc_cfg = base.replace(
            sampler=_dc.replace(base.sampler, encprop=True),
            models=_dc.replace(base.models, vae=_dc.replace(
                base.models.vae, fused_conv=True)))

    if sdxl:
        from cassmantle_tpu.serving.sdxl import SDXLPipeline

        full_pipe = SDXLPipeline(base, weights_dir=weights_dir)
        enc_pipe = SDXLPipeline(enc_cfg, weights_dir=weights_dir,
                                share_params_with=full_pipe)
    else:
        from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

        full_pipe = Text2ImagePipeline(base, weights_dir=weights_dir)
        enc_pipe = Text2ImagePipeline(enc_cfg, weights_dir=weights_dir,
                                      share_params_with=full_pipe)

    batch = 1 if (sdxl or smoke) else BATCH
    reps = int(os.environ.get("BENCH_ENCPROP_REPS", "2" if sdxl else "3"))
    prompts = (PROMPTS * ((batch + len(PROMPTS) - 1) // len(PROMPTS))
               )[:batch]

    def run_arm(pipe):
        imgs = pipe.generate(prompts, seed=0)     # warmup compile
        t0 = time.perf_counter()
        for i in range(reps):
            imgs = pipe.generate(prompts, seed=1)  # same seed both arms
        elapsed = time.perf_counter() - t0
        ips = reps * len(prompts) / elapsed / max(
            1, jax.local_device_count())
        return ips, imgs

    full_ips, full_imgs = run_arm(full_pipe)
    enc_ips, enc_imgs = run_arm(enc_pipe)

    harness = _smoke_clip_harness(weights_dir, smoke)
    quality = encprop_quality_report(harness, enc_imgs, full_imgs, prompts)

    s = enc_cfg.sampler
    keys = len(encprop_key_indices(s.num_steps, s.encprop_stride,
                                   s.encprop_dense_steps))
    return {
        "metric": metric,
        "value": round(enc_ips, 4),
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "ab_versus": "full-forward arm (same prompts/seed, shared params)",
        "full_images_per_sec": round(full_ips, 4),
        "speedup_vs_full": round(enc_ips / full_ips, 4) if full_ips else None,
        "batch": batch,
        "timed_rounds": reps,
        "encprop": {
            "num_steps": s.num_steps, "stride": s.encprop_stride,
            "dense_steps": s.encprop_dense_steps, "key_steps": keys,
            "propagated_steps": s.num_steps - keys,
        },
        "quality": quality,
    }


def bench_sd15_encprop(weights_dir: str) -> dict:
    """A/B arm for encoder propagation on the fixed DDIM-50 SD1.5
    config (config.encprop_serving_config): full UNet forwards at the
    20 key steps, batched decoder-only forwards on the other 30 (the
    analytic bound is 67.2 vs 82.8 TF/image — docs/PERF_NOTES.md
    'Encoder propagation accounting'), fused VAE ResBlocks on the
    decode tail. Quality rides the same record via the
    eval/clip_parity.py encprop gate."""
    res = _bench_encprop_ab(
        "sd15_512px_ddim50_encprop_images_per_sec_per_chip",
        weights_dir, sdxl=False)
    # ceiling fractions only mean something at the real geometry — the
    # 64px smoke would divide toy img/s by the 512px ceiling
    return res if _encprop_smoke_geometry() else _sd15_ceiling_context(res)


def bench_sdxl_encprop(weights_dir: str) -> dict:
    """The profile-driven SDXL ceiling-gap attack (ROADMAP item 4):
    encoder propagation at 1024² — the encoder (down+mid, 43% of UNet
    FLOPs, dominated by the depth-10 transformer level) runs only at
    the 20 key steps; propagated steps run the decoder alone, batched
    per segment — plus fused VAE ResBlocks and wide-head flash VAE
    attention on the 10.47 TF decode. Analytic bound 510.6 vs 686.6
    TF/image (74%), i.e. an in-config ceiling of ~0.386 img/s/chip vs
    the full config's 0.287; `fraction_of_fixed_config_ceiling` still
    reports against the FIXED full-config ceiling so the entry reads as
    progress toward the >80%-of-ceiling target."""
    res = _bench_encprop_ab(
        "sdxl_1024px_ddim50_encprop_images_per_sec_per_chip",
        weights_dir, sdxl=True)
    res["encprop_analytic_tf_per_image"] = SDXL_ENCPROP_ANALYTIC_TF_PER_IMAGE
    res["encprop_ceiling_ips"] = SDXL_ENCPROP_CEILING_IPS
    # see bench_sd15_encprop: no ceiling fraction from the 64px smoke
    return res if _encprop_smoke_geometry() else _sdxl_ceiling_context(res)


def _lcm_smoke_geometry() -> bool:
    return os.environ.get("BENCH_LCM_SMOKE_GEOMETRY", "").lower() in (
        "1", "true", "yes", "on")


def bench_sd15_lcm(weights_dir: str) -> dict:
    """Same-seed A/B for few-step consistency serving (the `sd15_lcm`
    entry, ISSUE 15): teacher arm = the fixed DDIM-50 SD1.5 config,
    student arm = config.lcm_serving_config() — FOUR direct x0
    predictions per image through the boundary-parameterized
    consistency sampler (ops/samplers.py). Both arms run the SAME
    prompts and seeds; the record carries img/s per arm, the
    UNet-forwards-per-image delta (teacher's schedule length vs the
    `pipeline.consistency_steps` counter, verified in-entry), and the
    eval/clip_parity.py consistency quality report between the arms'
    same-seed outputs. On hardware the student arm should load a
    DISTILLED checkpoint (parallel/train.py::ConsistencyDistillTrainer
    — same tree layout as the teacher's, so it drops into weights_dir
    as unet.safetensors of its own deployment); here the arms share
    one param tree, so the quality report measures the plumbing, and
    only counts as a gate once real distilled weights are in play.

    Env: BENCH_LCM_SMOKE_GEOMETRY=1 swaps in the 64px test geometry
    (teacher at 20 steps — the few-step accounting anchor in
    docs/PERF_NOTES.md — student at 4) so the CPU smoke exercises the
    real sampler structure; those numbers exercise the scan and the
    counter plumbing, not the MXU, and are NOT hardware evidence.
    BENCH_LCM_REPS overrides the timed rep count. ``noise_tolerance``
    is carried on the record so tools/bench_diff.py treats the smoke's
    run-to-run variance honestly."""
    import dataclasses as _dc

    jax = _setup_jax()
    from cassmantle_tpu.eval.clip_parity import (
        consistency_quality_report,
    )
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    smoke = _lcm_smoke_geometry()
    if smoke:
        from cassmantle_tpu.config import test_config

        base = test_config()
        base = base.replace(sampler=_dc.replace(base.sampler,
                                                num_steps=20))
        lcm_cfg = base.replace(sampler=_dc.replace(
            base.sampler, consistency=True, num_steps=4,
            consistency_teacher_steps=20))
    else:
        from cassmantle_tpu.config import (
            FrameworkConfig,
            lcm_serving_config,
        )

        base = FrameworkConfig()
        lcm_cfg = lcm_serving_config()

    full_pipe = Text2ImagePipeline(base, weights_dir=weights_dir)
    lcm_pipe = Text2ImagePipeline(lcm_cfg, weights_dir=weights_dir,
                                  share_params_with=full_pipe)

    batch = 1 if smoke else BATCH
    reps = int(os.environ.get("BENCH_LCM_REPS", "3"))
    prompts = (PROMPTS * ((batch + len(PROMPTS) - 1) // len(PROMPTS))
               )[:batch]

    def run_arm(pipe):
        steps_before = metrics.counter_total("pipeline.consistency_steps")
        imgs = pipe.generate(prompts, seed=0)     # warmup compile
        t0 = time.perf_counter()
        for _ in range(reps):
            imgs = pipe.generate(prompts, seed=1)  # same seed both arms
        elapsed = time.perf_counter() - t0
        ips = reps * len(prompts) / elapsed / max(
            1, jax.local_device_count())
        images = (reps + 1) * len(prompts)
        forwards = (metrics.counter_total("pipeline.consistency_steps")
                    - steps_before) / images
        return ips, imgs, forwards

    full_ips, full_imgs, full_counted = run_arm(full_pipe)
    lcm_ips, lcm_imgs, lcm_counted = run_arm(lcm_pipe)
    assert full_counted == 0.0, "teacher arm must not tick the counter"
    assert lcm_counted == lcm_cfg.sampler.num_steps, (
        f"counter says {lcm_counted} consistency forwards/image, "
        f"config says {lcm_cfg.sampler.num_steps}")

    harness = _smoke_clip_harness(weights_dir, smoke)
    quality = consistency_quality_report(harness, lcm_imgs, full_imgs,
                                         prompts)

    return {
        "metric": "sd15_512px_lcm4_images_per_sec_per_chip",
        "value": round(lcm_ips, 4),
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "ab_versus": "teacher arm (same prompts/seed, shared params)",
        "full_images_per_sec": round(full_ips, 4),
        "speedup_vs_full": (round(lcm_ips / full_ips, 4)
                            if full_ips else None),
        "batch": batch,
        "timed_rounds": reps,
        # the CPU smoke measures scheduler wall clock on a shared
        # 2-core host at toy geometry — noisier than the MXU entries
        "noise_tolerance": 0.35,
        "unet_forwards_per_image": {
            "teacher": base.sampler.num_steps,
            "student": int(lcm_counted),
            "counter": "pipeline.consistency_steps",
        },
        "consistency": {
            "num_steps": lcm_cfg.sampler.num_steps,
            "teacher_steps": lcm_cfg.sampler.consistency_teacher_steps,
        },
        "quality": quality,
    }


def _w8a8_smoke_geometry() -> bool:
    return os.environ.get("BENCH_W8A8_SMOKE_GEOMETRY", "").lower() in (
        "1", "true", "yes", "on")


def _bench_w8a8_image_ab(metric: str, weights_dir: str,
                         sdxl: bool) -> dict:
    """Same-seed A/B for W8A8 quantized image serving (the `sd15_w8a8`
    / `sdxl_w8a8` entries, ISSUE 20): fp arm = the fixed DDIM-50
    schedule on the fused-conv tree, w8a8 arm = the SAME schedule with
    int8 weights AND activations at every attention/MLP projection and
    fused-conv ResBlock site (ops/quant.py W8A8 leaves through the
    ops/quant_matmul.py int8 kernels). Both arms run the SAME prompts
    and seeds; the record carries img/s per arm, the
    `pipeline.w8a8_dispatches` counter delta verified in-entry (fp arm
    silent; w8a8 arm = schedule steps per image — the proof the int8
    kernel path actually dispatched), and the eval/clip_parity.py
    w8a8 quality report between the arms' same-seed outputs.

    SD1.5 shares one param tree (Text2ImagePipeline quantizes the fp
    donor's tree at build); SDXL builds two pipelines because
    SDXLPipeline's donor contract requires matching quantization mode.

    Env: BENCH_W8A8_SMOKE_GEOMETRY=1 swaps in the 64px test geometry
    with w8a8_min_size=0 so the tiny matmuls quantize — on SD1.5 that
    config matches the committed calibration artifact's signature
    (data/act_scales.json), so the smoke also exercises the
    static-activation-scale path. Off-TPU the int8 kernels run in
    Pallas interpret mode: the smoke proves kernel-path engagement and
    epilogue numerics, not MXU throughput, and is NOT hardware
    evidence (the BENCH_SUITE.json annotation records this).
    BENCH_W8A8_REPS overrides the timed rep count."""
    import dataclasses as _dc

    jax = _setup_jax()
    from cassmantle_tpu.eval.clip_parity import w8a8_quality_report
    from cassmantle_tpu.ops import quant
    from cassmantle_tpu.utils.logging import metrics

    smoke = _w8a8_smoke_geometry()
    if smoke:
        from cassmantle_tpu.config import test_config, test_sdxl_config

        seed_cfg = test_sdxl_config() if sdxl else test_config()
        q_cfg = seed_cfg.replace(models=_dc.replace(
            seed_cfg.models,
            unet=_dc.replace(seed_cfg.models.unet, fused_conv=True),
            unet_w8a8=True, w8a8_min_size=0))
    elif sdxl:
        from cassmantle_tpu.config import sdxl_config

        seed_cfg = sdxl_config()
        q_cfg = seed_cfg.replace(models=_dc.replace(
            seed_cfg.models,
            unet=_dc.replace(seed_cfg.models.unet, fused_conv=True,
                             conv_pad_to=128),
            unet_w8a8=True))
    else:
        from cassmantle_tpu.config import w8a8_serving_config

        q_cfg = w8a8_serving_config()
    # fp arm = the w8a8 config with ONLY the quantization flags off:
    # same fused-conv tree layout, same schedule — the A/B isolates
    # quantization, and on SD1.5 lets the arms share one param tree
    base = q_cfg.replace(models=_dc.replace(
        q_cfg.models, unet_w8a8=False, lm_w8a8=False))

    if sdxl:
        from cassmantle_tpu.serving.sdxl import SDXLPipeline

        fp_pipe = SDXLPipeline(base, weights_dir=weights_dir)
        # the SDXL donor contract requires MATCHING quantization mode
        # (no lossy cross-mode join), so the w8a8 arm builds its own
        # pipeline — the loader's param cache keeps the second build
        # cheap
        q_pipe = SDXLPipeline(q_cfg, weights_dir=weights_dir)
    else:
        from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

        fp_pipe = Text2ImagePipeline(base, weights_dir=weights_dir)
        q_pipe = Text2ImagePipeline(q_cfg, weights_dir=weights_dir,
                                    share_params_with=fp_pipe)

    batch = 1 if (sdxl or smoke) else BATCH
    reps = int(os.environ.get("BENCH_W8A8_REPS", "2" if sdxl else "3"))
    prompts = (PROMPTS * ((batch + len(PROMPTS) - 1) // len(PROMPTS))
               )[:batch]

    def run_arm(pipe):
        before = metrics.counter_total("pipeline.w8a8_dispatches")
        imgs = pipe.generate(prompts, seed=0)     # warmup compile
        t0 = time.perf_counter()
        for _ in range(reps):
            imgs = pipe.generate(prompts, seed=1)  # same seed both arms
        elapsed = time.perf_counter() - t0
        ips = reps * len(prompts) / elapsed / max(
            1, jax.local_device_count())
        images = (reps + 1) * len(prompts)
        dispatched = (metrics.counter_total("pipeline.w8a8_dispatches")
                      - before) / images
        return ips, imgs, dispatched

    fp_ips, fp_imgs, fp_counted = run_arm(fp_pipe)
    q_ips, q_imgs, q_counted = run_arm(q_pipe)
    steps = q_cfg.sampler.num_steps
    assert fp_counted == 0.0, "fp arm must not tick the w8a8 counter"
    assert q_counted == steps, (
        f"counter says {q_counted} w8a8 UNet dispatches/image, "
        f"schedule says {steps}")

    harness = _smoke_clip_harness(weights_dir, smoke)
    quality = w8a8_quality_report(harness, q_imgs, fp_imgs, prompts)

    return {
        "metric": metric,
        "value": round(q_ips, 4),
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "ab_versus": ("fp arm (same prompts/seed, separate param tree "
                      "— SDXL donor contract forbids cross-mode share)"
                      if sdxl else
                      "fp arm (same prompts/seed, w8a8 tree quantized "
                      "from the shared donor)"),
        "full_images_per_sec": round(fp_ips, 4),
        "speedup_vs_full": round(q_ips / fp_ips, 4) if fp_ips else None,
        "batch": batch,
        "timed_rounds": reps,
        # the CPU smoke runs the int8 kernels in interpret mode on a
        # shared host — noisier than the MXU entries
        "noise_tolerance": 0.35,
        "w8a8": {
            "sites": quant.w8a8_site_count(q_pipe.unet_params),
            "static_act_scales": quant.w8a8_calibrated(
                q_pipe.unet_params),
            "dispatches_per_image": int(q_counted),
            "counter": "pipeline.w8a8_dispatches",
        },
        "quality": quality,
    }


def bench_sd15_w8a8(weights_dir: str) -> dict:
    """A/B arm for full W8A8 serving on the fixed DDIM-50 SD1.5 config
    (config.w8a8_serving_config): int8 weights and activations at
    every projection and fused-conv ResBlock site, static calibrated
    activation scales from data/act_scales.json when the signature
    matches, halved weight-side HBM streaming (the `t2i_w8a8`
    cost-model entry carries the analytic bytes). Quality rides the
    record via eval/clip_parity.py::w8a8_quality_report (0.98 floor —
    the `w8a8` QualityGateConfig row). CASSMANTLE_NO_W8A8=1 reverts
    bit-exactly at pipeline build."""
    return _bench_w8a8_image_ab(
        "sd15_512px_ddim50_w8a8_images_per_sec_per_chip",
        weights_dir, sdxl=False)


def bench_sdxl_w8a8(weights_dir: str) -> dict:
    """SDXL twin of `sd15_w8a8`: the 1024² DDIM-50 config served W8A8
    (sdxl_config + fused_conv/128-lane padding + unet_w8a8 — the
    `sdxl_w8a8` cost-model entry). The arms are two pipelines because
    the SDXL donor contract requires matching quantization mode;
    quality gates via the `sdxl_w8a8` QualityGateConfig row."""
    return _bench_w8a8_image_ab(
        "sdxl_1024px_ddim50_w8a8_images_per_sec_per_chip",
        weights_dir, sdxl=True)


def bench_scorer(weights_dir: str) -> dict:
    """BASELINE ladder #1: MiniLM guess scorer, 1k pairs coalesced.

    Guesses are UNIQUE per rep (fresh misses — the device encode is
    what's being measured) while the 6 answer words repeat, matching
    real round traffic: the answer side rides the embed LRU
    (scorer.embed_cache_hits), so the device batch is ~half the text
    count. Reusing guess words here would let the cache absorb the
    whole workload and turn the entry into a dict-lookup benchmark."""
    _setup_jax()
    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    cfg = FrameworkConfig()
    scorer = EmbeddingScorer(cfg.models.minilm, weights_dir=weights_dir,
                             batch_buckets=cfg.serving.score_batch_sizes)
    words = ["stormy", "silver", "ancient", "quiet", "glass", "velvet"]

    def make_pairs(rep: int):
        return [(f"guess{rep}_{i}", words[i % 6]) for i in range(1000)]

    scorer.similarity(make_pairs(-1))  # warmup

    # best-of-reps = steady-state throughput (robust to one-off host or
    # tunnel stalls; every rep is a full coalesced batch)
    best = float("inf")
    for rep in range(5):
        pairs = make_pairs(rep)
        t0 = time.perf_counter()
        scorer.similarity(pairs)
        best = min(best, time.perf_counter() - t0)
    gps = len(pairs) / best
    return {
        "metric": "minilm_guess_scorings_per_sec",
        "value": round(gps, 1),
        "unit": "pairs/sec",
        "vs_baseline": None,
        # bench_diff regression gate (tools/bench_diff.py): best-of-5
        # coalesced batches still swing with host contention
        "noise_tolerance": 0.25,
    }


def _bench_gpt2_with(seeds, metric: str, weights_dir: str,
                     config_factory=None) -> dict:
    """Shared GPT-2 decode harness (one timing methodology for the
    single-prompt, batched, and speculative entries): warmup compile, 5
    best-of reps through decode_ids_batch (decode_ids is its B=1 case),
    aggregate tokens ACTUALLY generated per second (gen_len stops at
    EOS). A config with spec_decode on annotates the measured accept
    rate — the number that says whether the draft paid for itself."""
    jax = _setup_jax()
    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    cfg = (config_factory or FrameworkConfig)()
    gen = PromptGenerator(cfg, weights_dir=weights_dir)
    gen.decode_ids_batch(seeds, max_new_tokens=96)  # warmup

    tps = 0.0
    for _ in range(5):
        t0 = time.perf_counter()
        _, gen_len = gen.decode_ids_batch(seeds, max_new_tokens=96)
        n = int(jax.block_until_ready(gen_len).sum())
        tps = max(tps, n / (time.perf_counter() - t0))
    res = {
        "metric": metric,
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,
    }
    if len(seeds) > 1:
        res["batch"] = len(seeds)
    if gen.last_spec_stats is not None:
        res["spec_accept_rate"] = round(
            gen.last_spec_stats["accept_rate"], 4)
        res["spec_chunks"] = gen.last_spec_stats["chunks"]
    return res


def bench_gpt2(weights_dir: str) -> dict:
    """BASELINE ladder #2: GPT-2-small greedy decode, tokens/sec."""
    return _bench_gpt2_with(
        ["The lighthouse keeper walked down the winding stair"],
        "gpt2_greedy_tokens_per_sec", weights_dir)


def bench_gpt2_b4(weights_dir: str) -> dict:
    """Batched-decode A/B vs the `gpt2` entry: 4 prompts through ONE
    decode_ids_batch dispatch (the prompt-queue serving path,
    serving/pipeline.py BATCH_BUCKETS; all four seeds share the
    32-token prompt bucket) — aggregate tokens/sec should scale well
    past the single-prompt number because the per-step matmuls go from
    M=1 to M=4 on the same weights stream."""
    return _bench_gpt2_with(
        ["The lighthouse keeper walked down the winding stair",
         "A caravan crossed the silver dunes at dawn",
         "The night train rattled between sleeping cities",
         "An orchard bloomed under two pale moons"],
        "gpt2_greedy_batch4_tokens_per_sec", weights_dir)


def bench_gpt2_spec(weights_dir: str) -> dict:
    """A/B arm for speculative decoding vs the `gpt2` entry: same
    prompt, same greedy output BY CONSTRUCTION (exact argmax acceptance,
    tests/test_spec_decode.py pins bit-parity), decoded through
    ops/decode.py::speculative_decode with the self-drafting n-gram
    draft (config.spec_decode_serving_config — zero extra HBM, no draft
    checkpoint). The entry annotates ``spec_accept_rate``: tokens/sec
    rises over `gpt2` roughly by accept_rate x gamma per verify forward
    (docs/PERF_NOTES.md "LM decode accounting"), so a low accept rate on
    the real checkpoint is the signal to switch ``spec_decode.mode`` to
    "draft_model". CASSMANTLE_NO_SPEC_DECODE=1 is the kill switch."""
    from cassmantle_tpu.config import spec_decode_serving_config

    return _bench_gpt2_with(
        ["The lighthouse keeper walked down the winding stair"],
        "gpt2_spec_ngram_tokens_per_sec", weights_dir,
        config_factory=spec_decode_serving_config)


def bench_gpt2_w8a8(weights_dir: str) -> dict:
    """Same-seed A/B for the W8A8 prompt LM vs the fp `gpt2` path
    (ISSUE 20): both arms decode the SAME seed through
    decode_ids_batch with the same methodology as `_bench_gpt2_with`
    (warmup compile, 5 best-of reps, tokens actually generated per
    second). The w8a8 arm quantizes every GPT-2 block projection
    (qkv/out/fc1/fc2) to int8 with PER-TOKEN activation row scales
    computed in-graph (no calibration artifact — models/gpt2.py
    hardcodes act_per_token), so decode numerics track each token's
    own dynamic range. The record carries tokens/sec per arm, the
    `pipeline.w8a8_dispatches` counter delta verified in-entry (one
    tick per bucket-group decode dispatch: fp arm silent, w8a8 arm =
    warmup + timed reps — the proof the int8 kernel path served the
    tokens), and greedy token agreement between the arms as the
    quality report (advisory on random-init weights; on the real
    checkpoint a low agreement is the signal to re-examine per-token
    scale clipping).

    Env: BENCH_W8A8_SMOKE_GEOMETRY=1 swaps in the tiny test GPT-2 with
    w8a8_min_size=0 — off-TPU the int8 kernels run in Pallas interpret
    mode, far too slow for the full GPT-2-small decode on a CPU
    smoke."""
    import dataclasses as _dc

    import numpy as np

    jax = _setup_jax()
    from cassmantle_tpu.serving.pipeline import PromptGenerator
    from cassmantle_tpu.utils.logging import metrics

    smoke = _w8a8_smoke_geometry()
    if smoke:
        from cassmantle_tpu.config import test_config

        base = test_config()
        max_new = 16
        reps = 3
    else:
        from cassmantle_tpu.config import FrameworkConfig

        base = FrameworkConfig()
        max_new = 96
        reps = 5
    q_cfg = base.replace(models=_dc.replace(
        base.models, lm_w8a8=True,
        w8a8_min_size=0 if smoke else base.models.w8a8_min_size))
    seeds = ["The lighthouse keeper walked down the winding stair"]

    def run_arm(cfg):
        from cassmantle_tpu.ops import quant

        gen = PromptGenerator(cfg, weights_dir=weights_dir)
        before = metrics.counter_total("pipeline.w8a8_dispatches")
        gen.decode_ids_batch(seeds, max_new_tokens=max_new)  # warmup
        tps, ids, gen_len = 0.0, None, None
        for _ in range(reps):
            t0 = time.perf_counter()
            ids, gen_len = gen.decode_ids_batch(
                seeds, max_new_tokens=max_new)
            n = int(jax.block_until_ready(gen_len).sum())
            tps = max(tps, n / (time.perf_counter() - t0))
        dispatches = int(metrics.counter_total(
            "pipeline.w8a8_dispatches") - before)
        sites = quant.w8a8_site_count(gen.params)
        return tps, np.asarray(ids)[0], int(np.asarray(gen_len)[0]), \
            dispatches, sites, bool(gen.loaded_real_weights)

    fp_tps, fp_ids, fp_len, fp_disp, _, _ = run_arm(base)
    q_tps, q_ids, q_len, q_disp, q_sites, real = run_arm(q_cfg)
    assert fp_disp == 0, "fp arm must not tick the w8a8 counter"
    assert q_disp == reps + 1, (
        f"counter says {q_disp} w8a8 decode dispatches, "
        f"arm ran {reps + 1} (warmup + {reps} timed)")
    assert q_sites > 0, "w8a8 arm quantized zero LM sites"

    # greedy token agreement over the shorter arm's generated tokens:
    # the quality report for an LM A/B (images have CLIP; decode has
    # exact token identity)
    n_cmp = min(fp_len, q_len)
    agree = float(np.mean(fp_ids[:n_cmp] == q_ids[:n_cmp])) \
        if n_cmp else 0.0

    return {
        "metric": "gpt2_w8a8_tokens_per_sec",
        "value": round(q_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "ab_versus": "fp arm (same seed text, greedy, same bucket)",
        "full_tokens_per_sec": round(fp_tps, 1),
        "speedup_vs_full": round(q_tps / fp_tps, 4) if fp_tps else None,
        "max_new_tokens": max_new,
        "noise_tolerance": 0.35,
        "w8a8": {
            "sites": q_sites,
            "act_scales": "per-token (dynamic, in-graph)",
            "decode_dispatches": q_disp,
            "counter": "pipeline.w8a8_dispatches",
        },
        "quality": {
            "greedy_token_agreement": round(agree, 4),
            "compared_tokens": int(n_cmp),
            "gen_len": {"fp": fp_len, "w8a8": q_len},
            "real_weights": real,
        },
    }


def _bench_sdxl_with(config_factory, metric: str,
                     weights_dir: str) -> dict:
    """Shared SDXL harness (one timing methodology for both SDXL
    entries): dp mesh over the local devices, one prompt per device,
    images/sec/chip."""
    jax = _setup_jax()
    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import make_mesh
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    n = jax.local_device_count()
    mesh = make_mesh(MeshConfig(dp=-1, tp=1, sp=1)) if n > 1 else None
    pipe = SDXLPipeline(config_factory(), weights_dir=weights_dir,
                        mesh=mesh)
    prompts = (PROMPTS * ((n + len(PROMPTS) - 1) // len(PROMPTS)))[: max(n, 1)]
    pipe.generate(prompts, seed=0)  # warmup

    t0 = time.perf_counter()
    reps = 2
    for i in range(reps):
        pipe.generate(prompts, seed=i + 1)
    elapsed = time.perf_counter() - t0
    ips_chip = reps * len(prompts) / elapsed / max(1, n)
    return {
        "metric": metric,
        "value": round(ips_chip, 4),
        "unit": "images/sec/chip",
        "vs_baseline": None,
    }


# SDXL-base 1024² analytic full-pipeline cost (tools/profile_unet.py
# --cost-table --sdxl, backend-independent): 6.761 TF/UNet-forward x 100
# CFG forwards + 10.47 TF VAE decode + 0.22 TF dual text towers (cond +
# uncond) = ~686.8 TF/image. On a ~197 TFLOP/s bf16 v5e chip the fixed
# DDIM-50 in-config ceiling is therefore ~0.287 img/s/chip — the SDXL
# analogue of sd15's 2.51 (BASELINE.md has no workload-level SDXL img/s
# target, so the ceiling IS the baseline the fraction reports against).
SDXL_ANALYTIC_TF_PER_IMAGE = 686.8
SDXL_CEILING_IPS_DEFAULT = 0.287

# Encoder propagation rewrites the SDXL per-image analytic cost
# (tools/profile_unet.py --cost-table --sdxl now prints the
# encoder/decoder split and this bound): full forwards at 20 key steps
# + decoder-only (3.828 of 6.761 TF) forwards at the other 30, CFG-
# doubled, + the 10.47 TF VAE decode = ~510.6 TF/image (74% of the
# full 686.6) -> ~0.386 img/s/chip in-config ceiling on the same
# ~197 TFLOP/s chip. The `sdxl_encprop` entry reports BOTH this and
# the fraction of the FIXED full-config ceiling (progress toward the
# ROADMAP >80%-of-ceiling target is measured against the latter).
SDXL_ENCPROP_ANALYTIC_TF_PER_IMAGE = 510.6
SDXL_ENCPROP_CEILING_IPS = 0.386


def _sdxl_ceiling_context(res: dict) -> dict:
    """Attach the analytic ceiling context to an SDXL suite entry (the
    sd15 entries have carried this since round 4; VERDICT r5 weak #7
    flagged the asymmetry)."""
    ceiling = float(os.environ.get("BENCH_SDXL_CEILING_IPS",
                                   str(SDXL_CEILING_IPS_DEFAULT)))
    if ceiling > 0 and "value" in res:
        res["analytic_tf_per_image"] = SDXL_ANALYTIC_TF_PER_IMAGE
        res["ceiling_ips"] = ceiling
        res["fraction_of_fixed_config_ceiling"] = round(
            res["value"] / ceiling, 4)
        res["vs_baseline"] = res["fraction_of_fixed_config_ceiling"]
    return res


def bench_sdxl(weights_dir: str) -> dict:
    """BASELINE ladder #4: SDXL-base 1024², batched, data-parallel.
    ``vs_baseline`` reports fraction of the analytic in-config bf16
    ceiling (~0.287 img/s/chip — see SDXL_ANALYTIC_TF_PER_IMAGE)."""
    from cassmantle_tpu.config import sdxl_config

    return _sdxl_ceiling_context(_bench_sdxl_with(
        sdxl_config, "sdxl_1024px_ddim50_images_per_sec_per_chip",
        weights_dir))


def bench_e2e_round(weights_dir: str) -> dict:
    """BASELINE ladder #5: full round (prompt gen + image + 1k concurrent
    guess scorings through the continuous-batching queue)."""
    import asyncio

    _setup_jax()
    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(FrameworkConfig(), weights_dir=weights_dir)

    async def run() -> float:
        svc.score_queue.start()
        # warmup both paths; OOV tokens so the embed table's rung 0
        # can't serve the pair — the point is compiling the DEVICE path
        await svc.content_backend.generate("An old ship left the harbor", True)
        await svc.similarity([("qzwarmupx", "qzwarmupy")] * 64)
        t0 = time.perf_counter()
        content_task = asyncio.ensure_future(
            svc.content_backend.generate("The market opened at dawn", False)
        )
        # 1k guesses land while the round is generating (the serving
        # pressure point: queue coalescing + device contention)
        guesses = [
            svc.similarity([(f"word{i}", "stormy")]) for i in range(1000)
        ]
        await asyncio.gather(*guesses)
        await content_task
        elapsed = time.perf_counter() - t0
        await svc.stop()
        return elapsed

    elapsed = asyncio.run(run())
    return {
        "metric": "e2e_round_with_1k_guesses_seconds",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": None,
    }


async def soak_run(svc, rounds: int, workers: int = 32):
    """N rounds of content generation while `workers` guess loops keep
    constant pressure on the score queue; -> (elapsed_s, latencies_s,
    error_count). Shared by bench_soak and its CPU smoke test
    (tests/test_queue.py)."""
    import asyncio

    svc.score_queue.start()
    await svc.content_backend.generate("An old ship left the harbor", True)
    # OOV warmup pair: must compile the device scorer, not hit the table
    await svc.similarity([("qzwarmupx", "qzwarmupy")] * 64)

    latencies: list = []
    stop = asyncio.Event()

    errors = [0]

    async def guess_pressure(worker: int) -> None:
        i = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                await svc.similarity([(f"w{worker}_{i}", "stormy")])
            except Exception:
                # a worker must never die mid-soak: a transient scoring
                # error (rollover backpressure) would silently unload the
                # bench and overstate "sustained" throughput
                errors[0] += 1
                await asyncio.sleep(0.05)
                continue
            latencies.append(time.perf_counter() - t0)
            i += 1

    pressure = [asyncio.ensure_future(guess_pressure(w))
                for w in range(workers)]
    t0 = time.perf_counter()
    for r in range(rounds):
        await svc.content_backend.generate(f"Round {r} under load", False)
    elapsed = time.perf_counter() - t0
    stop.set()
    await asyncio.gather(*pressure, return_exceptions=True)
    await svc.stop()
    return elapsed, latencies, errors[0]


def bench_soak(weights_dir: str) -> dict:
    """BASELINE ladder rung 5 is *sustained* serving, not a burst: N
    consecutive rounds of content generation under CONTINUOUS guess
    load, reporting images/sec plus p50/p99 guess latency. The guess
    pressure never pauses between rounds — exactly the round-rollover
    contention the 1 Hz clock produces in production."""
    import asyncio

    _setup_jax()
    import numpy as np

    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.serving.service import InferenceService

    rounds = int(os.environ.get("BENCH_SOAK_ROUNDS", "5"))
    svc = InferenceService(FrameworkConfig(), weights_dir=weights_dir)
    elapsed, lats, errors = asyncio.run(soak_run(svc, rounds))
    if not lats:
        raise RuntimeError(
            f"soak produced no successful guess scorings ({errors} errors)"
        )
    ms = np.sort(np.asarray(lats)) * 1000.0
    return {
        "metric": f"soak_{rounds}rounds_images_per_sec_sustained",
        "value": round(rounds / elapsed, 4),
        "unit": "images/sec",
        "vs_baseline": None,
        "rounds": rounds,
        "guesses": len(lats),
        "guess_errors": errors,
        "guesses_per_sec": round(len(lats) / elapsed, 1),
        "guess_p50_ms": round(float(ms[len(ms) // 2]), 1),
        "guess_p99_ms": round(float(ms[int(len(ms) * 0.99)]), 1),
    }


def _rooms_worker_main(port: int, store_addr: str, num_rooms: int,
                       worker_id: str, advertise: str,
                       round_seconds: float,
                       score_batch_ms: float = 0.0) -> None:
    """Child process for the rooms_load harness: one fabric worker
    (fake content backend — the harness measures the GAME fabric, not
    the diffusion path) over the shared native (or replicated) store.
    ``score_batch_ms`` > 0 puts the fake scorer behind a real batching
    queue with that simulated per-batch device cost (the embed-table
    A/B arms need a device cost for the table rung to beat); the
    table arms themselves are selected via CASSMANTLE_FAKE_EMBED_TABLE
    / CASSMANTLE_NO_EMBED_TABLE in the spawn environment."""
    import dataclasses

    from aiohttp import web

    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.server.app import build_fabric, create_app

    cfg = FrameworkConfig()
    cfg = cfg.replace(
        # rate limits effectively off: the harness IS the flood
        game=dataclasses.replace(
            cfg.game, time_per_prompt=round_seconds, lock_timeout=10.0,
            acquire_timeout=0.5, rate_limit_default=1e6,
            rate_limit_api=1e6),
        fabric=dataclasses.replace(
            cfg.fabric, num_rooms=num_rooms, heartbeat_s=0.5,
            membership_ttl_s=2.5),
    )
    if score_batch_ms > 0:
        cfg = cfg.replace(serving=dataclasses.replace(
            cfg.serving, fake_score_batch_ms=score_batch_ms))
    fabric = build_fabric(cfg, fake=True, store_addr=store_addr,
                          worker_id=worker_id, advertise_addr=advertise)
    web.run_app(create_app(fabric, cfg), host="127.0.0.1", port=port,
                print=None)


async def _rooms_load_drive(base_urls, sessions: int, seconds: float,
                            ws_conns: int, guess_words=None) -> dict:
    """The synthetic load: N sessions in a sustained guess loop + M WS
    /clock subscriptions, spread across every worker (cross-worker 307s
    followed transparently); returns raw counters + latencies.
    ``guess_words`` replaces the default out-of-vocabulary ``guessN``
    stream with a fixed word cycle (the embed-table A/B arms drive
    in-vocabulary guesses through the same deterministic sequence)."""
    import asyncio

    import aiohttp

    timeout = aiohttp.ClientTimeout(total=15.0)
    latencies: list = []
    errors = [0]
    ws_ticks = [0]
    guesses = [0]
    async with aiohttp.ClientSession(timeout=timeout) as http:
        # the cluster map: room placement + advertised worker addresses
        # straight from the fabric block of /readyz
        async with http.get(base_urls[0] + "/readyz") as res:
            fabric_block = (await res.json()).get("fabric", {})
        placement = fabric_block.get("rooms", {})
        workers = fabric_block.get("workers", {})

        def owner_url(room: str) -> str:
            info = workers.get(placement.get(room) or "", {})
            return (info.get("addr") or base_urls[0]).rstrip("/")

        deadline = time.monotonic() + seconds

        async def player(i: int) -> None:
            sid = f"load-{i}"
            base = base_urls[i % len(base_urls)]
            q = f"?session={sid}"
            try:
                async with http.get(base + "/init" + q) as res:
                    await res.json()
                async with http.get(base + "/fetch/contents" + q) as res:
                    prompt = (await res.json())["prompt"]
                masks = prompt["masks"] or [0]
            except Exception:
                errors[0] += 1
                return
            g = 0
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                guess = (guess_words[g % len(guess_words)]
                         if guess_words else f"guess{g}")
                try:
                    async with http.post(
                        base + "/compute_score" + q,
                        json={"inputs": {str(masks[0]): guess}},
                    ) as res:
                        if res.status == 200:
                            await res.json()
                            latencies.append(time.perf_counter() - t0)
                            guesses[0] += 1
                        else:
                            errors[0] += 1
                except Exception:
                    errors[0] += 1
                    await asyncio.sleep(0.05)
                g += 1

        async def clock_watcher(i: int) -> None:
            rooms = sorted(placement) or [""]
            room = rooms[i % len(rooms)]
            url = owner_url(room) + f"/clock?session=ws-{i}&room={room}"
            try:
                async with http.ws_connect(url) as ws:
                    while time.monotonic() < deadline:
                        msg = await asyncio.wait_for(
                            ws.receive(), timeout=max(2.0, seconds))
                        if msg.type != aiohttp.WSMsgType.TEXT:
                            break
                        ws_ticks[0] += 1
            except Exception:
                errors[0] += 1

        tasks = [asyncio.ensure_future(player(i)) for i in range(sessions)]
        tasks += [asyncio.ensure_future(clock_watcher(i))
                  for i in range(ws_conns)]
        t0 = time.perf_counter()
        await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.perf_counter() - t0
        # post-load attribution scrape: workers start at zero, so their
        # /metrics counter totals ARE this run's deltas (the embed-table
        # arms read scorer.table_hits / score.items here)
        worker_counters: dict = {}
        for url in base_urls:
            try:
                async with http.get(url + "/metrics") as res:
                    counters = (await res.json()).get("counters", {})
            except Exception:
                continue
            for name, value in counters.items():
                worker_counters[name] = \
                    worker_counters.get(name, 0) + value
    return {
        "elapsed": elapsed,
        "latencies": latencies,
        "guesses": guesses[0],
        "ws_ticks": ws_ticks[0],
        "errors": errors[0],
        "worker_counters": worker_counters,
    }


def rooms_load_spawn_workers(workers: int, rooms: int, base_port: int,
                             store_addr: str,
                             round_seconds: float = 8.0,
                             score_batch_ms: float = 0.0) -> tuple:
    """(procs, base_urls): N fabric worker processes over one shared
    store address, each advertised for cross-worker redirects, all
    confirmed /healthz-ready."""
    import multiprocessing
    import urllib.request

    procs = []
    base_urls = []
    # spawn, not fork: the driver (pytest, bench suite) has jax loaded
    # and multithreaded — forking that risks a child deadlock. Spawned
    # workers import only the fake-backend server path (no jax at all),
    # so the clean interpreter costs ~a second and buys determinism.
    ctx = multiprocessing.get_context("spawn")
    for w in range(workers):
        port = base_port + w
        url = f"http://127.0.0.1:{port}"
        base_urls.append(url)
        p = ctx.Process(
            target=_rooms_worker_main,
            args=(port, store_addr, rooms, f"bench-w{w}", url,
                  round_seconds, score_batch_ms),
            daemon=True)
        p.start()
        procs.append(p)
    for url in base_urls:
        deadline = time.monotonic() + 60.0
        while True:
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=2.0) as res:
                    if res.status == 200:
                        break
            except Exception:
                pass
            if time.monotonic() >= deadline:
                for p in procs:
                    p.terminate()
                raise RuntimeError(f"worker {url} never became healthy")
            time.sleep(0.1)
    return procs, base_urls


def rooms_load_run(workers: int = 2, rooms: int = 4, sessions: int = 8,
                   seconds: float = 6.0, ws_conns: int = 4,
                   base_port: int = 8461, store_port: int = 7461,
                   round_seconds: float = 8.0,
                   store_addr: str = None,
                   score_batch_ms: float = 0.0,
                   guess_words=None) -> dict:
    """Spawn one shared mantlestore + N fabric worker processes, drive
    sustained guess + WS clock load across M rooms, return raw stats.
    ``store_addr`` overrides the store (e.g. ``repl:...`` against an
    externally spawned replicated cluster — the failover drill in
    tests/test_fabric_cluster.py). Shared by ``bench.py rooms_load``
    and the CPU smoke tests (tests/test_fabric.py)."""
    import asyncio

    from cassmantle_tpu.native.client import ensure_built, spawn_server

    if ensure_built() is None:
        raise RuntimeError("mantlestore toolchain unavailable")
    store_proc = None
    if store_addr is None:
        store_proc = spawn_server(store_port)
        store_addr = f"native:{store_port}"
    procs = []
    try:
        procs, base_urls = rooms_load_spawn_workers(
            workers, rooms, base_port, store_addr, round_seconds,
            score_batch_ms=score_batch_ms)
        raw = asyncio.run(
            _rooms_load_drive(base_urls, sessions, seconds, ws_conns,
                              guess_words=guess_words))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=5.0)
        if store_proc is not None:
            store_proc.kill()
            store_proc.wait()
    raw.update(workers=workers, rooms=rooms, sessions=sessions,
               ws_conns=ws_conns)
    return raw


def bench_rooms_load(weights_dir: str) -> dict:
    """ROADMAP item 2's deliverable: the game-fabric load rung made
    measurable. N worker processes × M rooms over one shared store,
    sustained guesses/sec + WS clock fan-out, request p50/p99 against a
    p99 SLO. Knobs: BENCH_ROOMS_WORKERS / BENCH_ROOMS_COUNT /
    BENCH_ROOMS_SESSIONS / BENCH_ROOMS_SECONDS / BENCH_ROOMS_WS /
    BENCH_ROOMS_P99_SLO_MS (env)."""
    import numpy as np

    env = os.environ.get
    raw = rooms_load_run(
        workers=int(env("BENCH_ROOMS_WORKERS", "2")),
        rooms=int(env("BENCH_ROOMS_COUNT", "4")),
        sessions=int(env("BENCH_ROOMS_SESSIONS", "8")),
        seconds=float(env("BENCH_ROOMS_SECONDS", "6")),
        ws_conns=int(env("BENCH_ROOMS_WS", "4")),
        base_port=int(env("BENCH_ROOMS_BASE_PORT", "8461")),
        store_port=int(env("BENCH_ROOMS_STORE_PORT", "7461")),
    )
    if not raw["latencies"]:
        raise RuntimeError(
            f"rooms_load produced no successful guesses "
            f"({raw['errors']} errors)")
    ms = np.sort(np.asarray(raw["latencies"])) * 1000.0
    slo_ms = float(env("BENCH_ROOMS_P99_SLO_MS", "2000"))
    p99 = float(ms[int(len(ms) * 0.99)])
    return {
        "metric": "rooms_load_guesses_per_sec_sustained",
        "value": round(raw["guesses"] / raw["elapsed"], 1),
        "unit": "guesses/sec",
        "vs_baseline": None,
        "workers": raw["workers"],
        "rooms": raw["rooms"],
        "sessions": raw["sessions"],
        "duration_s": round(raw["elapsed"], 2),
        "ws_conns": raw["ws_conns"],
        "ws_ticks": raw["ws_ticks"],
        "request_errors": raw["errors"],
        "request_p50_ms": round(float(ms[len(ms) // 2]), 1),
        "request_p99_ms": round(p99, 1),
        "p99_slo_ms": slo_ms,
        "slo_ok": bool(p99 <= slo_ms),
        # bench_diff regression gate: multi-process closed-loop load on
        # a shared host swings hard with core count and contention
        "noise_tolerance": 0.35,
    }


# -- chaos drill (ISSUE 12): seeded fault schedule vs the real fabric -----

def _phase_stats(raw: dict, extra: dict = None) -> dict:
    """One drill phase's record: p50/p99, error budget spent, plus the
    per-worker chaos.injections total scraped after the load."""
    import numpy as np

    lats = raw.get("latencies") or []
    total = raw.get("guesses", 0) + raw.get("errors", 0)
    stats = {
        "guesses": raw.get("guesses", 0),
        "errors": raw.get("errors", 0),
        "error_budget_spent": round(raw.get("errors", 0) / total, 4)
        if total else None,
    }
    if lats:
        ms = np.sort(np.asarray(lats)) * 1000.0
        stats["p50_ms"] = round(float(ms[len(ms) // 2]), 1)
        stats["p99_ms"] = round(float(ms[int(len(ms) * 0.99)]), 1)
    if extra:
        stats.update(extra)
    return stats


async def _scrape_chaos_injections(base_urls) -> int:
    """Sum of ``chaos.injections`` across the workers' /metrics — the
    drill's proof that the armed plan actually fired."""
    import aiohttp

    total = 0
    timeout = aiohttp.ClientTimeout(total=5.0)
    async with aiohttp.ClientSession(timeout=timeout) as http:
        for url in base_urls:
            try:
                async with http.get(url + "/metrics") as res:
                    counters = (await res.json()).get("counters", {})
            except Exception:
                continue
            total += int(counters.get("chaos.injections", 0))
    return total


async def _first_success_after(base_url: str, deadline_s: float) -> float:
    """Seconds until the worker answers a scoring request again —
    the drill's recovery clock (bounded; None-equivalent = deadline)."""
    import asyncio as _asyncio

    import aiohttp

    t0 = time.monotonic()
    timeout = aiohttp.ClientTimeout(total=3.0)
    async with aiohttp.ClientSession(timeout=timeout) as http:
        while time.monotonic() - t0 < deadline_s:
            try:
                async with http.post(
                    base_url + "/compute_score?session=recovery-probe",
                    json={"inputs": {"0": "probe"}},
                ) as res:
                    if res.status == 200:
                        return round(time.monotonic() - t0, 3)
            except Exception:
                pass
            await _asyncio.sleep(0.1)
    return round(deadline_s, 3)


def _drill_cluster_phase(name: str, spec: str, seed: int, *,
                         base_port: int, store_port: int, rooms: int,
                         sessions: int, seconds: float,
                         round_seconds: float = 8.0,
                         kill_leader: bool = False) -> dict:
    """One multi-process drill phase: fresh store(s) + 2 fabric workers
    booted with the phase's CASSMANTLE_CHAOS plan, sustained guess load,
    per-fault latency/error stats. ``kill_leader`` runs a replicated
    store pair and kills the leader mid-phase, measuring recovery."""
    import asyncio

    from cassmantle_tpu.native.client import spawn_server

    store_procs = []
    if kill_leader:
        store_procs.append(spawn_server(store_port, repl=True,
                                        repl_id="drill-A", lease_ms=600))
        store_procs.append(spawn_server(store_port + 1, follower=True,
                                        repl_id="drill-B", lease_ms=600))
        store_addr = (f"repl:127.0.0.1:{store_port},"
                      f"127.0.0.1:{store_port + 1}")
    else:
        store_procs.append(spawn_server(store_port))
        store_addr = f"native:{store_port}"
    prev = os.environ.pop("CASSMANTLE_CHAOS", None)
    if spec:
        os.environ["CASSMANTLE_CHAOS"] = f"seed={seed};{spec}"
    procs = []
    try:
        procs, base_urls = rooms_load_spawn_workers(
            2, rooms, base_port, store_addr,
            round_seconds=round_seconds)
        extra = {}
        if kill_leader:
            phase1 = asyncio.run(_rooms_load_drive(
                base_urls, sessions, seconds / 2.0, ws_conns=0))
            store_procs[0].kill()
            store_procs[0].wait()
            extra["recovery_s"] = asyncio.run(
                _first_success_after(base_urls[0], deadline_s=20.0))
            raw = asyncio.run(_rooms_load_drive(
                base_urls, sessions, seconds / 2.0, ws_conns=0))
            raw["guesses"] += phase1["guesses"]
            raw["errors"] += phase1["errors"]
            raw["latencies"] = phase1["latencies"] + raw["latencies"]
        else:
            raw = asyncio.run(_rooms_load_drive(
                base_urls, sessions, seconds, ws_conns=0))
        if spec:
            extra["injections"] = asyncio.run(
                _scrape_chaos_injections(base_urls))
        return _phase_stats(raw, extra)
    finally:
        if spec:
            os.environ.pop("CASSMANTLE_CHAOS", None)
        if prev is not None:
            os.environ["CASSMANTLE_CHAOS"] = prev
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=10.0)
        for sp in store_procs:
            try:
                sp.kill()
                sp.wait()
            except Exception:
                pass


def _drill_wedged_dispatch_phase(seed: int) -> dict:
    """In-process wedged-dispatch drill: a chaos ``wedge`` holds the
    REAL dispatch thread, submits fail at their deadline, the watchdog
    replaces the thread, and recovery is measured from the release to
    the next successful dispatch."""
    import asyncio

    from cassmantle_tpu import chaos
    from cassmantle_tpu.serving.queue import (
        BatchingQueue,
        DeadlineExceeded,
        DispatchTimeout,
        _DispatchWorker,
    )
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    chaos.configure(
        f"seed={seed};queue.dispatch=wedge:times=1,wedge_s=30")
    sup = ServingSupervisor(degraded_cooldown_s=0.2)
    q = BatchingQueue(
        lambda items: [0.0 for _ in items], max_batch=4,
        max_delay_ms=1, default_deadline_s=0.3, hang_timeout_s=0.6,
        supervisor=sup, name="drillscore",
        dispatcher=_DispatchWorker(name="drill.dispatch_worker"))
    stats = {"deadline_failures": 0}

    async def run() -> None:
        try:
            await q.submit("wedge-me")
        except (DeadlineExceeded, DispatchTimeout):
            stats["deadline_failures"] += 1
        # let the watchdog declare the wedge and replace the thread:
        # the hang clock arms when the handler is OBSERVED running,
        # one wait-window after dispatch, so the fire lands at up to
        # ~2x hang_timeout_s
        await asyncio.sleep(1.5)
        t0 = time.monotonic()
        chaos.release("queue.dispatch")
        assert await q.submit("after") == 0.0
        stats["recovery_s"] = round(time.monotonic() - t0, 3)
        # the overrun COUNT, not the live degraded flag: the short
        # drill cooldown has usually lapsed by this read
        stats["watchdog_fired"] = (
            sup.status()["watchdog"]["overruns"] >= 1)
        await q.stop()

    try:
        asyncio.run(run())
    finally:
        chaos.disarm()
    stats["injections"] = 1
    return stats


def _drill_sigterm_handoff_phase(*, base_port: int, store_port: int,
                                 rooms: int) -> dict:
    """The graceful-handoff drill: SIGTERM one of two workers and pin
    that (a) its rooms are adopted by the survivor BEFORE the process
    exits, and (b) a score accepted on the victim before the signal is
    still visible through the survivor after (no lost accepted
    scores — the ISSUE 12 acceptance)."""
    import asyncio
    import signal as _signal

    import aiohttp

    from cassmantle_tpu.native.client import spawn_server

    store_proc = spawn_server(store_port)
    procs = []
    try:
        procs, base_urls = rooms_load_spawn_workers(
            2, rooms, base_port, f"native:{store_port}",
            round_seconds=30.0)

        async def run() -> dict:
            timeout = aiohttp.ClientTimeout(total=5.0)
            async with aiohttp.ClientSession(timeout=timeout) as http:
                async with http.get(base_urls[1] + "/readyz") as res:
                    fab = (await res.json())["fabric"]
                victim_id = fab["worker"]
                victim_rooms = [r for r, w in fab["rooms"].items()
                                if w == victim_id]
                if not victim_rooms:
                    return {"error": "victim owns no rooms"}
                room = victim_rooms[0]
                sid = "handoff-s"
                q = f"?session={sid}&room={room}"
                async with http.get(base_urls[1] + "/init" + q) as res:
                    assert res.status == 200
                async with http.get(
                        base_urls[1] + "/fetch/contents" + q) as res:
                    prompt = (await res.json())["prompt"]
                mask = (prompt["masks"] or [0])[0]
                async with http.post(
                    base_urls[1] + "/compute_score" + q,
                    json={"inputs": {str(mask): "drill-guess"}},
                ) as res:
                    scores_before = await res.json()
                t_term = time.monotonic()
                os.kill(procs[1].pid, _signal.SIGTERM)
                adopted_at = None
                adopted_while_alive = False
                deadline = t_term + 15.0
                while time.monotonic() < deadline:
                    alive = procs[1].is_alive()
                    try:
                        async with http.get(
                                base_urls[0] + "/readyz") as res:
                            placement = (await res.json())[
                                "fabric"]["rooms"]
                    except Exception:
                        placement = {}
                    if adopted_at is None and all(
                            placement.get(r) not in (victim_id, None)
                            for r in victim_rooms):
                        adopted_at = time.monotonic()
                        adopted_while_alive = alive
                    if adopted_at is not None and not alive:
                        break
                    await asyncio.sleep(0.03)
                procs[1].join(timeout=10.0)
                exited_at = time.monotonic()
                # the survivor now owns the room: the victim's accepted
                # score must still be there (shared store, no loss)
                async with http.get(
                        base_urls[0] + "/fetch/contents" + q) as res:
                    prompt_after = (await res.json())["prompt"]
                key = str(mask)
                before = scores_before.get(key)
                after = prompt_after.get("scores", {}).get(key)
                # handoff() exits only after observing the peer beat
                # that rebuilt the ring, so adoption-before-exit holds
                # by construction; the 30ms external poll can still
                # miss the window, so the hard pins are adoption WELL
                # below the staleness TTL (the handoff moved the rooms,
                # not the TTL) + the draining verdict + score survival
                return {
                    "adopted_before_exit_observed": bool(
                        adopted_at is not None
                        and adopted_while_alive),
                    "adoption_s": round(adopted_at - t_term, 3)
                    if adopted_at else None,
                    "membership_ttl_s": 2.5,
                    "handoff_exit_s": round(exited_at - t_term, 3),
                    "score_preserved": (
                        before is not None and after is not None
                        and float(after) == float(before)),
                }

        return asyncio.run(run())
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=10.0)
        try:
            store_proc.kill()
            store_proc.wait()
        except Exception:
            pass


DRILL_PHASES = ("baseline", "slow_store", "flaky_generation",
                "heartbeat_flap", "leader_kill", "wedged_dispatch",
                "sigterm_handoff")


def chaos_drill_run(seed: int = 42, rooms: int = 3, sessions: int = 4,
                    seconds: float = 3.0, base_port: int = 8531,
                    store_port: int = 7531,
                    phases=DRILL_PHASES) -> dict:
    """The seeded chaos drill (docs/CHAOS.md runbook): a fresh
    two-worker fabric per phase, each phase arming one fault family
    via CASSMANTLE_CHAOS (same seed => same schedule), plus the
    in-process wedged-dispatch and process-level SIGTERM-handoff
    phases. Shared by ``bench.py chaos_drill`` and the slow-tier smoke
    (tests/test_chaos_drill.py)."""
    from cassmantle_tpu.native.client import ensure_built

    if ensure_built() is None:
        raise RuntimeError("mantlestore toolchain unavailable")
    specs = {
        "baseline": "",
        "slow_store": "store.client.op=latency:delay_s=0.02,p=0.3",
        "flaky_generation": "round.generate=flake:p=0.5",
        "heartbeat_flap": "fabric.heartbeat=flake:p=0.5",
        "leader_kill": "",
    }
    out = {"seed": seed, "phases": {}}
    port = base_port
    sport = store_port
    for phase in phases:
        if phase == "wedged_dispatch":
            out["phases"][phase] = _drill_wedged_dispatch_phase(seed)
            continue
        if phase == "sigterm_handoff":
            out["phases"][phase] = _drill_sigterm_handoff_phase(
                base_port=port, store_port=sport, rooms=rooms)
            port += 4
            sport += 4
            continue
        out["phases"][phase] = _drill_cluster_phase(
            phase, specs[phase], seed, base_port=port,
            store_port=sport, rooms=rooms, sessions=sessions,
            seconds=seconds,
            round_seconds=1.5 if phase == "flaky_generation" else 8.0,
            kill_leader=(phase == "leader_kill"))
        port += 4
        sport += 4
    return out


def bench_chaos_drill(weights_dir: str) -> dict:
    """ISSUE 12's deliverable: the fleet driven through a seeded fault
    schedule — slow store, flaky generation, membership flap, store
    leader kill, wedged dispatch, SIGTERM handoff — reporting per-fault
    p99, error budget spent, and recovery seconds. Knobs:
    BENCH_CHAOS_SEED / BENCH_CHAOS_SECONDS / BENCH_CHAOS_ROOMS /
    BENCH_CHAOS_SESSIONS / BENCH_CHAOS_BASE_PORT /
    BENCH_CHAOS_STORE_PORT (env)."""
    env = os.environ.get
    raw = chaos_drill_run(
        seed=int(env("BENCH_CHAOS_SEED", "42")),
        rooms=int(env("BENCH_CHAOS_ROOMS", "3")),
        sessions=int(env("BENCH_CHAOS_SESSIONS", "4")),
        seconds=float(env("BENCH_CHAOS_SECONDS", "4")),
        base_port=int(env("BENCH_CHAOS_BASE_PORT", "8531")),
        store_port=int(env("BENCH_CHAOS_STORE_PORT", "7531")),
    )
    phases = raw["phases"]
    recovery = phases.get("leader_kill", {}).get("recovery_s")
    return {
        "metric": "chaos_drill_leader_kill_recovery_s",
        "value": recovery,
        "unit": "seconds",
        "vs_baseline": None,
        "seed": raw["seed"],
        "phases": phases,
    }


# -- overload drill (ISSUE 13): ramp load past capacity, watch the -------
# -- control plane plateau instead of collapse ---------------------------

def _overload_worker_main(port: int, batch_ms: float, bucket: int,
                          round_seconds: float) -> None:
    """Child process for the overload drill: ONE fabric worker, fake
    content backend, the fake scorer behind a REAL BatchingQueue whose
    handler holds the dispatch thread ``batch_ms`` per batch (known
    capacity = bucket / batch_s items/sec), with drill-tight latency
    targets, deadlines, and SLO windows so adaptive admission and the
    brownout ladder act within a ~10 s drill instead of a ~10 min
    incident. No jax import (same contract as the rooms_load worker)."""
    import dataclasses

    from aiohttp import web

    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.server.app import build_fabric, create_app

    cfg = FrameworkConfig()
    cfg = cfg.replace(
        game=dataclasses.replace(
            cfg.game, time_per_prompt=round_seconds, lock_timeout=10.0,
            acquire_timeout=0.5, rate_limit_default=1e6,
            rate_limit_api=1e6),
        serving=dataclasses.replace(
            cfg.serving,
            fake_score_batch_ms=batch_ms,
            score_batch_sizes=(bucket,),
            max_queue_delay_ms=5.0,
            submit_deadline_s=1.5,
            queue_latency_target_s=0.5,
            admission_min_pending=4,
            # the drill saturates the host CPU by design; the loop-lag
            # leg is covered by units (tests/test_overload.py), so keep
            # it from double-firing here
            loop_lag_shed_s=2.0,
            brownout_step_up_dwell_s=0.5,
            brownout_step_down_dwell_s=0.5,
        ),
        obs=dataclasses.replace(
            cfg.obs,
            slo_eval_interval_s=0.25,
            slo_fast_window_s=1.5,
            slo_slow_window_s=3.0,
            slo_score_p99_s=0.2),
    )
    fabric = build_fabric(cfg, fake=True)
    web.run_app(create_app(fabric, cfg), host="127.0.0.1", port=port,
                print=None)


async def _overload_drive(base_url: str, phases, sessions: int,
                          guess_words=None) -> dict:
    """Open-loop synthetic load: each phase fires /compute_score POSTs
    at a fixed arrival rate WITHOUT waiting for completions (a closed
    loop would self-throttle and never overload anything). Tracks per
    phase: accepted latencies, rejection latencies + their Retry-After
    values, and the brownout tier (sampled from /metrics).
    ``guess_words`` replaces the all-OOV ``guessN`` stream with a fixed
    cycle (the embed-table drill mixes in-vocabulary words with OOV
    tokens so the table rung and the admission-controlled queue carry
    their designed shares of the same flood)."""
    import asyncio

    import aiohttp

    timeout = aiohttp.ClientTimeout(total=10.0)
    out = {"phases": {}}
    async with aiohttp.ClientSession(timeout=timeout) as http:
        sids = [f"ovl-{i}" for i in range(sessions)]
        masks = [0]
        for sid in sids:
            q = f"?session={sid}"
            async with http.get(base_url + "/init" + q) as res:
                await res.json()
        async with http.get(base_url + "/fetch/contents"
                            + f"?session={sids[0]}") as res:
            masks = (await res.json())["prompt"]["masks"] or [0]

        tier_seen = [0.0]

        async def tier_sampler(stop: asyncio.Event) -> None:
            while not stop.is_set():
                try:
                    async with http.get(base_url + "/metrics") as res:
                        gauges = (await res.json())["gauges"]
                    tier_seen[0] = max(
                        tier_seen[0],
                        float(gauges.get("overload.brownout_tier", 0.0)))
                except Exception:
                    pass
                try:
                    await asyncio.wait_for(stop.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass

        async def one_request(i: int, rec: dict) -> None:
            sid = sids[i % len(sids)]
            guess = (guess_words[i % len(guess_words)]
                     if guess_words else f"guess{i}")
            t0 = time.perf_counter()
            try:
                async with http.post(
                    base_url + f"/compute_score?session={sid}",
                    json={"inputs": {str(masks[0]): guess}},
                ) as res:
                    ms = (time.perf_counter() - t0) * 1000.0
                    if res.status == 200:
                        await res.json()
                        rec["accepted_ms"].append(ms)
                    elif res.status in (429, 503):
                        rec["rejected_ms"].append(ms)
                        ra = res.headers.get("Retry-After")
                        if ra is not None:
                            rec["retry_after_s"].append(float(ra))
                    else:
                        rec["errors"] += 1
            except Exception:
                rec["errors"] += 1

        for name, rate, seconds in phases:
            rec = {"accepted_ms": [], "rejected_ms": [],
                   "retry_after_s": [], "errors": 0,
                   "rate": rate, "seconds": seconds}
            stop = asyncio.Event()
            sampler = asyncio.ensure_future(tier_sampler(stop))
            tier_seen[0] = 0.0
            tasks = []
            interval = 1.0 / rate
            t_start = time.monotonic()
            i = 0
            while True:
                due = t_start + i * interval
                now = time.monotonic()
                if due - now > 0:
                    await asyncio.sleep(due - now)
                if time.monotonic() - t_start >= seconds:
                    break
                tasks.append(asyncio.ensure_future(one_request(i, rec)))
                i += 1
            await asyncio.gather(*tasks, return_exceptions=True)
            stop.set()
            await sampler
            rec["elapsed_s"] = time.monotonic() - t_start
            rec["max_tier"] = tier_seen[0]
            rec["goodput_per_s"] = (len(rec["accepted_ms"])
                                    / rec["elapsed_s"])
            out["phases"][name] = rec
        # the post-drill verdict: the /readyz overload block + final tier
        async with http.get(base_url + "/readyz") as res:
            body = await res.json()
        out["overload_block"] = body.get("overload", {})
        async with http.get(base_url + "/metrics") as res:
            body = await res.json()
        gauges = body["gauges"]
        out["final_tier"] = float(gauges.get("overload.brownout_tier",
                                             0.0))
        # the worker started at zero, so its counter totals ARE this
        # drill's deltas (table_served / score.batches attribution)
        out["worker_counters"] = dict(body.get("counters", {}))
    return out


def overload_drill_run(batch_ms: float = 100.0, bucket: int = 4,
                       base_port: int = 8571, sessions: int = 6,
                       baseline_s: float = 3.0, overload_s: float = 5.0,
                       recovery_s: float = 5.0,
                       round_seconds: float = 30.0,
                       guess_words=None) -> dict:
    """Spawn the drill worker and ramp: ~0.4x capacity (baseline), 2x
    (overload), ~0.2x (recovery). Capacity = bucket / batch_s. Shared
    by ``bench.py overload_drill`` and the tier-1 goodput smoke
    (tests/test_overload.py)."""
    import asyncio
    import multiprocessing
    import urllib.request

    capacity = bucket / (batch_ms / 1000.0)
    phases = [
        ("baseline", 0.4 * capacity, baseline_s),
        ("overload", 2.0 * capacity, overload_s),
        ("recovery", 0.2 * capacity, recovery_s),
    ]
    ctx = multiprocessing.get_context("spawn")
    url = f"http://127.0.0.1:{base_port}"
    p = ctx.Process(target=_overload_worker_main,
                    args=(base_port, batch_ms, bucket, round_seconds),
                    daemon=True)
    p.start()
    try:
        deadline = time.monotonic() + 60.0
        while True:
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=2.0) as res:
                    if res.status == 200:
                        break
            except Exception:
                pass
            if time.monotonic() >= deadline:
                raise RuntimeError("overload worker never became healthy")
            time.sleep(0.1)
        raw = asyncio.run(_overload_drive(url, phases, sessions,
                                          guess_words=guess_words))
    finally:
        p.terminate()
        p.join(timeout=5.0)
    raw.update(capacity_per_s=capacity, batch_ms=batch_ms,
               bucket=bucket)
    return raw


def _pctl(values, q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    return float(vs[min(len(vs) - 1, int(len(vs) * q))])


def bench_overload_drill(weights_dir: str) -> dict:
    """ISSUE 13's proof: goodput under 2x sustained load plateaus at
    capacity instead of collapsing, accepted p99 stays inside the
    deadline budget, rejections fail fast with a computed Retry-After,
    and the brownout ladder engages under burn and recovers with
    hysteresis. Knobs: BENCH_OVERLOAD_BATCH_MS / BENCH_OVERLOAD_BUCKET
    / BENCH_OVERLOAD_SECONDS / BENCH_OVERLOAD_BASE_PORT (env)."""
    env = os.environ.get
    seconds = float(env("BENCH_OVERLOAD_SECONDS", "5"))
    raw = overload_drill_run(
        batch_ms=float(env("BENCH_OVERLOAD_BATCH_MS", "100")),
        bucket=int(env("BENCH_OVERLOAD_BUCKET", "4")),
        base_port=int(env("BENCH_OVERLOAD_BASE_PORT", "8571")),
        baseline_s=max(3.0, seconds * 0.6),
        overload_s=seconds,
        recovery_s=seconds,
    )
    phases = {}
    for name, rec in raw["phases"].items():
        phases[name] = {
            "offered_per_s": round(rec["rate"], 1),
            "goodput_per_s": round(rec["goodput_per_s"], 1),
            "accepted": len(rec["accepted_ms"]),
            "rejected": len(rec["rejected_ms"]),
            "errors": rec["errors"],
            "accepted_p50_ms": round(_pctl(rec["accepted_ms"], 0.5), 1),
            "accepted_p99_ms": round(_pctl(rec["accepted_ms"], 0.99), 1),
            "reject_p50_ms": round(_pctl(rec["rejected_ms"], 0.5), 1),
            "retry_after_min_s": (min(rec["retry_after_s"])
                                  if rec["retry_after_s"] else None),
            "max_brownout_tier": rec["max_tier"],
        }
    over = phases["overload"]
    base = phases["baseline"]
    return {
        "metric": "overload_drill_goodput_at_2x_per_s",
        "value": over["goodput_per_s"],
        "unit": "accepted req/s",
        "vs_baseline": None,
        "capacity_per_s": raw["capacity_per_s"],
        "goodput_vs_baseline": (
            round(over["goodput_per_s"] / base["goodput_per_s"], 2)
            if base["goodput_per_s"] else None),
        "final_brownout_tier": raw["final_tier"],
        "phases": phases,
    }


# -- embed-table A/B arms (ISSUE 16): the zero-device guess path vs ------
# -- the queued device path under identical load -------------------------

@contextlib.contextmanager
def _arm_env(extra: dict):
    """Temporarily set the arm-selection env flags. The rooms/overload
    workers are spawn children, so flags set here are inherited at
    Process.start() — no per-worker plumbing needed."""
    saved = {k: os.environ.get(k) for k in extra}
    os.environ.update(extra)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# both arms build + consult the SAME hash-embed table code path; the
# kill switch (the production bit-exact revert) is the only difference,
# so the delta is purely "rung 0 serves" vs "everything queues"
_TABLE_ARM_ENV = {"CASSMANTLE_FAKE_EMBED_TABLE": "1",
                  "CASSMANTLE_NO_EMBED_TABLE": "0"}
_DEVICE_ARM_ENV = {"CASSMANTLE_FAKE_EMBED_TABLE": "1",
                   "CASSMANTLE_NO_EMBED_TABLE": "1"}


def _invocab_guesses(n: int = 512, oov_every: int = 0):
    """Deterministic guess cycle drawn from the real wordlist (the
    embed-table arms need in-vocabulary traffic; the default guessN
    stream is 100% OOV by construction). ``oov_every`` > 0 interleaves
    a synthetic OOV token every k-th slot."""
    from cassmantle_tpu.server.assets import load_wordlist

    words = list(load_wordlist())
    out = []
    for j in range(n):
        if oov_every and j % oov_every == oov_every - 1:
            out.append(f"qzoov{j}")
        else:
            out.append(words[(j * 97) % len(words)])
    return out


def bench_rooms_load_table(weights_dir: str) -> dict:
    """ISSUE 16's tentpole proof: the rooms_load rung re-run as an A/B
    pair under identical geometry and an identical in-vocabulary guess
    stream, with the fake scorer behind a REAL batching queue that
    holds the dispatch thread BENCH_ROOMS_TABLE_BATCH_MS per batch (the
    simulated device cost). Table arm: hash-embed table armed
    (CASSMANTLE_FAKE_EMBED_TABLE=1) — every guess completes as a host
    int8 dot, zero queue submits. Device arm: same table built, kill
    switch on (CASSMANTLE_NO_EMBED_TABLE=1) — every guess rides the
    queue. value = table-arm guesses/s; the acceptance bar is
    speedup_vs_device_arm >= 2.0, and each arm's counter_deltas carry
    the attribution (scorer.table_hits up / score.items ~0 in the
    table arm, the reverse in the device arm)."""
    import numpy as np

    env = os.environ.get
    batch_ms = float(env("BENCH_ROOMS_TABLE_BATCH_MS", "200"))
    knobs = dict(
        workers=int(env("BENCH_ROOMS_WORKERS", "2")),
        rooms=int(env("BENCH_ROOMS_COUNT", "4")),
        sessions=int(env("BENCH_ROOMS_SESSIONS", "8")),
        seconds=float(env("BENCH_ROOMS_SECONDS", "6")),
        ws_conns=int(env("BENCH_ROOMS_WS", "4")),
        score_batch_ms=batch_ms,
        guess_words=_invocab_guesses(),
    )
    arms = {}
    for arm, extra, bport, sport in (
            ("table", _TABLE_ARM_ENV, 8481, 7481),
            ("device", _DEVICE_ARM_ENV, 8491, 7491),
    ):
        with _arm_env(extra):
            raw = rooms_load_run(base_port=bport, store_port=sport,
                                 **knobs)
        if not raw["latencies"]:
            raise RuntimeError(
                f"rooms_load_table {arm} arm produced no guesses "
                f"({raw['errors']} errors)")
        ms = np.sort(np.asarray(raw["latencies"])) * 1000.0
        arms[arm] = {
            "guesses_per_s": round(raw["guesses"] / raw["elapsed"], 1),
            "guesses": raw["guesses"],
            "request_errors": raw["errors"],
            "request_p50_ms": round(float(ms[len(ms) // 2]), 1),
            "request_p99_ms": round(float(ms[int(len(ms) * 0.99)]), 1),
            "counter_deltas": _counter_deltas(
                {}, raw.get("worker_counters", {})),
        }
    table, device = arms["table"], arms["device"]
    speedup = (round(table["guesses_per_s"] / device["guesses_per_s"], 2)
               if device["guesses_per_s"] else None)
    return {
        "metric": "rooms_load_table_arm_guesses_per_sec",
        "value": table["guesses_per_s"],
        "unit": "guesses/sec",
        "vs_baseline": None,
        "speedup_vs_device_arm": speedup,
        "speedup_floor": 2.0,
        "speedup_ok": bool(speedup is not None and speedup >= 2.0),
        "score_batch_ms": batch_ms,
        "workers": knobs["workers"],
        "sessions": knobs["sessions"],
        "arms": arms,
        # the table arm's attribution doubles as the entry-level record
        "counter_deltas": dict(table["counter_deltas"]),
        "noise_tolerance": 0.35,
    }


def bench_overload_drill_table(weights_dir: str) -> dict:
    """The overload drill re-run with the embed table armed and a
    half-in-vocabulary flood: the in-vocab share completes at rung 0
    (bypassing admission entirely — overload.table_served counts it)
    while the OOV share still saturates the queue and exercises the
    limiter. value = table-arm goodput at 2x offered; the device arm
    (kill switch) plateaus at queue capacity, so goodput_vs_device > 1
    is table-served headroom the limiter never had to police."""
    env = os.environ.get
    seconds = float(env("BENCH_OVERLOAD_SECONDS", "5"))
    knobs = dict(
        batch_ms=float(env("BENCH_OVERLOAD_BATCH_MS", "100")),
        bucket=int(env("BENCH_OVERLOAD_BUCKET", "4")),
        baseline_s=max(3.0, seconds * 0.6),
        overload_s=seconds,
        recovery_s=seconds,
        guess_words=_invocab_guesses(oov_every=2),
    )
    arms = {}
    for arm, extra, bport in (("table", _TABLE_ARM_ENV, 8581),
                              ("device", _DEVICE_ARM_ENV, 8591)):
        with _arm_env(extra):
            raw = overload_drill_run(base_port=bport, **knobs)
        over = raw["phases"]["overload"]
        arms[arm] = {
            "goodput_at_2x_per_s": round(over["goodput_per_s"], 1),
            "accepted": len(over["accepted_ms"]),
            "rejected": len(over["rejected_ms"]),
            "accepted_p99_ms": round(_pctl(over["accepted_ms"], 0.99), 1),
            "max_brownout_tier": over["max_tier"],
            "counter_deltas": _counter_deltas(
                {}, raw.get("worker_counters", {})),
        }
    table, device = arms["table"], arms["device"]
    ratio = (round(table["goodput_at_2x_per_s"]
                   / device["goodput_at_2x_per_s"], 2)
             if device["goodput_at_2x_per_s"] else None)
    capacity = knobs["bucket"] / (knobs["batch_ms"] / 1000.0)
    return {
        "metric": "overload_drill_table_goodput_at_2x_per_s",
        "value": table["goodput_at_2x_per_s"],
        "unit": "accepted req/s",
        "vs_baseline": None,
        "capacity_per_s": capacity,
        "goodput_vs_device_arm": ratio,
        "invocab_share": 0.5,
        "arms": arms,
        "counter_deltas": dict(table["counter_deltas"]),
        "noise_tolerance": 0.35,
    }


# -- device-loss drill (ISSUE 17): poison, then kill, the (fake) device --
# -- and prove zero invalid outputs served + bounded recovery ------------

def device_loss_drill_run(seed: int = 42, rate: float = 50.0,
                          baseline_s: float = 1.5, poison_s: float = 2.0,
                          kill_s: float = 5.0, recovered_s: float = 2.0,
                          rebuild_s: float = 0.25) -> dict:
    """The integrity/recovery stack driven end to end IN PROCESS: a
    real BatchingQueue (own dispatch worker), a real ServingSupervisor,
    a real DeviceRecoveryManager — only the device itself is fake (a
    handler whose 'runtime' the ``device.lost`` chaos rule kills and
    whose outputs the ``device.poison`` rule corrupts). Four phases:

    - **baseline**: closed-loop submits, everything serves.
    - **poison**: ``device.poison`` flake armed; corrupted batch members
      must fail their OWN future with OutputInvalid — zero non-finite
      values may ever resolve as results (``invalid_served`` == 0).
    - **kill**: ``device.lost`` fires once; the dispatch error
      classifies, the supervisor flips ``device_lost`` (submits fail
      fast), the manager rebuilds (``rebuild_s`` fake re-upload) and
      recovery_s is the lost->serving wall clock.
    - **recovered**: chaos disarmed; goodput must be back >= 90%.

    Every submit carries a deadline, so ALL futures resolve by
    construction — the drill asserts the accounting matches."""
    import asyncio
    import math

    import numpy as np

    from cassmantle_tpu.chaos import ChaosInjected, configure, disarm, \
        fault_point
    from cassmantle_tpu.serving import integrity
    from cassmantle_tpu.serving.device_recovery import (
        DeviceRecoveryManager,
    )
    from cassmantle_tpu.serving.integrity import OutputInvalid
    from cassmantle_tpu.serving.queue import (
        BatchingQueue,
        DeadlineExceeded,
        QueueFull,
        _DispatchWorker,
    )
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    dev = {"alive": True, "generation": 0}

    def handle(items):
        try:
            fault_point("device.lost", peer="drill")
        except ChaosInjected:
            dev["alive"] = False  # the runtime is gone until rebuilt
            raise
        if not dev["alive"]:
            raise RuntimeError("fake TPU: device is lost")
        out = np.asarray([float(len(str(s))) for s in items],
                         dtype=np.float32)
        out = integrity.poison(out, peer="drill")
        bad = set(integrity.invalid_members(np.isfinite(out)).tolist())
        if bad:
            integrity.note_invalid("drill", "score", sorted(bad))
        return [OutputInvalid("drill", "score", [i]) if i in bad
                else float(out[i]) for i in range(len(items))]

    def rebuild() -> None:
        time.sleep(rebuild_s)  # stands in for the checkpoint re-upload
        dev["generation"] += 1
        dev["alive"] = True

    def warm() -> None:
        if not dev["alive"]:
            raise RuntimeError("fake TPU: still lost after rebuild")

    sup = ServingSupervisor()
    rec = DeviceRecoveryManager(supervisor=sup, rebuild=rebuild,
                                warm=warm, backoff_s=0.1)

    async def drive() -> dict:
        q = BatchingQueue(
            handle, max_batch=8, max_delay_ms=5.0, name="drill",
            default_deadline_s=2.0, hang_timeout_s=5.0,
            supervisor=sup,
            dispatcher=_DispatchWorker("drill.dispatch", rank=20),
            on_dispatch_error=rec.note_dispatch_exception,
        )
        loop = asyncio.get_running_loop()
        invalid_served = [0]
        lost_at = [None]
        recovered_at = [None]

        async def phase(name: str, seconds: float) -> dict:
            stats = {"submitted": 0, "ok": 0, "invalid": 0,
                     "rejected": 0, "dispatch_failed": 0,
                     "deadline": 0}
            end = loop.time() + seconds
            i = 0
            while loop.time() < end:
                lost = sup.device_lost
                if lost is not None and lost_at[0] is None:
                    lost_at[0] = loop.time()
                if lost is None and lost_at[0] is not None \
                        and recovered_at[0] is None:
                    recovered_at[0] = loop.time()
                stats["submitted"] += 1
                try:
                    res = await q.submit(f"{name}-{i}", deadline_s=2.0)
                    if isinstance(res, float) and not math.isfinite(res):
                        invalid_served[0] += 1  # the one forbidden path
                    stats["ok"] += 1
                except OutputInvalid:
                    stats["invalid"] += 1
                except DeadlineExceeded:
                    stats["deadline"] += 1
                except QueueFull:
                    stats["rejected"] += 1
                except Exception:
                    stats["dispatch_failed"] += 1
                i += 1
                await asyncio.sleep(1.0 / rate)
            resolved = sum(stats[k] for k in
                           ("ok", "invalid", "rejected",
                            "dispatch_failed", "deadline"))
            stats["all_resolved"] = resolved == stats["submitted"]
            stats["goodput"] = (stats["ok"] / stats["submitted"]
                                if stats["submitted"] else 0.0)
            return stats

        phases = {"baseline": await phase("baseline", baseline_s)}
        configure(f"seed={seed};device.poison=flake:p=0.35,peer=drill")
        phases["poison"] = await phase("poison", poison_s)
        configure(f"seed={seed};device.lost=raise:times=1,peer=drill")
        phases["kill"] = await phase("kill", kill_s)
        disarm()
        rec.join(timeout=10.0)
        phases["recovered"] = await phase("recovered", recovered_s)
        await q.stop()
        return {
            "phases": phases,
            "invalid_served": invalid_served[0],
            "recovery_s": (
                round(recovered_at[0] - lost_at[0], 3)
                if lost_at[0] is not None and recovered_at[0] is not None
                else None),
            "device_generation": dev["generation"],
        }

    return asyncio.run(drive())


def bench_device_loss_drill(weights_dir: str) -> dict:
    """ISSUE 17's deliverable: zero invalid outputs served under device
    poison, bounded lost->serving recovery after a device kill, every
    submitted future resolved, and >= 90% goodput once recovered.
    Knobs: BENCH_DEVLOSS_SEED / BENCH_DEVLOSS_RATE /
    BENCH_DEVLOSS_KILL_S / BENCH_DEVLOSS_REBUILD_S (env)."""
    env = os.environ.get
    raw = device_loss_drill_run(
        seed=int(env("BENCH_DEVLOSS_SEED", "42")),
        rate=float(env("BENCH_DEVLOSS_RATE", "50")),
        kill_s=float(env("BENCH_DEVLOSS_KILL_S", "5")),
        rebuild_s=float(env("BENCH_DEVLOSS_REBUILD_S", "0.25")),
    )
    phases = raw["phases"]
    poison, recovered = phases["poison"], phases["recovered"]
    return {
        "metric": "device_loss_drill_recovery_s",
        "value": raw["recovery_s"],
        "unit": "seconds",
        "vs_baseline": None,
        "invalid_served": raw["invalid_served"],
        "zero_invalid_ok": raw["invalid_served"] == 0,
        "poison_invalid_failed": poison["invalid"],
        "all_resolved": all(p["all_resolved"] for p in phases.values()),
        "recovered_goodput": round(recovered["goodput"], 3),
        "recovered_goodput_ok": recovered["goodput"] >= 0.9,
        "device_generation": raw["device_generation"],
        "phases": phases,
        # recovery wall clock = rebuild sleep + classification/thread
        # latency; timing-noisy by nature on shared CI hosts
        "noise_tolerance": 0.5,
    }


# -- canary drill (ISSUE 18): does the synthetic prober actually catch ----
# -- the faults it exists to catch? ---------------------------------------

def canary_drill_run(seed: int = 42, store_port: int = 7661) -> dict:
    """The canary prober's proof-of-detection drill: one in-process
    fabric worker on a REAL socket over a REAL mantlestore (the
    ``store.client.op`` fault point lives in the native client), probed
    by the real :class:`CanaryProber` over real HTTP. Three fault
    classes are armed in turn — slow store, device output poison, a
    wedged dispatch thread — and each probe is driven explicitly, so
    "detected within one probe period" is literal: the single probe
    fired while the fault was armed must fail. Between faults the probe
    must recover (chaos disarmed => ok again), the FAILED probe's trace
    must be retrievable through the ``probe.e2e_s`` bucket exemplar,
    and the whole drill must leave player surfaces untouched:
    ``game.guesses`` flat, the score admission limiter's estimate
    unmoved (probe submits bypass it by design)."""
    import asyncio
    import dataclasses

    from aiohttp.test_utils import TestServer

    from cassmantle_tpu import chaos
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.engine.content import FakeContentBackend
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.fabric.rooms import RoomFabric
    from cassmantle_tpu.native.client import (
        MantleStore,
        ensure_built,
        spawn_server,
    )
    from cassmantle_tpu.obs.prober import CanaryProber
    from cassmantle_tpu.obs.trace import tracer
    from cassmantle_tpu.serving.service import InferenceService
    from cassmantle_tpu.serving.supervisor import ServingSupervisor
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.utils.logging import metrics

    if ensure_built() is None:
        raise RuntimeError("mantlestore toolchain unavailable")

    base = test_config()
    cfg = base.replace(
        game=dataclasses.replace(
            base.game, rate_limit_default=1e6, rate_limit_api=1e6,
            time_per_prompt=30.0),
        fabric=dataclasses.replace(
            base.fabric, num_rooms=1, heartbeat_s=30.0),
        serving=dataclasses.replace(
            base.serving, submit_deadline_s=2.0, dispatch_hang_s=1.0),
        obs=dataclasses.replace(
            base.obs, probe_timeout_s=2.0, probe_interval_s=3600.0,
            slo_eval_interval_s=300.0, process_sample_interval_s=60.0),
    )

    store_proc = spawn_server(store_port)

    async def drive() -> dict:
        store = MantleStore(port=store_port)
        await store.connect()
        sup = ServingSupervisor()
        service = InferenceService(
            cfg, backend=FakeContentBackend(image_size=64),
            supervisor=sup)

        def factory(room, room_store):
            return Game(cfg, room_store, service.content_backend,
                        embed=service.embed,
                        similarity=service.similarity,
                        supervisor=sup, room=room)

        fabric = RoomFabric(cfg, store, factory, worker_id="canary-w",
                            start_timers=False, heartbeat=False,
                            supervisor=sup)
        server = TestServer(create_app(fabric, cfg, start_timer=False,
                                       device_health=False))
        await server.start_server()
        url = f"http://127.0.0.1:{server.port}"
        fabric.membership.addr = url
        prober = CanaryProber(fabric, cfg, self_addr=url)

        limiter = service.score_queue.admission
        limit_before = limiter._limit if limiter is not None else None
        counters_before = dict(metrics.snapshot()["counters"])

        def guesses_total(counters: dict) -> float:
            return sum(v for k, v in counters.items()
                       if k.split("{", 1)[0] == "game.guesses")

        def clear_embed_cache() -> None:
            # the probe's near-guess/answer rows land in the scorer LRU
            # on the first probe; a poison drill must force them back
            # onto the device path or the fault never executes
            with service.scorer._embed_cache_lock:
                service.scorer._embed_cache.clear()

        async def recover(deadline_s: float = 10.0) -> dict:
            t0 = time.monotonic()
            while True:
                v = await prober.probe_once()
                if v["ok"] or time.monotonic() - t0 > deadline_s:
                    return {"ok": bool(v["ok"]),
                            "recovery_s":
                                round(time.monotonic() - t0, 3)}
                await asyncio.sleep(0.25)

        def slim(v: dict) -> dict:
            return {"ok": bool(v["ok"]), "leg": v["leg"],
                    "error": v["error"], "e2e_s": v["e2e_s"],
                    "trace": v["trace"]}

        phases: dict = {}
        try:
            phases["baseline"] = slim(await prober.probe_once())

            chaos.configure(
                f"seed={seed};store.client.op=latency:delay_s=3.0")
            phases["slow_store"] = slim(await prober.probe_once())
            chaos.disarm()
            phases["slow_store"]["recovered"] = await recover()

            clear_embed_cache()
            chaos.configure(
                f"seed={seed};device.poison=raise:peer=scorer")
            phases["device_poison"] = slim(await prober.probe_once())
            chaos.disarm()
            clear_embed_cache()
            phases["device_poison"]["recovered"] = await recover()

            chaos.configure(f"seed={seed};queue.dispatch="
                            f"wedge:times=1,wedge_s=30,peer=score")
            phases["wedged_dispatch"] = slim(await prober.probe_once())
            chaos.release("queue.dispatch")
            chaos.disarm()
            # let the deadline fail the wedged batch and the watchdog
            # replace the dispatch thread (dispatch_hang_s=1.0)
            await asyncio.sleep(2.5)
            phases["wedged_dispatch"]["recovered"] = await recover()

            # the last FAILED probe's trace: retrievable directly from
            # the tracer AND linked from a probe.e2e_s bucket exemplar
            failed_trace = phases["wedged_dispatch"]["trace"]
            spans = tracer.get_trace(failed_trace)
            snap = metrics.snapshot(exemplars=True)
            ex = snap.get("exemplars", {}).get("probe.e2e_s", {})
            linked = {e["trace_id"] for e in ex.values()}
            counters_after = dict(snap["counters"])
            return {
                "phases": phases,
                "trace_retrievable": bool(spans),
                "exemplar_linked": failed_trace in linked,
                "probe_ok_total":
                    counters_after.get("probe.ok", 0.0)
                    - counters_before.get("probe.ok", 0.0),
                "probe_failures_total":
                    counters_after.get("probe.failures", 0.0)
                    - counters_before.get("probe.failures", 0.0),
                "game_guesses_delta":
                    guesses_total(counters_after)
                    - guesses_total(counters_before),
                "admit_limit_moved":
                    (limiter is not None
                     and limiter._limit != limit_before),
            }
        finally:
            chaos.disarm()
            await prober.close()
            await service.score_queue.stop()
            await service.prompt_queue.stop()
            await server.close()
            await store.close()

    try:
        return asyncio.run(drive())
    finally:
        store_proc.kill()
        store_proc.wait()


def bench_canary_drill(weights_dir: str) -> dict:
    """ISSUE 18's deliverable: every armed fault class (slow store,
    device poison, wedged dispatch) caught by the very next probe —
    within one probe period by construction — with the failed probe's
    trace retrievable via its histogram exemplar, recovery observed
    once chaos disarms, and zero probe bleed into player surfaces
    (``game.guesses`` and the admission limiter stay flat). Knobs:
    BENCH_CANARY_SEED / BENCH_CANARY_STORE_PORT (env)."""
    env = os.environ.get
    raw = canary_drill_run(
        seed=int(env("BENCH_CANARY_SEED", "42")),
        store_port=int(env("BENCH_CANARY_STORE_PORT", "7661")),
    )
    phases = raw["phases"]
    faults = ("slow_store", "device_poison", "wedged_dispatch")
    detected = sum(1 for f in faults if not phases[f]["ok"])
    return {
        "metric": "canary_drill_faults_detected",
        "value": detected,
        "unit": "faults",
        "vs_baseline": None,
        "baseline_ok": phases["baseline"]["ok"],
        "all_detected_within_one_probe": detected == len(faults),
        "detected_legs": {f: phases[f]["leg"] for f in faults},
        "all_recovered": all(phases[f]["recovered"]["ok"]
                             for f in faults),
        "trace_retrievable": raw["trace_retrievable"],
        "exemplar_linked": raw["exemplar_linked"],
        "probe_invisible_to_players":
            raw["game_guesses_delta"] == 0
            and not raw["admit_limit_moved"],
        "game_guesses_delta": raw["game_guesses_delta"],
        "admit_limit_moved": raw["admit_limit_moved"],
        "phases": phases,
        # a detection count, not a timing: exact by construction
        "noise_tolerance": 0.0,
    }


# Counters whose per-entry deltas carry diagnostic weight: recompiles,
# cache effectiveness, staged-serving churn, and every supervision
# counter (suffix match). Attached to each BENCH_SUITE.json record so
# the bench trajectory carries its own diagnosis — a throughput drop
# that arrives with a jit.recompiles delta or a dispatch_hangs count
# explains itself without a rerun.
_DELTA_COUNTERS = {
    "jit.compiles", "jit.recompiles",
    # cumulative XLA compile WALL seconds (utils/jit_sentinel.py): a
    # 100 s recompile is visible in the trajectory, not just countable
    "jit.compile_seconds",
    "scorer.embed_cache_hits", "scorer.embed_cache_misses",
    "game.image_cache_hits", "game.image_cache_misses",
    "stage.denoise.admissions", "stage.denoise.preemptions",
    "stage.denoise.steps", "dispatch.thread_replacements",
    # encoder propagation: full-encoder vs decoder-only UNet forwards
    # the arm actually dispatched (zero in the full-forward arm and
    # under CASSMANTLE_NO_ENCPROP, so the A/B deltas separate arms)
    "pipeline.encprop_key_steps", "pipeline.encprop_shallow_steps",
    "pipeline.encprop_prop_steps",
    # overload control plane (ISSUE 13): brownout churn + shed totals
    "overload.brownout_trips", "overload.brownout_recoveries",
    "overload.score_shed", "overload.loop_lag_sheds",
    "pipeline.brownout_images",
    # embed-table scoring ladder (ISSUE 16): rung-0 serves vs queued
    # device dispatch — the A/B arms' attribution lives in these plus
    # the score queue totals (flat score.items IS the zero-device proof)
    "scorer.table_hits", "scorer.table_oov", "scorer.table_pins",
    "overload.table_served", "score.batches", "score.items",
    # output integrity + device recovery (ISSUE 17): invalid members
    # caught per pipeline/stage, staged-slot quarantines, and the
    # recovery loop's outcomes — a perf delta arriving with recoveries
    # or quarantines names its own cause
    "pipeline.output_invalid", "stage.denoise.quarantines",
    "rounds.generate_invalid", "device.recoveries",
    "device.recovery_permanent", "retry.budget_exhausted",
    "checkpoint.fingerprint_mismatch",
    # canary prober + tail sampling (ISSUE 18): probe verdict totals
    # (probe.failures rides the .failures suffix) and the tail
    # retention/abandonment accounting — a perf delta that arrives with
    # probe failures or abandoned traces names its own cause
    "probe.ok", "obs.tail_retained", "obs.traces_abandoned",
    # W8A8 serving (ISSUE 20): UNet forwards / LM bucket-group decode
    # dispatches that went through the int8 kernel path — zero in the
    # fp arms and under CASSMANTLE_NO_W8A8, so the A/B deltas are the
    # kernel-engagement receipts
    "pipeline.w8a8_dispatches",
}
_DELTA_SUFFIXES = (".dispatch_hangs", ".deadline_expired", ".rejected",
                   ".rejected_degraded", ".failures", ".loop_errors",
                   # overload control plane (ISSUE 13)
                   ".rejected_overload", ".rejected_predicted_late",
                   ".rejected_background",
                   # device-lost fail-fast rejections (ISSUE 17)
                   ".rejected_device_lost")


def _counter_snapshot() -> dict:
    from cassmantle_tpu.utils.logging import metrics

    return dict(metrics.snapshot()["counters"])


def _counter_deltas(before: dict, after: dict) -> dict:
    """Nonzero deltas of the diagnosis counters between two /metrics
    counter snapshots (labeled series keep their label suffix)."""
    out = {}
    for name, value in sorted(after.items()):
        base = name.split("{", 1)[0]
        if base not in _DELTA_COUNTERS and \
                not base.endswith(_DELTA_SUFFIXES):
            continue
        delta = value - before.get(name, 0.0)
        if delta:
            out[name] = int(delta) if float(delta).is_integer() \
                else delta
    return out


# Ordered by evidence-per-minute-of-tunnel-uptime: the north-star config
# and its fastest challenger run FIRST, so a tunnel that dies mid-suite
# (rounds 1-4 all hit this) still lands the two numbers the perf case
# turns on. Cheap CPU-light entries (scorer, gpt2) and the long e2e/soak
# runs come last.
SUITE = {
    "sd15": bench_sd15,
    "sd15_turbo": bench_sd15_turbo,
    "sd15_fast": bench_sd15_fast,
    "sd15_deepcache": bench_sd15_deepcache,
    "sd15_fusedconv": bench_sd15_fusedconv,
    "sd15_int8": bench_sd15_int8,
    "sd15_w8a8": bench_sd15_w8a8,
    "sd15_staged": bench_sd15_staged,
    "sd15_encprop": bench_sd15_encprop,
    "sd15_lcm": bench_sd15_lcm,
    "sd15_b8": bench_sd15_b8,
    "sdxl": bench_sdxl,
    "sdxl_encprop": bench_sdxl_encprop,
    "sdxl_w8a8": bench_sdxl_w8a8,
    "sdxl_turbo": bench_sdxl_turbo,
    "scorer": bench_scorer,
    "gpt2": bench_gpt2,
    "gpt2_spec": bench_gpt2_spec,
    "gpt2_w8a8": bench_gpt2_w8a8,
    "gpt2_b4": bench_gpt2_b4,
    "e2e": bench_e2e_round,
    "soak": bench_soak,
    "rooms_load": bench_rooms_load,
    "chaos_drill": bench_chaos_drill,
    "overload_drill": bench_overload_drill,
    "rooms_load_table": bench_rooms_load_table,
    "overload_drill_table": bench_overload_drill_table,
    "device_loss_drill": bench_device_loss_drill,
    "canary_drill": bench_canary_drill,
}

# ``--north-star-only`` measures exactly these, with BENCH_ROUNDS=1
# unless the caller already pinned a rep count: the smallest run that
# yields a stable hardware number for the target metric and its fastest
# challenger. The watcher fires this FIRST, so even a minutes-long
# tunnel window produces the evidence four full-suite attempts never
# got to.
NORTH_STAR_ENTRIES = ("sd15", "sd15_turbo")


def _kill_switch_already_set() -> bool:
    """Same parse as ops/attention.py: ''/'0'/'false'/'no'/'off' mean
    the flash-cross kernel is ENABLED (so a failure-retry with the kill
    switch is still worth attempting)."""
    return os.environ.get("CASSMANTLE_NO_FLASH_CROSS", "").lower() \
        not in ("", "0", "false", "no", "off")


def _run_entry_isolated(name: str, weights_dir: str,
                        timeout_s: float, cpu: bool = False) -> dict:
    """Run one suite entry as ``bench.py --entry NAME`` in a child
    process with a wall-clock timeout. Isolation matters for the two
    non-exception failure modes that can't be caught in-process: a
    device tunnel dying MID-suite (the call hangs forever, never
    raises — round 1 lost its numbers this way) and an OOM poisoning
    the shared process for every later entry. The persistent
    ``.jax_cache`` keeps per-child recompiles cheap.

    A child whose failure LOOKS like the flash-cross kernel (Pallas/
    Mosaic markers in stderr — e.g. a TPU generation rejecting it at
    compile) gets ONE retry with the kill switch set, budgeted within
    the entry's REMAINING time: a number on the proven path beats an
    error record, but a retry must never double the entry's wall-clock
    budget, and unrelated failures (missing weights, OOM) fail
    immediately with their real diagnostic. Timeouts never retry. A
    successful retry is sticky: the caller pre-sets the kill switch
    for every later entry, so one doomed compile isn't repeated 8x."""
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__),
           "--entry", name, weights_dir]
    if cpu:
        cmd.insert(2, "--platform-cpu")

    def run_once(extra_env: dict, budget_s: float):
        return subprocess.run(
            cmd, capture_output=True, text=True, timeout=budget_s,
            env={**os.environ, **extra_env})

    try:
        t0 = time.perf_counter()
        proc = run_once({}, timeout_s)
        retried = False
        flash_markers = ("pallas", "mosaic", "flash_cross")
        if (proc.returncode != 0 and not _kill_switch_already_set()
                and any(m in proc.stderr.lower()
                        for m in flash_markers)):
            remaining = max(60.0, timeout_s
                            - (time.perf_counter() - t0))
            sys.stderr.write(
                f"[suite] {name} failed (exit {proc.returncode}); "
                f"first attempt stderr tail:\n{proc.stderr[-1500:]}\n"
                f"[suite] retrying with CASSMANTLE_NO_FLASH_CROSS=1 "
                f"({remaining:.0f}s budget)\n")
            proc = run_once({"CASSMANTLE_NO_FLASH_CROSS": "1"},
                            remaining)
            retried = True
    except subprocess.TimeoutExpired as exc:
        # keep whatever the child said before the kill: the only
        # diagnostics for how far the entry got
        tail = (exc.stderr or b"")
        if isinstance(tail, bytes):
            tail = tail.decode("utf-8", "ignore")
        return {"metric": name,
                "error": f"timeout after {timeout_s:.0f}s "
                         f"(device hang mid-suite?)",
                "stderr_tail": tail[-500:]}
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        return {"metric": name,
                "error": f"exit {proc.returncode}: {proc.stderr[-500:]}"}
    try:
        res = json.loads(proc.stdout.splitlines()[-1])
    except Exception:
        return {"metric": name,
                "error": f"unparseable output: {proc.stdout[-300:]}"}
    if retried:
        res["flash_cross_disabled"] = True  # measured on the fallback
    return res


def main() -> None:
    args = list(sys.argv[1:])
    suite = "--suite" in args
    # --north-star-only: suite machinery (isolation, persistence, merge)
    # restricted to NORTH_STAR_ENTRIES at 1 timed round — the
    # short-tunnel-window fast path. An explicit BENCH_ROUNDS still wins.
    north_only = "--north-star-only" in args
    if north_only:
        suite = True
        os.environ.setdefault("BENCH_ROUNDS", "1")
    # --platform-cpu: CPU smoke of the bench harness itself (skips the
    # device probe; numbers are NOT measurements). Must pin before any
    # jax import — a dead accelerator tunnel otherwise hangs backend
    # init even for CPU-only work.
    cpu = "--platform-cpu" in args
    if cpu:
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)
    entry = None
    if "--entry" in args:
        i = args.index("--entry")
        if i + 1 >= len(args):
            sys.exit("--entry needs a suite entry name")
        entry = args[i + 1]
        del args[i:i + 2]
        if entry not in SUITE:
            sys.exit(f"unknown suite entry {entry!r}")
    flags = [a for a in args if a.startswith("--")]
    unknown = [f for f in flags
               if f not in ("--suite", "--platform-cpu",
                            "--north-star-only")]
    if unknown:
        sys.exit(f"unknown flag(s): {' '.join(unknown)} "
                 f"(--suite, --entry, --platform-cpu, "
                 f"--north-star-only)")
    args = [a for a in args if not a.startswith("--")]
    # defaults resolve against the repo, not the cwd (module-CLI runs
    # from anywhere); an explicit positional path keeps shell meaning
    repo = os.path.dirname(os.path.abspath(__file__))
    weights_dir = args[0] if args else os.path.join(repo, "weights")

    if entry:  # child mode: one entry, one JSON line, no probe
        # arm the jit compile sentinel (log-only) so the entry's delta
        # record can say how many (re)compiles its wall clock hides
        from cassmantle_tpu.utils import jit_sentinel

        jit_sentinel.enable_sentinel()
        before = _counter_snapshot()
        t0 = time.perf_counter()
        res = SUITE[entry](weights_dir)
        res["bench_wall_s"] = round(time.perf_counter() - t0, 1)
        deltas = _counter_deltas(before, _counter_snapshot())
        if deltas:
            res["counter_deltas"] = deltas
        print(json.dumps(res))
        return

    if not cpu:
        probe_device()
    if not suite:
        # fallback akin to the suite children's (though in-process, so
        # unlike theirs it shares state with the failed attempt): a
        # number on the proven XLA cross-attention path beats a crash.
        # The retry runs OUTSIDE the except block so the failed
        # pipeline's device buffers (pinned by the live traceback)
        # are released before a second pipeline is built.
        retry = False
        try:
            res = bench_sd15(weights_dir)
        except Exception:
            import traceback

            tb = traceback.format_exc()
            sys.stderr.write(tb)
            # only flash-kernel-shaped failures earn the fallback; an
            # unrelated error (missing path, OOM) must surface its real
            # diagnostic immediately, not after a second pipeline build
            if _kill_switch_already_set() or not any(
                    m in tb.lower()
                    for m in ("pallas", "mosaic", "flash_cross")):
                raise
            print("[bench] retrying with CASSMANTLE_NO_FLASH_CROSS=1",
                  file=sys.stderr)
            retry = True
        if retry:
            os.environ["CASSMANTLE_NO_FLASH_CROSS"] = "1"
            res = bench_sd15(weights_dir)
            res["flash_cross_disabled"] = True
        print(json.dumps(res))
        return

    entry_timeout = float(os.environ.get("BENCH_ENTRY_TIMEOUT", "2400"))
    wanted = os.environ.get("BENCH_SUITE_ENTRIES")
    if north_only:
        if wanted:
            sys.stderr.write(
                "[suite] --north-star-only overrides "
                f"BENCH_SUITE_ENTRIES={wanted!r}\n")
        names = list(NORTH_STAR_ENTRIES)
    elif wanted:
        names = [n.strip() for n in wanted.split(",") if n.strip()]
        bad = sorted(set(names) - set(SUITE))
        if bad or not names:
            # a typo must not buy a successful empty overnight run
            sys.exit(f"BENCH_SUITE_ENTRIES has unknown entries {bad}; "
                     f"valid: {sorted(SUITE)}")
    else:
        names = list(SUITE)
    # Per-entry persistence: the suite file is rewritten atomically the
    # moment each entry completes, so a tunnel dying mid-suite (rounds
    # 1-3 all lost whole runs this way) still lands every number
    # measured before the outage. Merge semantics: the run starts from
    # the existing record; a fresh success always overwrites, but a
    # fresh ERROR never clobbers a previously-measured success — a dead
    # tunnel must not erase hardware evidence. Partial runs
    # (BENCH_SUITE_ENTRIES) merge into the same file for the same
    # reason; there is no side ".partial" file any more.
    # BENCH_SUITE_PATH redirects the artifact (tests must not rewrite
    # the repo's real evidence file). CPU smoke runs are NOT
    # measurements — they get their own default file so a debug
    # invocation can never overwrite hardware evidence.
    default_name = ("BENCH_SUITE.cpu-smoke.json" if cpu
                    else "BENCH_SUITE.json")
    suite_path = os.environ.get(
        "BENCH_SUITE_PATH", os.path.join(repo, default_name))
    def load_disk() -> dict:
        if not os.path.exists(suite_path):
            return {}
        try:
            with open(suite_path) as f:
                data = json.load(f)
        except Exception as exc:
            sys.stderr.write(
                f"[suite] existing {suite_path} unreadable ({exc}); "
                f"starting fresh\n")
            return {}
        if not isinstance(data, dict):
            sys.stderr.write(
                f"[suite] existing {suite_path} is not an object; "
                f"starting fresh\n")
            return {}
        return data

    def persist_entry(name: str, res: dict) -> None:
        """Write ONE entry's outcome under an exclusive lock.

        Each entry is persisted exactly once, the moment it completes —
        never re-merged at later persists — so a concurrent suite run's
        fresher same-name measurement can't be clobbered by our older
        one at suite end. The read-resolve-write runs under the lock
        (per-pid tmp name) so two processes' writes can't interleave,
        and the keep-prior decision sees the LIVE file, not a snapshot.
        Merge rule: a fresh success overwrites; a fresh ERROR keeps a
        previously-measured success (a dead tunnel must not erase
        hardware evidence), annotated last_error/last_error_at so the
        file records that this run could not reproduce it."""
        import fcntl

        with open(suite_path + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            merged = load_disk()
            prev = merged.get(name)
            if ("error" in res and isinstance(prev, dict)
                    and "error" not in prev):
                sys.stderr.write(
                    f"[suite] {name} failed this run; keeping prior "
                    f"measurement from {prev.get('measured_at', '?')} "
                    f"(new error: {res['error'][:200]})\n")
                kept = dict(prev)
                kept["last_error"] = res["error"][:300]
                kept["last_error_at"] = res["measured_at"]
                merged[name] = kept
            else:
                merged[name] = res
            tmp = f"{suite_path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(merged, f, indent=2)
            os.replace(tmp, suite_path)

    # regression sentinel (tools/bench_diff.py): snapshot the PRE-run
    # suite state so the end-of-run diff compares this run's fresh
    # numbers against what the file held before we merged into it
    baseline_before = load_disk()
    fresh_results: dict = {}
    north_star = None
    for name in names:
        res = _run_entry_isolated(name, weights_dir, entry_timeout,
                                  cpu=cpu)
        if res.get("flash_cross_disabled"):
            # sticky: don't repeat the doomed kernel compile in every
            # remaining entry (children inherit our env)
            os.environ["CASSMANTLE_NO_FLASH_CROSS"] = "1"
        res["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        if name == "sd15":
            # the north-star guard below must see THIS run's outcome:
            # a fresh failure exits non-zero even when the file keeps a
            # prior measurement, so callers keying on the exit code
            # never mistake a stale number for a fresh green run
            north_star = res
        # the per-entry JSON stream always reports THIS run's outcome,
        # errors included; keep-prior only affects what's persisted
        print(json.dumps(res), file=sys.stderr)
        fresh_results[name] = res
        persist_entry(name, res)
    # print the regression-sentinel diff table (ISSUE 14): fresh run vs
    # the pre-run baseline, noise-aware per-entry tolerances. Advisory
    # here — the suite's exit semantics stay the north-star guard's;
    # gate CI on a separate `tools/bench_diff.py` invocation.
    try:
        from tools.bench_diff import diff_suites, format_table

        rows = diff_suites(baseline_before, fresh_results,
                           entries=list(fresh_results))
        sys.stderr.write("\n[suite] bench_diff vs pre-run baseline "
                         "(tools/bench_diff.py):\n"
                         + format_table(rows) + "\n")
    except Exception as exc:  # the diff must never fail the suite
        sys.stderr.write(f"[suite] bench_diff table unavailable: "
                         f"{exc}\n")
    if "sd15" in names and (north_star is None or "error" in north_star):
        # never emit a malformed north-star line with a zero exit
        sys.exit(f"north-star bench failed: {north_star}")
    if north_star is not None:
        print(json.dumps(north_star))


if __name__ == "__main__":
    main()
