// mantlestore — native state store for cassmantle_tpu.
//
// The reference outsources ALL shared state to a Redis server
// (SURVEY.md §1 L0: sessions, round content, the countdown-as-TTL clock,
// and the startup/buffer/promotion locks). This is the framework's native
// equivalent: a single-threaded epoll TCP server speaking a RESP2 subset,
// implementing exactly the operations the game engine's StateStore
// contract needs — strings with TTL, hashes, sets, and expiring locks.
//
// Design notes:
// - single-threaded event loop: every command is atomic by construction,
//   which is the property the engine's double-buffer/promotion logic
//   relies on (no torn read-modify-write between workers).
// - TTLs use the steady clock, checked lazily on access plus a periodic
//   sweep, mirroring redis semantics (TTL -> -2 missing, -1 no expiry).
// - locks are (token, deadline) pairs: LOCK name token ttl_ms -> +OK or
//   +BUSY; a crashed holder's lock self-expires. Blocking acquisition is
//   client-side (the engine polls with its acquire timeout).
//
// - durability: with a snapshot path, state serializes as a stream of
//   replayable RESP commands (SET/HSET/SADD + PEXPIRE with the REMAINING
//   ttl) — written atomically (tmp+rename) every snapshot_interval_s and
//   on SIGTERM/SIGINT, replayed through the normal dispatch at boot. A
//   restarted worker resumes the in-flight round exactly the way the
//   reference resumes from Redis durability (SURVEY.md §5.4).
//
// - replication (--repl / --follower): every mutating command appends to
//   a bounded in-memory command log with monotonically increasing
//   offsets. Followers are kept applied by a client-side pump
//   (engine/store.py ReplicatedStore) through the REPL verbs:
//     REPL OFFSET               -> [log_start, log_end, applied]
//     REPL TAIL from max        -> [next_offset, raw command stream]
//                                  ([-1] when `from` fell off the
//                                  trimmed log: full resync required)
//     REPL APPLY expected strm  -> new applied offset; the stream
//                                  replays through normal dispatch ONLY
//                                  when the local offset == expected, so
//                                  racing pumps apply exactly once
//                                  (HINCRBY and friends are not
//                                  idempotent)
//     REPL DUMP                 -> [log_end, full-state stream incl.
//                                  live locks] for resync
//     REPL RESET offset strm    -> flush + replay (unlogged) + set
//                                  offsets; the resync landing
//     REPL PROMOTE              -> +OK (follower becomes leader once the
//                                  replicated leader lease expired in
//                                  its local lock table) | +BUSY
//     REPL ROLE / REPL LEASE    -> observability
//   Replay is deterministic over the existing command set — a follower
//   is exactly the leader's command history re-executed, so lock
//   tombstone/overrun semantics carry over unchanged. The leader
//   heartbeats its lease through the ordinary LOCK discipline (a
//   logged `LOCK __repl:leader__ <id> <lease_ms>` refresh): followers
//   see liveness as a replicated lock entry, and a dead leader (or a
//   dead pump — indistinguishable, both mean the follower is blind)
//   reads as lease expiry. Followers reject client writes with
//   -READONLY; a demoted ex-leader that observes another holder on its
//   own lease steps down rather than split-brain.
//
// Build: g++ -O2 -std=c++17 -o mantlestore mantlestore.cc
// Run:   ./mantlestore [port] [snapshot_path [interval_s]]
//                      [--repl] [--follower] [--id NAME] [--lease-ms N]
//        (default port 7070, localhost only; no path = in-memory only)

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

using Clock = std::chrono::steady_clock;

static double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Store
// ---------------------------------------------------------------------------

struct Entry {
  enum Kind { STRING, HASH, SET } kind = STRING;
  std::string str;
  std::unordered_map<std::string, std::string> hash;
  std::unordered_set<std::string> set;
  double deadline = -1.0;  // -1 = no expiry
};

struct LockEntry {
  std::string token;
  double deadline;
};

class Store {
 public:
  bool alive(const std::string& key) {
    auto it = data_.find(key);
    if (it == data_.end()) return false;
    if (it->second.deadline >= 0 && now_s() >= it->second.deadline) {
      data_.erase(it);
      return false;
    }
    return true;
  }

  Entry* get(const std::string& key) {
    return alive(key) ? &data_[key] : nullptr;
  }

  Entry& upsert(const std::string& key, Entry::Kind kind) {
    // wrong-type writes REPLACE the entry with a fresh one of the new
    // kind (TTL cleared) — previously the entry kept its old kind, so
    // e.g. HSET over a string key wrote fields no HGET could see.
    // Pinned against MemoryStore in tests/test_store_parity.py.
    if (!alive(key) || data_[key].kind != kind) {
      Entry e;
      e.kind = kind;
      data_[key] = std::move(e);
    }
    return data_[key];
  }

  void erase(const std::string& key) { data_.erase(key); }

  void sweep() {
    double t = now_s();
    for (auto it = data_.begin(); it != data_.end();) {
      if (it->second.deadline >= 0 && t >= it->second.deadline)
        it = data_.erase(it);
      else
        ++it;
    }
    for (auto it = locks_.begin(); it != locks_.end();) {
      // keep expired entries for a grace period: LOCK already treats
      // them as acquirable, and the tombstone is what lets the owner's
      // late UNLOCK report :2 (overrun) instead of :0 — sweeping at the
      // deadline made that hazard verdict race the 1 Hz sweep
      if (t >= it->second.deadline + 60.0)
        it = locks_.erase(it);
      else
        ++it;
    }
  }

  std::unordered_map<std::string, Entry> data_;
  std::unordered_map<std::string, LockEntry> locks_;
};

// ---------------------------------------------------------------------------
// Replication state
// ---------------------------------------------------------------------------

static const char* kLeaderLease = "__repl:leader__";

struct Repl {
  bool enabled = false;
  bool leader = true;        // standalone servers are implicit leaders
  std::string id = "node";
  long long lease_ms = 3000;
  // Command log: serialized RESP commands, offsets [log_start,
  // log_start + log.size()). Trimmed from the front past max_log —
  // a follower that fell off the window does a full REPL DUMP resync.
  std::deque<std::string> log;
  long long log_start = 0;
  size_t max_log = 65536;

  long long log_end() const { return log_start + (long long)log.size(); }

  void append(const std::string& serialized) {
    log.push_back(serialized);
    while (log.size() > max_log) {
      log.pop_front();
      log_start++;
    }
  }
};

static Repl g_repl;

// Who is asking: a real client (readonly-checked on followers, logged),
// the replication replay path (not readonly-checked — it IS how
// follower state advances — but logged so the follower's log mirrors
// the leader's), or a load path (snapshot boot / RESET: neither).
enum Origin { ORIGIN_CLIENT, ORIGIN_REPLAY, ORIGIN_LOAD };

static bool is_mutating(const std::string& cmd) {
  static const std::unordered_set<std::string> kMutating = {
      "SET", "SETEX", "DEL", "PEXPIRE", "HSET", "HDEL", "HINCRBY",
      "SADD", "SREM", "LOCK", "UNLOCK", "FLUSHALL"};
  return kMutating.count(cmd) > 0;
}

// ---------------------------------------------------------------------------
// RESP protocol
// ---------------------------------------------------------------------------

static void resp_simple(std::string& out, const char* s) {
  out += '+';
  out += s;
  out += "\r\n";
}

static void resp_error(std::string& out, const char* s) {
  out += '-';
  out += s;
  out += "\r\n";
}

static void resp_int(std::string& out, long long v) {
  out += ':';
  out += std::to_string(v);
  out += "\r\n";
}

static void resp_bulk(std::string& out, const std::string& v) {
  out += '$';
  out += std::to_string(v.size());
  out += "\r\n";
  out += v;
  out += "\r\n";
}

static void resp_nil(std::string& out) { out += "$-1\r\n"; }

static void resp_array_header(std::string& out, size_t n) {
  out += '*';
  out += std::to_string(n);
  out += "\r\n";
}

// Parse one RESP array-of-bulk-strings command from buf starting at pos.
// Returns true + advances pos when a full command was parsed.
static bool parse_command(const std::string& buf, size_t& pos,
                          std::vector<std::string>& argv) {
  argv.clear();
  size_t p = pos;
  if (p >= buf.size() || buf[p] != '*') return false;
  size_t eol = buf.find("\r\n", p);
  if (eol == std::string::npos) return false;
  long n = strtol(buf.c_str() + p + 1, nullptr, 10);
  if (n < 0 || n > 1024) return false;
  p = eol + 2;
  for (long i = 0; i < n; i++) {
    if (p >= buf.size() || buf[p] != '$') return false;
    eol = buf.find("\r\n", p);
    if (eol == std::string::npos) return false;
    long len = strtol(buf.c_str() + p + 1, nullptr, 10);
    if (len < 0 || len > (64 << 20)) return false;
    p = eol + 2;
    if (buf.size() < p + (size_t)len + 2) return false;
    argv.emplace_back(buf, p, len);
    p += len + 2;
  }
  pos = p;
  return true;
}

// ---------------------------------------------------------------------------
// Command dispatch
// ---------------------------------------------------------------------------

static void emit_command(std::string& out,
                         const std::vector<std::string>& argv);
static void serialize_state(Store& store, std::string& out,
                            bool include_locks);
static void execute(Store& store, const std::vector<std::string>& argv,
                    std::string& out, Origin origin);
static void heartbeat_lease(Store& store);

static void repl_command(Store& store, const std::vector<std::string>& argv,
                         std::string& out) {
  std::string sub = argv.size() > 1 ? argv[1] : "";
  for (auto& c : sub) c = toupper(c);

  if (sub == "ROLE" && argv.size() == 2) {
    // standalone (repl disabled) answers "leader": a single-endpoint
    // ReplicatedStore degenerates to a plain client
    resp_simple(out, g_repl.leader ? "leader" : "follower");
  } else if (sub == "OFFSET" && argv.size() == 2) {
    resp_array_header(out, 3);
    resp_int(out, g_repl.log_start);
    resp_int(out, g_repl.log_end());
    resp_int(out, g_repl.log_end());  // applied == log_end by construction
  } else if (sub == "TAIL" && argv.size() == 4) {
    if (!g_repl.enabled) {
      resp_error(out, "ERR replication disabled");
      return;
    }
    long long from = strtoll(argv[2].c_str(), nullptr, 10);
    long long maxn = strtoll(argv[3].c_str(), nullptr, 10);
    if (from < g_repl.log_start) {
      resp_array_header(out, 1);
      resp_int(out, -1);  // trimmed past `from`: resync required
      return;
    }
    long long n = g_repl.log_end() - from;
    if (maxn >= 0 && n > maxn) n = maxn;
    if (n < 0) n = 0;
    std::string stream;
    for (long long i = 0; i < n; i++)
      stream += g_repl.log[(size_t)(from - g_repl.log_start + i)];
    resp_array_header(out, 2);
    resp_int(out, from + n);
    resp_bulk(out, stream);
  } else if (sub == "APPLY" && argv.size() == 4) {
    if (!g_repl.enabled) {
      resp_error(out, "ERR replication disabled");
      return;
    }
    if (g_repl.leader) {
      resp_error(out, "ERR leader does not APPLY");
      return;
    }
    long long expected = strtoll(argv[2].c_str(), nullptr, 10);
    if (expected != g_repl.log_end()) {
      // precondition failed (a racing pump already applied this batch,
      // or the caller is stale): apply nothing, report local truth
      resp_int(out, g_repl.log_end());
      return;
    }
    size_t pos = 0;
    std::vector<std::string> cmd_args;
    std::string discard;
    while (parse_command(argv[3], pos, cmd_args)) {
      execute(store, cmd_args, discard, ORIGIN_REPLAY);
      discard.clear();
    }
    resp_int(out, g_repl.log_end());
  } else if (sub == "DUMP" && argv.size() == 2) {
    std::string stream;
    serialize_state(store, stream, /*include_locks=*/true);
    resp_array_header(out, 2);
    resp_int(out, g_repl.log_end());
    resp_bulk(out, stream);
  } else if (sub == "RESET" && argv.size() == 4) {
    if (!g_repl.enabled) {
      resp_error(out, "ERR replication disabled");
      return;
    }
    long long offset = strtoll(argv[2].c_str(), nullptr, 10);
    store.data_.clear();
    store.locks_.clear();
    g_repl.log.clear();
    g_repl.log_start = offset;
    size_t pos = 0;
    std::vector<std::string> cmd_args;
    std::string discard;
    while (parse_command(argv[3], pos, cmd_args)) {
      execute(store, cmd_args, discard, ORIGIN_LOAD);
      discard.clear();
    }
    resp_int(out, offset);
  } else if (sub == "PROMOTE" && argv.size() == 2) {
    if (!g_repl.enabled) {
      resp_error(out, "ERR replication disabled");
      return;
    }
    if (g_repl.leader) {
      resp_simple(out, "OK");  // idempotent
      return;
    }
    auto it = store.locks_.find(kLeaderLease);
    if (it != store.locks_.end() && now_s() < it->second.deadline &&
        it->second.token != g_repl.id) {
      // the replicated lease is still live: the leader (and the pump
      // feeding us) was heartbeating within the TTL — refusing here is
      // what prevents a promotion racing a healthy leader
      resp_simple(out, "BUSY");
      return;
    }
    g_repl.leader = true;
    heartbeat_lease(store);  // claim the lease in our own log NOW
    fprintf(stderr, "mantlestore: promoted to leader (id=%s)\n",
            g_repl.id.c_str());
    resp_simple(out, "OK");
  } else if (sub == "LEASE" && argv.size() == 2) {
    auto it = store.locks_.find(kLeaderLease);
    bool live = it != store.locks_.end() && now_s() < it->second.deadline;
    resp_array_header(out, 2);
    resp_bulk(out, live ? it->second.token : "");
    resp_int(out, live
                 ? (long long)((it->second.deadline - now_s()) * 1000.0)
                 : 0);
  } else {
    resp_error(out, "ERR unknown REPL subcommand");
  }
}

static void execute_core(Store& store, const std::vector<std::string>& argv,
                         std::string& out, const std::string& cmd) {
  if (cmd == "PING") {
    resp_simple(out, "PONG");
  } else if (cmd == "SET" && argv.size() == 3) {
    Entry& e = store.upsert(argv[1], Entry::STRING);
    e.kind = Entry::STRING;
    e.str = argv[2];
    e.deadline = -1;
    resp_simple(out, "OK");
  } else if (cmd == "SETEX" && argv.size() == 4) {
    // SETEX key ttl_ms value  (milliseconds for sub-second test clocks)
    Entry& e = store.upsert(argv[1], Entry::STRING);
    e.kind = Entry::STRING;
    e.str = argv[3];
    e.deadline = now_s() + strtod(argv[2].c_str(), nullptr) / 1000.0;
    resp_simple(out, "OK");
  } else if (cmd == "GET" && argv.size() == 2) {
    Entry* e = store.get(argv[1]);
    if (e && e->kind == Entry::STRING)
      resp_bulk(out, e->str);
    else
      resp_nil(out);
  } else if (cmd == "DEL" && argv.size() >= 2) {
    long long n = 0;
    for (size_t i = 1; i < argv.size(); i++) {
      if (store.alive(argv[i])) n++;
      store.erase(argv[i]);
    }
    resp_int(out, n);
  } else if (cmd == "EXISTS" && argv.size() == 2) {
    resp_int(out, store.alive(argv[1]) ? 1 : 0);
  } else if (cmd == "PEXPIRE" && argv.size() == 3) {
    Entry* e = store.get(argv[1]);
    if (e) {
      e->deadline = now_s() + strtod(argv[2].c_str(), nullptr) / 1000.0;
      resp_int(out, 1);
    } else {
      resp_int(out, 0);
    }
  } else if (cmd == "PTTL" && argv.size() == 2) {
    Entry* e = store.get(argv[1]);
    if (!e)
      resp_int(out, -2);
    else if (e->deadline < 0)
      resp_int(out, -1);
    else
      resp_int(out, (long long)((e->deadline - now_s()) * 1000.0));
  } else if (cmd == "HSET" && argv.size() >= 4 && argv.size() % 2 == 0) {
    Entry& e = store.upsert(argv[1], Entry::HASH);
    long long added = 0;
    for (size_t i = 2; i + 1 < argv.size(); i += 2) {
      added += e.hash.count(argv[i]) ? 0 : 1;
      e.hash[argv[i]] = argv[i + 1];
    }
    resp_int(out, added);
  } else if (cmd == "HGET" && argv.size() == 3) {
    Entry* e = store.get(argv[1]);
    if (e && e->kind == Entry::HASH) {
      auto it = e->hash.find(argv[2]);
      if (it != e->hash.end()) {
        resp_bulk(out, it->second);
        return;
      }
    }
    resp_nil(out);
  } else if (cmd == "HGETALL" && argv.size() == 2) {
    Entry* e = store.get(argv[1]);
    if (e && e->kind == Entry::HASH) {
      resp_array_header(out, e->hash.size() * 2);
      for (auto& kv : e->hash) {
        resp_bulk(out, kv.first);
        resp_bulk(out, kv.second);
      }
    } else {
      resp_array_header(out, 0);
    }
  } else if (cmd == "HDEL" && argv.size() >= 3) {
    Entry* e = store.get(argv[1]);
    long long n = 0;
    if (e && e->kind == Entry::HASH)
      for (size_t i = 2; i < argv.size(); i++) n += e->hash.erase(argv[i]);
    resp_int(out, n);
  } else if (cmd == "HINCRBY" && argv.size() == 4) {
    Entry& e = store.upsert(argv[1], Entry::HASH);
    long long v = 0;
    auto it = e.hash.find(argv[2]);
    if (it != e.hash.end()) v = strtoll(it->second.c_str(), nullptr, 10);
    v += strtoll(argv[3].c_str(), nullptr, 10);
    e.hash[argv[2]] = std::to_string(v);
    resp_int(out, v);
  } else if (cmd == "SADD" && argv.size() >= 3) {
    Entry& e = store.upsert(argv[1], Entry::SET);
    long long n = 0;
    for (size_t i = 2; i < argv.size(); i++)
      n += e.set.insert(argv[i]).second ? 1 : 0;
    resp_int(out, n);
  } else if (cmd == "SREM" && argv.size() >= 3) {
    Entry* e = store.get(argv[1]);
    long long n = 0;
    if (e && e->kind == Entry::SET)
      for (size_t i = 2; i < argv.size(); i++) n += e->set.erase(argv[i]);
    resp_int(out, n);
  } else if (cmd == "SMEMBERS" && argv.size() == 2) {
    Entry* e = store.get(argv[1]);
    if (e && e->kind == Entry::SET) {
      resp_array_header(out, e->set.size());
      for (auto& m : e->set) resp_bulk(out, m);
    } else {
      resp_array_header(out, 0);
    }
  } else if (cmd == "SISMEMBER" && argv.size() == 3) {
    Entry* e = store.get(argv[1]);
    resp_int(out,
             (e && e->kind == Entry::SET && e->set.count(argv[2])) ? 1 : 0);
  } else if (cmd == "LOCK" && argv.size() == 4) {
    // LOCK name token ttl_ms -> +OK acquired | +BUSY held by other
    auto it = store.locks_.find(argv[1]);
    if (it != store.locks_.end() && now_s() < it->second.deadline &&
        it->second.token != argv[2]) {
      resp_simple(out, "BUSY");
    } else {
      store.locks_[argv[1]] = {
          argv[2], now_s() + strtod(argv[3].c_str(), nullptr) / 1000.0};
      resp_simple(out, "OK");
    }
  } else if (cmd == "UNLOCK" && argv.size() == 3) {
    // UNLOCK name token -> :1 released | :0 not held by this token
    // (TTL lapsed AND reacquired/steal-eligible) | :2 own token found
    // but past its TTL (overrun: exclusion not guaranteed for the hold
    // tail). The client maps :0/:2 onto the same hazard taxonomy as
    // MemoryStore — see cassmantle_tpu/native/client.py.
    auto it = store.locks_.find(argv[1]);
    if (it != store.locks_.end() && it->second.token == argv[2]) {
      bool live = now_s() < it->second.deadline;
      store.locks_.erase(it);
      resp_int(out, live ? 1 : 2);
    } else {
      resp_int(out, 0);
    }
  } else if (cmd == "FLUSHALL" && argv.size() == 1) {
    store.data_.clear();
    store.locks_.clear();
    resp_simple(out, "OK");
  } else if (cmd == "DBSIZE" && argv.size() == 1) {
    store.sweep();
    resp_int(out, (long long)store.data_.size());
  } else {
    resp_error(out, "ERR unknown command");
  }
}

static void execute(Store& store, const std::vector<std::string>& argv,
                    std::string& out, Origin origin = ORIGIN_CLIENT) {
  if (argv.empty()) {
    resp_error(out, "ERR empty command");
    return;
  }
  std::string cmd = argv[0];
  for (auto& c : cmd) c = toupper(c);

  if (cmd == "REPL") {
    repl_command(store, argv, out);
    return;
  }
  bool mutating = is_mutating(cmd);
  if (mutating && origin == ORIGIN_CLIENT && g_repl.enabled &&
      !g_repl.leader) {
    // redis-style fencing: after a failover, a stale worker still
    // writing to this (now-follower) node must fail loudly, not fork
    // the state — its ReplicatedStore treats READONLY as
    // leadership-changed and re-elects
    resp_error(out, "READONLY follower");
    return;
  }
  size_t before = out.size();
  execute_core(store, argv, out, cmd);
  bool append = mutating && g_repl.enabled && origin != ORIGIN_LOAD;
  if (append && origin == ORIGIN_CLIENT) {
    // CLIENT commands append only when they actually mutated: errors,
    // +BUSY LOCKs, and :0 UNLOCKs changed nothing — replaying a BUSY
    // LOCK on a follower would ACQUIRE the lock there and fork the
    // lock tables.
    if (out.size() > before && out[before] == '-')
      append = false;
    else if (cmd == "LOCK")
      append = out.compare(before, 3, "+OK") == 0;
    else if (cmd == "UNLOCK")
      append = out.compare(before, 2, ":0") != 0;
  }
  // REPLAY appends UNCONDITIONALLY: the follower's log must mirror the
  // byte stream it was shipped, not its own re-derived verdicts — a
  // replayed LOCK can locally answer +BUSY (its TTL was recomputed at
  // apply time, so a lapsed-then-retaken lock can look still-live on a
  // lagging follower) and verdict-gating the append would skew the
  // offset bookkeeping and double-apply the next command (breaking
  // exactly-once for HINCRBY and friends). The transient lock-table
  // skew converges as TTLs expire and only ever DELAYS a promote.
  if (append) {
    std::string serialized;
    emit_command(serialized, argv);
    g_repl.append(serialized);
  }
}

// Leader lease heartbeat: an ordinary logged LOCK refresh, so
// followers observe leader liveness as a replicated lock entry and the
// lease obeys the exact LOCK/TTL discipline everything else does. A
// BUSY answer means ANOTHER id holds a live lease in our own table
// (we were demoted and somehow kept running): step down.
static void heartbeat_lease(Store& store) {
  if (!g_repl.enabled || !g_repl.leader) return;
  std::vector<std::string> cmd = {kLeaderLease, g_repl.id,
                                  std::to_string(g_repl.lease_ms)};
  cmd.insert(cmd.begin(), "LOCK");
  std::string out;
  // CLIENT origin: the leader's own command, so the append stays
  // verdict-gated — a +BUSY refresh (the demote case) must never land
  // in the log, where followers would replay it as an acquisition
  execute(store, cmd, out, ORIGIN_CLIENT);
  if (out.rfind("+BUSY", 0) == 0) {
    g_repl.leader = false;
    fprintf(stderr, "mantlestore: lease held by another id; demoting\n");
  }
}

// ---------------------------------------------------------------------------
// Snapshot persistence (replayable RESP command stream)
// ---------------------------------------------------------------------------

static void emit_command(std::string& out,
                         const std::vector<std::string>& argv) {
  resp_array_header(out, argv.size());
  for (const auto& a : argv) resp_bulk(out, a);
}

static void serialize_state(Store& store, std::string& out,
                            bool include_locks) {
  store.sweep();
  double t = now_s();
  // parse_command caps commands at 1024 args: chunk multi-member emits
  // well below that so replay never truncates.
  const size_t kChunk = 512;
  for (const auto& [key, e] : store.data_) {
    long long ms = -1;
    if (e.deadline >= 0) {
      ms = (long long)((e.deadline - t) * 1000.0);
      if (ms <= 0) continue;  // effectively expired: don't resurrect it
    }
    if (e.kind == Entry::STRING) {
      emit_command(out, {"SET", key, e.str});
    } else if (e.kind == Entry::HASH) {
      std::vector<std::string> cmd = {"HSET", key};
      for (const auto& [f, v] : e.hash) {
        cmd.push_back(f);
        cmd.push_back(v);
        if (cmd.size() >= kChunk) {
          emit_command(out, cmd);
          cmd = {"HSET", key};
        }
      }
      if (cmd.size() > 2) emit_command(out, cmd);
    } else {
      std::vector<std::string> cmd = {"SADD", key};
      for (const auto& m : e.set) {
        cmd.push_back(m);
        if (cmd.size() >= kChunk) {
          emit_command(out, cmd);
          cmd = {"SADD", key};
        }
      }
      if (cmd.size() > 2) emit_command(out, cmd);
    }
    if (ms > 0)
      emit_command(out, {"PEXPIRE", key, std::to_string(ms)});
  }
  if (include_locks) {
    // the resync path (REPL DUMP) carries live locks so a fresh
    // follower knows the leader lease and any round-lifecycle holder;
    // expired tombstones are skipped (their only job is the owner's
    // late-UNLOCK verdict, and the owner talks to the leader)
    for (const auto& [name, lk] : store.locks_) {
      long long ms = (long long)((lk.deadline - t) * 1000.0);
      if (ms > 0)
        emit_command(out, {"LOCK", name, lk.token, std::to_string(ms)});
    }
  }
}

static bool save_snapshot(Store& store, const std::string& path) {
  std::string out;
  // locks deliberately not persisted across restarts: they self-expire
  // and a restarted holder must not believe it still owns one
  serialize_state(store, out, /*include_locks=*/false);
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return false;
  bool ok = fwrite(out.data(), 1, out.size(), f) == out.size();
  // fsync before rename: otherwise a crash can persist the rename but
  // not the data blocks, replacing a good snapshot with a torn one
  ok = fflush(f) == 0 && ok;
  ok = fsync(fileno(f)) == 0 && ok;
  ok = fclose(f) == 0 && ok;
  if (ok) ok = rename(tmp.c_str(), path.c_str()) == 0;
  if (!ok) remove(tmp.c_str());
  return ok;
}

static void load_snapshot(Store& store, const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return;  // first boot: nothing to restore
  std::string buf;
  char chunk[65536];
  size_t r;
  while ((r = fread(chunk, 1, sizeof(chunk), f)) > 0) buf.append(chunk, r);
  fclose(f);
  size_t pos = 0;
  std::vector<std::string> argv;
  std::string discard;
  size_t n = 0;
  while (parse_command(buf, pos, argv)) {
    execute(store, argv, discard, ORIGIN_LOAD);
    discard.clear();
    n++;
  }
  fprintf(stderr, "mantlestore: restored %zu commands from %s\n", n,
          path.c_str());
}

// ---------------------------------------------------------------------------
// Event loop
// ---------------------------------------------------------------------------

struct Conn {
  int fd;
  std::string in;
  std::string out;
  size_t out_off = 0;
};

static int set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

static volatile sig_atomic_t g_shutdown = 0;
static void on_term(int) { g_shutdown = 1; }

int main(int argc, char** argv) {
  int port = 7070;
  std::string snapshot_path;
  double snapshot_interval = 30.0;
  int positional = 0;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    if (arg == "--repl") {
      g_repl.enabled = true;
    } else if (arg == "--follower") {
      g_repl.enabled = true;
      g_repl.leader = false;
    } else if (arg == "--id" && i + 1 < argc) {
      g_repl.id = argv[++i];
    } else if (arg == "--lease-ms" && i + 1 < argc) {
      g_repl.lease_ms = strtoll(argv[++i], nullptr, 10);
    } else if (arg == "--max-log" && i + 1 < argc) {
      g_repl.max_log = (size_t)strtoll(argv[++i], nullptr, 10);
    } else if (positional == 0) {
      port = atoi(arg.c_str());
      positional++;
    } else if (positional == 1) {
      snapshot_path = arg;
      positional++;
    } else if (positional == 2) {
      snapshot_interval = strtod(arg.c_str(), nullptr);
      positional++;
    }
  }
  signal(SIGPIPE, SIG_IGN);
  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);

  int listener = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(listener, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(listener, 128);
  set_nonblock(listener);

  int ep = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = listener;
  epoll_ctl(ep, EPOLL_CTL_ADD, listener, &ev);

  Store store;
  if (!snapshot_path.empty()) load_snapshot(store, snapshot_path);
  std::unordered_map<int, Conn> conns;
  std::vector<std::string> cmd_args;
  double last_sweep = now_s();
  double last_save = now_s();
  // heartbeat well inside the lease (3 beats per TTL, ≥4 Hz ceiling
  // from the 250 ms epoll timeout) so one dropped beat never lapses it
  double hb_interval = g_repl.lease_ms / 3000.0;
  if (hb_interval > 1.0) hb_interval = 1.0;
  double last_hb = 0.0;
  if (g_repl.enabled && g_repl.leader) {
    heartbeat_lease(store);
    last_hb = now_s();
  }

  fprintf(stderr, "mantlestore listening on 127.0.0.1:%d%s%s\n", port,
          snapshot_path.empty() ? "" : " (durable)",
          !g_repl.enabled ? ""
                          : (g_repl.leader ? " (repl leader)"
                                           : " (repl follower)"));
  fflush(stderr);

  epoll_event events[64];
  for (;;) {
    int n = epoll_wait(ep, events, 64, 250);
    if (g_shutdown) {
      if (!snapshot_path.empty()) {
        if (save_snapshot(store, snapshot_path)) {
          fprintf(stderr, "mantlestore: snapshot saved on shutdown\n");
          return 0;
        }
        fprintf(stderr, "mantlestore: SNAPSHOT SAVE FAILED on shutdown\n");
        return 1;
      }
      return 0;
    }
    if (now_s() - last_sweep > 1.0) {
      store.sweep();
      last_sweep = now_s();
    }
    if (g_repl.enabled && g_repl.leader &&
        now_s() - last_hb > hb_interval) {
      heartbeat_lease(store);
      last_hb = now_s();
    }
    if (!snapshot_path.empty() &&
        now_s() - last_save > snapshot_interval) {
      if (!save_snapshot(store, snapshot_path))
        fprintf(stderr, "mantlestore: periodic snapshot save failed\n");
      last_save = now_s();
    }
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == listener) {
        for (;;) {
          int cfd = accept(listener, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &cev);
          conns[cfd] = Conn{cfd};
        }
        continue;
      }
      auto cit = conns.find(fd);
      if (cit == conns.end()) continue;
      Conn& conn = cit->second;
      bool closed = false;

      if (events[i].events & (EPOLLIN | EPOLLHUP | EPOLLERR)) {
        char buf[65536];
        for (;;) {
          ssize_t r = read(fd, buf, sizeof(buf));
          if (r > 0) {
            conn.in.append(buf, r);
          } else if (r == 0) {
            closed = true;
            break;
          } else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            closed = true;
            break;
          }
        }
        size_t pos = 0;
        while (parse_command(conn.in, pos, cmd_args))
          execute(store, cmd_args, conn.out);
        if (pos > 0) conn.in.erase(0, pos);
        if (conn.in.size() > (64u << 20)) closed = true;  // abuse guard
      }

      if (!closed && !conn.out.empty()) {
        ssize_t w = write(fd, conn.out.data() + conn.out_off,
                          conn.out.size() - conn.out_off);
        if (w > 0) {
          conn.out_off += w;
          if (conn.out_off == conn.out.size()) {
            conn.out.clear();
            conn.out_off = 0;
          }
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          closed = true;
        }
        // if output remains, watch for writability too
        epoll_event cev{};
        cev.events = EPOLLIN | (conn.out.empty() ? 0 : EPOLLOUT);
        cev.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &cev);
      }

      if (closed) {
        epoll_ctl(ep, EPOLL_CTL_DEL, fd, nullptr);
        close(fd);
        conns.erase(fd);
      }
    }
  }
  return 0;
}
