#!/bin/sh
# Build mantlestore into native/build/.
set -e
cd "$(dirname "$0")"
mkdir -p build
g++ -O2 -std=c++17 -Wall -o build/mantlestore mantlestore.cc
echo "built native/build/mantlestore"
