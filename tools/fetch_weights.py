"""One-shot weight/tokenizer bootstrap (reference download_model.py analogue).

The reference's bootstrap downloads NLTK corpora and a gensim word2vec
artifact (download_model.py:4-10). This framework's artifacts are model
checkpoints + tokenizer vocabularies, laid out as::

    weights/
      clip_text.safetensors   # CLIP ViT-L/14 FULL model: text tower
                              # (SD1.5's encoder) + vision tower + both
                              # projections (eval/clip_parity.py loads
                              # the image side from this same file)
      unet.safetensors        # SD1.5 UNet
      vae.safetensors         # SD VAE (decoder+post_quant used)
      gpt2.safetensors        # GPT-2-small
      minilm.safetensors      # all-MiniLM-L6-v2
      clip_text_2.safetensors # OpenCLIP bigG text tower (SDXL)
      unet_xl.safetensors     # SDXL-base UNet
      vae_xl.safetensors      # SDXL VAE
      clip_vocab.json / clip_merges.txt
      gpt2_vocab.json / gpt2_merges.txt
      minilm_vocab.txt

Run this on a machine WITH network egress; every pipeline automatically
prefers these files over random init (models/weights.py:maybe_load,
utils/tokenizers.py:load_tokenizer). In a zero-egress environment this
script exits gracefully and the framework runs on deterministic random
init.

Usage:  python tools/fetch_weights.py [--out weights]
"""

from __future__ import annotations

import argparse
import os
import sys

SOURCES = {
    "clip_text.safetensors": (
        "openai/clip-vit-large-patch14", "model.safetensors"),
    "unet.safetensors": (
        "runwayml/stable-diffusion-v1-5", "unet/diffusion_pytorch_model.safetensors"),
    "vae.safetensors": (
        "runwayml/stable-diffusion-v1-5", "vae/diffusion_pytorch_model.safetensors"),
    "gpt2.safetensors": ("gpt2", "model.safetensors"),
    "minilm.safetensors": (
        "sentence-transformers/all-MiniLM-L6-v2", "model.safetensors"),
    "gpt2_vocab.json": ("gpt2", "vocab.json"),
    "gpt2_merges.txt": ("gpt2", "merges.txt"),
    "clip_vocab.json": ("openai/clip-vit-large-patch14", "vocab.json"),
    "clip_merges.txt": ("openai/clip-vit-large-patch14", "merges.txt"),
    "minilm_vocab.txt": (
        "sentence-transformers/all-MiniLM-L6-v2", "vocab.txt"),
    # Mistral-7B-Instruct (models/mistral.py) — the reference's actual
    # prompt LLM (backend.py:25). Sharded checkpoint: fetch both shards;
    # load_safetensors callers merge dicts.
    "mistral-00001.safetensors": (
        "mistralai/Mistral-7B-Instruct-v0.1",
        "model-00001-of-00002.safetensors"),
    "mistral-00002.safetensors": (
        "mistralai/Mistral-7B-Instruct-v0.1",
        "model-00002-of-00002.safetensors"),
    "mistral_tokenizer.json": (
        "mistralai/Mistral-7B-Instruct-v0.1", "tokenizer.json"),
    # SDXL-base (serving/sdxl.py): second text tower + XL UNet/VAE
    "clip_text_2.safetensors": (
        "stabilityai/stable-diffusion-xl-base-1.0",
        "text_encoder_2/model.safetensors"),
    "unet_xl.safetensors": (
        "stabilityai/stable-diffusion-xl-base-1.0",
        "unet/diffusion_pytorch_model.safetensors"),
    "vae_xl.safetensors": (
        "stabilityai/stable-diffusion-xl-base-1.0",
        "vae/diffusion_pytorch_model.safetensors"),
}


def main() -> int:
    parser = argparse.ArgumentParser()
    # default resolves against the repo (where bench/clip-report/serve
    # look for weights/); an explicit --out keeps its shell meaning
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "weights"))
    args = parser.parse_args()
    os.makedirs(args.out, exist_ok=True)

    try:
        from huggingface_hub import hf_hub_download
    except ImportError:
        print("huggingface_hub unavailable; cannot fetch weights.")
        return 1

    failures = []
    for filename, (repo, remote) in SOURCES.items():
        target = os.path.join(args.out, filename)
        if os.path.exists(target):
            print(f"[skip] {filename} already present")
            continue
        try:
            path = hf_hub_download(repo_id=repo, filename=remote)
            os.replace(path, target) if os.access(
                os.path.dirname(path), os.W_OK
            ) else None
            if not os.path.exists(target):
                import shutil

                shutil.copyfile(path, target)
            print(f"[ok]   {filename} <- {repo}/{remote}")
        except Exception as exc:  # zero-egress or transient
            failures.append(filename)
            print(f"[fail] {filename}: {exc}")

    if failures:
        print(f"\n{len(failures)} artifacts missing; the framework will "
              "use deterministic random init for those models.")
        return 0  # not fatal by design
    print("\nAll artifacts fetched.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
