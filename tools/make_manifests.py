"""Generate authoritative checkpoint key manifests (data/manifests/).

VERDICT r2 #3: the weight-conversion tests previously fabricated torch
checkpoints from an in-repo reverse mapping — written by the same hand,
against the same assumptions, as the converters they test. A naming or
layout mismatch with the real published artifacts would keep every test
green while the first real-weights boot silently fell back to random
init. These manifests pin the converters to the *authentic* inventories
(tests/test_weights.py feeds them through the real converters and
requires 100% key coverage; see ``manifest tests`` there).

Authority, per model family (this container has zero egress, so the
inventories cannot be downloaded — they are derived from sources that
are themselves authoritative):

- transformers-hosted checkpoints (CLIP, GPT-2, MiniLM/BERT, Mistral):
  the safetensors files on the Hub hold exactly the torch
  ``state_dict()`` of the corresponding transformers model class at the
  published config. We instantiate those classes on the ``meta`` device
  (no weights, no memory) and dump name+shape — the same library code
  path that produced the real files' key sets. Known save-era deltas
  (buffers persisted by older transformers, e.g.
  ``embeddings.position_ids``; GPT-2's causal-mask buffers) are appended
  as ``optional`` keys: present in the published files, absent from a
  modern state_dict, and semantically ignorable.
- diffusers-hosted checkpoints (SD1.5/SDXL UNet + VAE — diffusers is
  NOT installed here): generated from the diffusers state-dict naming
  grammar at the published configs, then validated against the exact
  published parameter totals (SD1.5 UNet 859,520,964; SDXL UNet
  2,567,463,684; AutoencoderKL 83,653,863). A wrong block layout,
  missing tensor, or wrong shape cannot sum to the right total.
  Era note: the SD1.5-era VAE file predates the diffusers Attention
  refactor and names mid-block attention ``query/key/value/proj_attn``;
  the SDXL-era file uses ``to_q/to_k/to_v/to_out.0``. Both manifests
  encode their own era's naming and models/weights.py accepts both.

Usage:  python tools/make_manifests.py [--check]
  --check: regenerate in-memory and diff against data/manifests/
           (non-zero exit on drift) instead of writing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO_ROOT, "data", "manifests")

# Exact published totals (parameters, not tensors). The transformers
# ones double-check our config transcription; the diffusers ones are the
# primary validation of the grammar-generated inventories.
EXPECTED_TOTALS = {
    "clip_full": 427_616_513,     # openai/clip-vit-large-patch14
    "clip_bigg": 694_659_840,     # SDXL text_encoder_2 (OpenCLIP bigG)
    "gpt2": 124_439_808,          # gpt2 (small), tied head not re-counted
    "minilm": 22_713_216,         # all-MiniLM-L6-v2 (BertModel incl pooler)
    "mistral": 7_241_732_096,     # Mistral-7B-Instruct-v0.1
    "unet_sd15": 859_520_964,     # SD1.5 UNet2DConditionModel
    "unet_sdxl": 2_567_463_684,   # SDXL-base UNet2DConditionModel
    "vae_sd15": 83_653_863,       # AutoencoderKL (full: enc+dec+quant)
    "vae_sdxl": 83_653_863,       # same architecture, SDXL-era naming
}


# ---------------------------------------------------------------- meta dumps

def _meta_state_shapes(model) -> dict:
    return {k: list(v.shape) for k, v in model.state_dict().items()}


def manifest_clip_full() -> tuple:
    import torch
    from transformers import CLIPConfig, CLIPModel

    cfg = CLIPConfig(
        projection_dim=768,
        text_config=dict(
            vocab_size=49408, hidden_size=768, intermediate_size=3072,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=77, projection_dim=768),
        vision_config=dict(
            hidden_size=1024, intermediate_size=4096,
            num_hidden_layers=24, num_attention_heads=16,
            image_size=224, patch_size=14, projection_dim=768),
    )
    with torch.device("meta"):
        shapes = _meta_state_shapes(CLIPModel(cfg))
    # persisted by the save-era transformers (<4.31); in the real file
    optional = {
        "text_model.embeddings.position_ids": [1, 77],
        "vision_model.embeddings.position_ids": [1, 257],
    }
    return shapes, optional


def manifest_clip_bigg() -> tuple:
    import torch
    from transformers import CLIPTextConfig, CLIPTextModelWithProjection

    cfg = CLIPTextConfig(
        vocab_size=49408, hidden_size=1280, intermediate_size=5120,
        num_hidden_layers=32, num_attention_heads=20,
        max_position_embeddings=77, projection_dim=1280,
        hidden_act="gelu",
    )
    with torch.device("meta"):
        shapes = _meta_state_shapes(CLIPTextModelWithProjection(cfg))
    optional = {"text_model.embeddings.position_ids": [1, 77]}
    return shapes, optional


def manifest_gpt2() -> tuple:
    import torch
    from transformers import GPT2Config, GPT2Model

    with torch.device("meta"):
        shapes = _meta_state_shapes(GPT2Model(GPT2Config()))
    # the published file carries the (re-derivable) causal-mask buffers
    optional = {}
    for i in range(12):
        optional[f"h.{i}.attn.bias"] = [1, 1, 1024, 1024]
        optional[f"h.{i}.attn.masked_bias"] = []
    return shapes, optional


def manifest_minilm() -> tuple:
    import torch
    from transformers import BertConfig, BertModel

    cfg = BertConfig(
        vocab_size=30522, hidden_size=384, num_hidden_layers=6,
        num_attention_heads=12, intermediate_size=1536,
        max_position_embeddings=512,
    )
    with torch.device("meta"):
        shapes = _meta_state_shapes(BertModel(cfg))
    optional = {"embeddings.position_ids": [1, 512]}
    return shapes, optional


def manifest_mistral() -> tuple:
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    cfg = MistralConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32,
        num_key_value_heads=8, head_dim=128, max_position_embeddings=32768,
        sliding_window=4096, tie_word_embeddings=False,
    )
    with torch.device("meta"):
        shapes = _meta_state_shapes(MistralForCausalLM(cfg))
    # some save eras persist per-layer RoPE tables
    optional = {f"model.layers.{i}.self_attn.rotary_emb.inv_freq": [64]
                for i in range(32)}
    return shapes, optional


# ------------------------------------------------------- diffusers grammars

def _resblock(out, src, cin, cout, temb=None):
    out[f"{src}.norm1.weight"] = [cin]
    out[f"{src}.norm1.bias"] = [cin]
    out[f"{src}.conv1.weight"] = [cout, cin, 3, 3]
    out[f"{src}.conv1.bias"] = [cout]
    if temb:
        out[f"{src}.time_emb_proj.weight"] = [cout, temb]
        out[f"{src}.time_emb_proj.bias"] = [cout]
    out[f"{src}.norm2.weight"] = [cout]
    out[f"{src}.norm2.bias"] = [cout]
    out[f"{src}.conv2.weight"] = [cout, cout, 3, 3]
    out[f"{src}.conv2.bias"] = [cout]
    if cin != cout:
        out[f"{src}.conv_shortcut.weight"] = [cout, cin, 1, 1]
        out[f"{src}.conv_shortcut.bias"] = [cout]


def _spatial_transformer(out, src, ch, depth, ctx, linear_proj):
    out[f"{src}.norm.weight"] = [ch]
    out[f"{src}.norm.bias"] = [ch]
    proj_shape = [ch, ch] if linear_proj else [ch, ch, 1, 1]
    out[f"{src}.proj_in.weight"] = proj_shape
    out[f"{src}.proj_in.bias"] = [ch]
    for k in range(depth):
        t = f"{src}.transformer_blocks.{k}"
        for n in ("norm1", "norm2", "norm3"):
            out[f"{t}.{n}.weight"] = [ch]
            out[f"{t}.{n}.bias"] = [ch]
        for attn, kv in (("attn1", ch), ("attn2", ctx)):
            out[f"{t}.{attn}.to_q.weight"] = [ch, ch]
            out[f"{t}.{attn}.to_k.weight"] = [ch, kv]
            out[f"{t}.{attn}.to_v.weight"] = [ch, kv]
            out[f"{t}.{attn}.to_out.0.weight"] = [ch, ch]
            out[f"{t}.{attn}.to_out.0.bias"] = [ch]
        out[f"{t}.ff.net.0.proj.weight"] = [8 * ch, ch]  # GEGLU
        out[f"{t}.ff.net.0.proj.bias"] = [8 * ch]
        out[f"{t}.ff.net.2.weight"] = [ch, 4 * ch]
        out[f"{t}.ff.net.2.bias"] = [ch]
    out[f"{src}.proj_out.weight"] = proj_shape
    out[f"{src}.proj_out.bias"] = [ch]


def _unet_manifest(chs, blocks, attn_levels, depths, ctx, temb, add_dim,
                   linear_proj) -> dict:
    out: dict = {}
    base = chs[0]
    levels = len(chs)
    out["conv_in.weight"] = [base, 4, 3, 3]
    out["conv_in.bias"] = [base]
    out["time_embedding.linear_1.weight"] = [temb, base]
    out["time_embedding.linear_1.bias"] = [temb]
    out["time_embedding.linear_2.weight"] = [temb, temb]
    out["time_embedding.linear_2.bias"] = [temb]
    if add_dim:
        out["add_embedding.linear_1.weight"] = [temb, add_dim]
        out["add_embedding.linear_1.bias"] = [temb]
        out["add_embedding.linear_2.weight"] = [temb, temb]
        out["add_embedding.linear_2.bias"] = [temb]

    skips = [base]
    prev = base
    for lvl, ch in enumerate(chs):
        for b in range(blocks):
            _resblock(out, f"down_blocks.{lvl}.resnets.{b}", prev, ch, temb)
            if attn_levels[lvl] and depths[lvl]:
                _spatial_transformer(
                    out, f"down_blocks.{lvl}.attentions.{b}", ch,
                    depths[lvl], ctx, linear_proj)
            prev = ch
            skips.append(ch)
        if lvl != levels - 1:
            out[f"down_blocks.{lvl}.downsamplers.0.conv.weight"] = \
                [ch, ch, 3, 3]
            out[f"down_blocks.{lvl}.downsamplers.0.conv.bias"] = [ch]
            skips.append(ch)

    mid = chs[-1]
    mid_depth = max([d for lvl, d in enumerate(depths)
                     if attn_levels[lvl]] or [1])
    _resblock(out, "mid_block.resnets.0", mid, mid, temb)
    _spatial_transformer(out, "mid_block.attentions.0", mid, mid_depth,
                         ctx, linear_proj)
    _resblock(out, "mid_block.resnets.1", mid, mid, temb)

    for i in range(levels):
        lvl = levels - 1 - i
        ch = chs[lvl]
        for b in range(blocks + 1):
            skip = skips.pop()
            _resblock(out, f"up_blocks.{i}.resnets.{b}", prev + skip, ch,
                      temb)
            if attn_levels[lvl] and depths[lvl]:
                _spatial_transformer(
                    out, f"up_blocks.{i}.attentions.{b}", ch, depths[lvl],
                    ctx, linear_proj)
            prev = ch
        if lvl != 0:
            out[f"up_blocks.{i}.upsamplers.0.conv.weight"] = [ch, ch, 3, 3]
            out[f"up_blocks.{i}.upsamplers.0.conv.bias"] = [ch]

    out["conv_norm_out.weight"] = [base]
    out["conv_norm_out.bias"] = [base]
    out["conv_out.weight"] = [4, base, 3, 3]
    out["conv_out.bias"] = [4]
    return out


def manifest_unet_sd15() -> tuple:
    return _unet_manifest(
        chs=(320, 640, 1280, 1280), blocks=2,
        attn_levels=(True, True, True, False), depths=(1, 1, 1, 1),
        ctx=768, temb=1280, add_dim=0, linear_proj=False), {}


def manifest_unet_sdxl() -> tuple:
    return _unet_manifest(
        chs=(320, 640, 1280), blocks=2,
        attn_levels=(False, True, True), depths=(0, 2, 10),
        ctx=2048, temb=1280, add_dim=2816, linear_proj=True), {}


def _vae_attn(out, src, ch, era_new: bool):
    if era_new:  # SDXL-era diffusers Attention naming
        out[f"{src}.group_norm.weight"] = [ch]
        out[f"{src}.group_norm.bias"] = [ch]
        names = ("to_q", "to_k", "to_v", "to_out.0")
    else:  # SD1.5-era AttentionBlock naming
        out[f"{src}.group_norm.weight"] = [ch]
        out[f"{src}.group_norm.bias"] = [ch]
        names = ("query", "key", "value", "proj_attn")
    for n in names:
        out[f"{src}.{n}.weight"] = [ch, ch]
        out[f"{src}.{n}.bias"] = [ch]


def _vae_resblock(out, src, cin, cout):
    _resblock(out, src, cin, cout, temb=None)


def manifest_vae(era_new: bool) -> tuple:
    chs = (128, 256, 512, 512)
    blocks = 2
    levels = len(chs)
    latent = 4
    out: dict = {}

    # encoder
    out["encoder.conv_in.weight"] = [chs[0], 3, 3, 3]
    out["encoder.conv_in.bias"] = [chs[0]]
    prev = chs[0]
    for lvl, ch in enumerate(chs):
        for b in range(blocks):
            _vae_resblock(out, f"encoder.down_blocks.{lvl}.resnets.{b}",
                          prev, ch)
            prev = ch
        if lvl != levels - 1:
            out[f"encoder.down_blocks.{lvl}.downsamplers.0.conv.weight"] \
                = [ch, ch, 3, 3]
            out[f"encoder.down_blocks.{lvl}.downsamplers.0.conv.bias"] = [ch]
    mid = chs[-1]
    _vae_resblock(out, "encoder.mid_block.resnets.0", mid, mid)
    _vae_attn(out, "encoder.mid_block.attentions.0", mid, era_new)
    _vae_resblock(out, "encoder.mid_block.resnets.1", mid, mid)
    out["encoder.conv_norm_out.weight"] = [mid]
    out["encoder.conv_norm_out.bias"] = [mid]
    out["encoder.conv_out.weight"] = [2 * latent, mid, 3, 3]
    out["encoder.conv_out.bias"] = [2 * latent]
    out["quant_conv.weight"] = [2 * latent, 2 * latent, 1, 1]
    out["quant_conv.bias"] = [2 * latent]
    out["post_quant_conv.weight"] = [latent, latent, 1, 1]
    out["post_quant_conv.bias"] = [latent]

    # decoder
    out["decoder.conv_in.weight"] = [mid, latent, 3, 3]
    out["decoder.conv_in.bias"] = [mid]
    _vae_resblock(out, "decoder.mid_block.resnets.0", mid, mid)
    _vae_attn(out, "decoder.mid_block.attentions.0", mid, era_new)
    _vae_resblock(out, "decoder.mid_block.resnets.1", mid, mid)
    prev = mid
    for i in range(levels):
        lvl = levels - 1 - i
        ch = chs[lvl]
        for b in range(blocks + 1):
            _vae_resblock(out, f"decoder.up_blocks.{i}.resnets.{b}",
                          prev, ch)
            prev = ch
        if lvl != 0:
            out[f"decoder.up_blocks.{i}.upsamplers.0.conv.weight"] = \
                [ch, ch, 3, 3]
            out[f"decoder.up_blocks.{i}.upsamplers.0.conv.bias"] = [ch]
    out["decoder.conv_norm_out.weight"] = [chs[0]]
    out["decoder.conv_norm_out.bias"] = [chs[0]]
    out["decoder.conv_out.weight"] = [3, chs[0], 3, 3]
    out["decoder.conv_out.bias"] = [3]
    return out, {}


SOURCES = {
    "clip_full": ("openai/clip-vit-large-patch14", "model.safetensors",
                  manifest_clip_full),
    "clip_bigg": ("stabilityai/stable-diffusion-xl-base-1.0",
                  "text_encoder_2/model.safetensors", manifest_clip_bigg),
    "gpt2": ("gpt2", "model.safetensors", manifest_gpt2),
    "minilm": ("sentence-transformers/all-MiniLM-L6-v2",
               "model.safetensors", manifest_minilm),
    "mistral": ("mistralai/Mistral-7B-Instruct-v0.1",
                "model-0000*-of-00002.safetensors (merged)",
                manifest_mistral),
    "unet_sd15": ("runwayml/stable-diffusion-v1-5",
                  "unet/diffusion_pytorch_model.safetensors",
                  manifest_unet_sd15),
    "unet_sdxl": ("stabilityai/stable-diffusion-xl-base-1.0",
                  "unet/diffusion_pytorch_model.safetensors",
                  manifest_unet_sdxl),
    "vae_sd15": ("runwayml/stable-diffusion-v1-5",
                 "vae/diffusion_pytorch_model.safetensors",
                 lambda: manifest_vae(era_new=False)),
    "vae_sdxl": ("stabilityai/stable-diffusion-xl-base-1.0",
                 "vae/diffusion_pytorch_model.safetensors",
                 lambda: manifest_vae(era_new=True)),
}


def build(name: str) -> dict:
    repo, remote, fn = SOURCES[name]
    tensors, optional = fn()
    total = sum(int(np_prod(s)) for s in tensors.values())
    expected = EXPECTED_TOTALS[name]
    if total != expected:
        sys.exit(f"{name}: generated inventory sums to {total:,} params, "
                 f"published total is {expected:,} — grammar/config wrong")
    return {
        "source": {"repo": repo, "file": remote},
        "params_total": total,
        "tensor_count": len(tensors),
        # keys some artifact eras carry on top of `tensors` (persisted
        # buffers); converters must tolerate-and-ignore them
        "optional": optional,
        "tensors": dict(sorted(tensors.items())),
    }


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="diff against data/manifests instead of writing")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of manifest names")
    args = ap.parse_args()

    names = (args.only.split(",") if args.only else list(SOURCES))
    os.makedirs(OUT_DIR, exist_ok=True)
    drift = []
    for name in names:
        manifest = build(name)
        path = os.path.join(OUT_DIR, f"{name}.json")
        if args.check:
            on_disk = json.load(open(path)) if os.path.exists(path) else None
            if on_disk != manifest:
                drift.append(name)
                print(f"[check] {name}: DRIFT")
            else:
                print(f"[check] {name}: ok "
                      f"({manifest['tensor_count']} tensors, "
                      f"{manifest['params_total']:,} params)")
            continue
        with open(path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=False)
            f.write("\n")
        print(f"[write] {name}: {manifest['tensor_count']} tensors, "
              f"{manifest['params_total']:,} params -> {path}")
    if drift:
        print(f"{len(drift)} manifests drifted: {drift}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
