"""JAX dispatch-discipline lint gate: recompile hazards, tracer leaks,
host-buffer escapes, env-flag registry.

Runs the four ``cassmantle_tpu/analysis`` JAX passes over the package
(rule catalog: ``docs/STATIC_ANALYSIS.md``):

- ``recompile-hazard`` — jit sites that defeat the compile cache:
  jit built inside loops, unhashable/per-call static arguments,
  mutable-attribute capture at trace time, unbucketed shapes fed to a
  jit from a loop;
- ``tracer-leak`` — traced values escaping a jit region (stores to
  ``self.*``/globals/outer containers) and host ``if``/``while`` on
  traced values (TracerBoolConversion, caught statically);
- ``buffer-escape`` — the PR 6 aliasing class: a mutable numpy host
  mirror mutated in place AND passed uncopied into async dispatch /
  device placement;
- ``env-flag`` — every ``CASSMANTLE_*`` read has a docs/DEPLOY.md §6
  lever-table row, and vice versa.

The static half pairs with the runtime compile-count sentinel
(``utils/jit_sentinel.py``), exactly how ``check_concurrency`` pairs
with ``utils/locks.OrderedLock``.

Run standalone: ``python tools/check_jax.py [cassmantle_tpu/]
[--json]`` (exit 1 on violations). Gated as a fast-tier test in
``tests/test_check_jax.py``.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.core import (  # noqa: E402
    PACKAGE,
    iter_modules,
    main_for,
    run_passes,
)


def jax_passes(root: pathlib.Path = PACKAGE):
    """The pass set this tool (and lint_all) runs, fresh instances —
    EnvFlagPass accumulates seen flags across a walk, so instances must
    not be shared between walks. The registry's stale-row direction
    ("documented but never read") is only meaningful when the walk
    covers the whole package, so scoped runs skip it."""
    from cassmantle_tpu.analysis.bufferescape import BufferEscapePass
    from cassmantle_tpu.analysis.envflags import EnvFlagPass
    from cassmantle_tpu.analysis.recompile import RecompilePass
    from cassmantle_tpu.analysis.tracerleak import TracerLeakPass

    try:
        covers_package = PACKAGE.resolve().is_relative_to(
            pathlib.Path(root).resolve())
    except AttributeError:  # pragma: no cover - py<3.9
        covers_package = True
    return [RecompilePass(), TracerLeakPass(), BufferEscapePass(),
            EnvFlagPass(check_orphans=covers_package)]


def check(root: pathlib.Path = PACKAGE) -> List[str]:
    """All violations as human-readable strings; empty = clean."""
    return [str(f) for f in
            run_passes(iter_modules(root), jax_passes(root))]


def main(argv=None) -> int:
    return main_for(jax_passes, argv, default_root=PACKAGE,
                    prog="check_jax")


if __name__ == "__main__":
    raise SystemExit(main())
