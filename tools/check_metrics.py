"""Static metric-name lint: convention + docs-catalog coverage.

Thin CLI shim: the pass itself lives on the shared lint framework in
``cassmantle_tpu/analysis/metric_names.py`` (rules unchanged — dotted
lowercase ``subsystem.metric`` names, histogram ``_s``/``_size``
suffixes, every literal name present in the ``docs/OBSERVABILITY.md``
catalog; f-string holes are wildcards). Drift fails tier-1
(``tests/test_check_metrics.py``).

Run standalone: ``python tools/check_metrics.py [--json]`` (exit 1 on
violations).
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.metric_names import (  # noqa: E402,F401
    CATALOG_DOC,
    PACKAGE,
    _name_matches,
    _SEGMENT,
    check,
    extract_sites,
    load_catalog,
    load_catalog_types,
    main,
)

if __name__ == "__main__":
    raise SystemExit(main())
