"""Exception-flow & resource-lifecycle lint gate: swallowed errors,
future discipline, leaked tasks/threads/resources.

Runs the three ``cassmantle_tpu/analysis`` lifecycle passes over the
package (rule catalog: ``docs/STATIC_ANALYSIS.md``):

- ``swallowed-error`` / ``overbroad-except`` — broad ``except`` bodies
  in serving/engine/fabric/server/native code that neither re-raise,
  count a metric, flight-record, classify through the recovery plane,
  nor carry the error to a waiter; plus the PR 8 cancel-swallow shape
  (a loop handler that makes its task uncancellable, gh-86296) and
  ``BaseException``/bare catches outside shutdown paths;
- ``future-discipline`` — futures that can escape unresolved:
  error-path stranding, unguarded ``set_result``/``set_exception`` in
  racy contexts, and classes that enqueue futures their ``stop()``
  never fails (the PR 6 stranding shape);
- ``task-leak`` / ``thread-leak`` / ``resource-leak`` — fire-and-forget
  ``create_task``/``ensure_future``, threads ``stop()`` never joins,
  sockets/files/executors opened without close-on-stop.

The static half pairs with the runtime leak sentinel
(``utils/leak_sentinel.py``, armed per-test by conftest), exactly how
``check_concurrency`` pairs with ``utils/locks.OrderedLock`` and
``check_jax`` with the jit sentinel.

Run standalone: ``python tools/check_lifecycle.py [cassmantle_tpu/]
[--json]`` (exit 1 on violations). Gated as a fast-tier test in
``tests/test_check_lifecycle.py``.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.core import (  # noqa: E402
    PACKAGE,
    iter_modules,
    main_for,
    run_passes,
)


def lifecycle_passes(root: pathlib.Path = PACKAGE):
    """The pass set this tool (and lint_all) runs, fresh instances per
    walk for symmetry with jax_passes (these passes are stateless
    today, but the fresh-instance rule is the framework contract)."""
    from cassmantle_tpu.analysis.exceptionflow import ExceptionFlowPass
    from cassmantle_tpu.analysis.futuredisc import FutureDisciplinePass
    from cassmantle_tpu.analysis.lifecycle import LifecyclePass

    del root  # no whole-package-only directions in this family
    return [ExceptionFlowPass.for_repo(), FutureDisciplinePass.for_repo(),
            LifecyclePass.for_repo()]


def check(root: pathlib.Path = PACKAGE) -> List[str]:
    """All violations as human-readable strings; empty = clean."""
    return [str(f) for f in
            run_passes(iter_modules(root), lifecycle_passes(root))]


def main(argv=None) -> int:
    return main_for(lifecycle_passes, argv, default_root=PACKAGE,
                    prog="check_lifecycle")


if __name__ == "__main__":
    raise SystemExit(main())
