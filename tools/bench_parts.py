"""Stage-level timing of the SD1.5 serving path on the real chip.

Times each piece of the north-star pipeline separately (CLIP encode, one
2B-batch UNet denoise step, the 50-step DDIM scan, VAE decode) so perf
work targets the real hot spot. Also times UNet variants (bf16 params,
flash vs XLA attention) to size individual levers.

Usage: python tools/bench_parts.py [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(name, fn, *args, reps=3, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:36s} {dt * 1e3:9.1f} ms")
    return dt


def main() -> None:
    from cassmantle_tpu.config import FrameworkConfig
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    cfg = FrameworkConfig()
    pipe = Text2ImagePipeline(cfg, weights_dir=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "weights"))

    ids = jnp.asarray(pipe._tokenize(["a lighthouse over a stormy sea"] * batch))
    uncond = jnp.asarray(pipe._tokenize([""] * batch))
    rng = jax.random.PRNGKey(0)

    # full pipeline
    full = timeit(
        "full pipeline (tokenize..uint8)",
        lambda: pipe._sample(pipe._params, ids, uncond, rng),
    )

    # CLIP encode
    clip_fn = jax.jit(
        lambda p, i: pipe.clip.apply(p, i)["hidden"]
    )
    timeit("clip encode (B)", clip_fn, pipe.clip_params, ids)

    # single UNet step at CFG batch (2B)
    lat_hw = cfg.sampler.image_size // pipe.vae_scale
    lat2 = jnp.zeros((2 * batch, lat_hw, lat_hw, 4), jnp.float32)
    t2 = jnp.zeros((2 * batch,), jnp.int32)
    ctx2 = jnp.zeros((2 * batch, pipe.pad_len,
                      cfg.models.unet.context_dim), jnp.float32)
    unet_fn = jax.jit(lambda p, l, t, c: pipe.unet.apply(p, l, t, c))
    step = timeit("unet step (2B batch)", unet_fn, pipe.unet_params,
                  lat2, t2, ctx2)
    print(f"{'-> 50 steps would be':36s} {step * 50 * 1e3:9.1f} ms")

    # fp32-storage variant (the pipeline default is bf16; this sizes the
    # bf16-weights lever by timing the OLD layout)
    unet_fp32 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32)
        if a.dtype == jnp.bfloat16 else a,
        pipe.unet_params,
    )
    timeit("unet step (fp32 params)", unet_fn, unet_fp32, lat2, t2, ctx2)

    # XLA-attention variant
    from cassmantle_tpu.ops.attention import xla_only

    with xla_only():
        unet_xla = jax.jit(
            lambda p, l, t, c: pipe.unet.apply(p, l, t, c))
        timeit("unet step (XLA attention)", unet_xla, pipe.unet_params,
               lat2, t2, ctx2)

    # VAE decode
    latB = jnp.zeros((batch, lat_hw, lat_hw, 4), jnp.float32)
    vae_fn = jax.jit(lambda p, l: pipe.vae.apply(p, l))
    timeit("vae decode (B)", vae_fn, pipe.vae_params, latB)

    print(f"batch={batch}: full={full * 1e3:.0f} ms "
          f"-> {batch / full:.2f} images/sec")


if __name__ == "__main__":
    main()
