"""Offline weight quantization: produce an int8 LM checkpoint.

One-shot (like the reference's download_model.py bootstrap): build the
prompt-LM with ``lm_int8`` (loading/converting whatever fp checkpoint is
in --weights, or deterministic random init without one), then write
``<family>.int8.safetensors`` next to it. Every later boot with
``lm_int8`` loads int8 straight from disk — no fp pass, half the read
bytes, and the quantization cost is paid once instead of per process.

Usage: python tools/quantize_weights.py --weights weights [--lm mistral]
       (or: python -m cassmantle_tpu quantize-weights ...)
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--weights", required=True,
                        help="checkpoint directory (output lands here)")
    parser.add_argument("--lm", default="gpt2",
                        choices=("gpt2", "mistral"))
    parser.add_argument("--platform", default="cpu",
                        choices=("auto", "cpu"),
                        help="default 'cpu': quantization is host-only, "
                             "so don't initialize the accelerator or "
                             "round-trip multi-GB trees through it")
    args = parser.parse_args()

    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)

    from cassmantle_tpu.config import FrameworkConfig, MistralConfig
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    cfg = FrameworkConfig()
    models = dataclasses.replace(cfg.models, lm_int8=True)
    if args.lm == "mistral":
        models = dataclasses.replace(models, mistral=MistralConfig())
    cfg = cfg.replace(models=models)

    gen = PromptGenerator(cfg, weights_dir=args.weights)
    path = gen.save_quantized()
    print(f"quantized checkpoint written: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
