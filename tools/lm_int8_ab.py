"""On-hardware A/B for weights-only int8 LM decode (VERDICT round-1 #6).

The int8 story ("halves decode step time, fits Mistral-7B on a 16 GB
chip with headroom") must be a measurement, not an assertion. This tool
builds the SAME prompt-LM family twice — fp (param_dtype storage) and
weights-only int8 (ops/quant.py) — runs identical fixed-length greedy
decodes through the serving PromptGenerator, and reports tokens/sec,
param-tree bytes, and device memory stats side by side as one JSON
line. Works for GPT-2 (default) and Mistral (--family mistral; at
Mistral-7B dims the fp arm may not fit a 16 GB chip — that OOM is
itself the result the int8 path exists to fix, reported as such).

Usage: python tools/lm_int8_ab.py [--family gpt2|mistral]
           [--tokens 64] [--reps 3] [--weights weights]
           [--platform cpu] [--tiny] [--out LM_INT8_AB.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

SEED_TEXT = "The lighthouse keeper counted the storms of"


def _build_cfg(family: str, tiny: bool, int8: bool):
    from cassmantle_tpu.config import (
        FrameworkConfig,
        MistralConfig,
        test_config,
    )

    cfg = test_config() if tiny else FrameworkConfig()
    models = cfg.models
    if family == "mistral":
        models = dataclasses.replace(
            models,
            mistral=MistralConfig.tiny() if tiny else MistralConfig())
    models = dataclasses.replace(models, lm_int8=int8)
    # decode length is fixed by the explicit max_new_tokens passed to
    # generate() (greedy_decode runs a fixed-length lax.scan), so
    # tokens/sec is comparable across arms without touching the config
    return cfg.replace(models=models)


def _device_mem() -> dict:
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    return {k: stats[k] for k in
            ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
            if k in stats}


def _measure_arm(cfg, weights_dir, tokens: int, reps: int) -> dict:
    import jax

    from cassmantle_tpu.ops.quant import QTensor, tree_nbytes
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    gen = PromptGenerator(cfg, weights_dir=weights_dir)
    gen.generate(SEED_TEXT, max_new_tokens=tokens)   # warmup / compile
    t0 = time.perf_counter()
    for _ in range(reps):
        text = gen.generate(SEED_TEXT, max_new_tokens=tokens)
    dt = (time.perf_counter() - t0) / reps
    n_q = sum(1 for leaf in jax.tree_util.tree_leaves(
        gen.params, is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(leaf, QTensor))
    return {
        "tokens_per_sec": round(tokens / dt, 1),
        "decode_s": round(dt, 4),
        "param_bytes": tree_nbytes(gen.params),
        # 0 in the int8 arm means nothing met the size predicate (tiny
        # smoke dims) — the A/B is then a no-op, not a measurement
        "quantized_leaves": n_q,
        "memory": _device_mem(),
        "real_weights": gen.loaded_real_weights,
        "sample_chars": len(text),
    }


_DEFAULT_WEIGHTS = os.path.join(REPO_ROOT, "weights")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--family", default="gpt2",
                    choices=["gpt2", "mistral"])
    ap.add_argument("--tokens", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--weights", default=_DEFAULT_WEIGHTS)
    ap.add_argument("--platform", default="auto", choices=["auto", "cpu"])
    ap.add_argument("--tiny", action="store_true",
                    help="tiny dims (plumbing smoke, not a measurement)")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON to this path")
    ap.add_argument("--arm", default=None, choices=["fp", "int8"],
                    help=argparse.SUPPRESS)  # internal: one-arm child
    args = ap.parse_args()

    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)

    if os.path.isdir(args.weights):
        weights_dir = args.weights
    elif args.weights != _DEFAULT_WEIGHTS:
        # an explicitly named directory that doesn't exist must not be
        # silently demoted to a random-init run
        sys.exit(f"--weights {args.weights!r} is not a directory")
    else:
        weights_dir = None

    if args.arm:  # child mode: measure ONE arm, print its JSON
        cfg = _build_cfg(args.family, args.tiny, args.arm == "int8")
        print(json.dumps(_measure_arm(cfg, weights_dir, args.tokens,
                                      args.reps)))
        return

    report = {
        "metric": f"lm_int8_decode_ab_{args.family}",
        "family": args.family,
        "tokens": args.tokens,
        "tiny": args.tiny,
    }
    # each arm runs in its OWN subprocess: XLA's peak_bytes_in_use is
    # process-cumulative, so in-process sequencing would charge the fp
    # arm's footprint to the int8 arm's memory report
    import subprocess

    for arm in ("fp", "int8"):
        child = [sys.executable, os.path.abspath(__file__),
                 "--arm", arm, "--family", args.family,
                 "--tokens", str(args.tokens), "--reps", str(args.reps),
                 "--weights", args.weights, "--platform", args.platform]
        if args.tiny:
            child.append("--tiny")
        try:
            proc = subprocess.run(child, capture_output=True, text=True,
                                  timeout=3600)
            if proc.returncode != 0:   # OOM on the fp arm IS a result
                report[arm] = {"error": proc.stderr[-800:]}
            else:
                report[arm] = json.loads(proc.stdout.splitlines()[-1])
        except Exception as exc:
            report[arm] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"[lm_int8_ab] {arm}: {report[arm]}", file=sys.stderr)

    fp, q8 = report.get("fp", {}), report.get("int8", {})
    # a real-weights A/B needs BOTH arms loaded from checkpoints
    report["real_weights"] = bool(
        fp.get("real_weights") and q8.get("real_weights"))
    if "tokens_per_sec" in fp and "tokens_per_sec" in q8:
        report["speedup"] = round(
            q8["tokens_per_sec"] / fp["tokens_per_sec"], 3)
    if "param_bytes" in fp and "param_bytes" in q8 and fp["param_bytes"]:
        report["param_shrink"] = round(
            q8["param_bytes"] / fp["param_bytes"], 3)

    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
