"""Concurrency lint gate: lock discipline, blocking-in-async, host-sync.

Runs the three ``cassmantle_tpu/analysis`` concurrency passes over the
package (rule catalog: ``docs/STATIC_ANALYSIS.md``):

- ``lock-order-cycle`` / ``lock-across-await`` / ``lock-blocking-call``
  — the static defense against the PR 1 dispatch-deadlock class;
- ``async-blocking-call`` — blocking calls inside ``async def`` bodies
  in the server/serving/engine event-loop layers;
- ``host-sync`` — device→host syncs inside jit regions or inside loops
  of serving/ops hot paths.

Run standalone: ``python tools/check_concurrency.py [cassmantle_tpu/]
[--json]`` (exit 1 on violations). Gated as a fast-tier test in
``tests/test_check_concurrency.py``, so a reintroduced deadlock shape
fails tier-1 before it ships.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.core import (  # noqa: E402
    PACKAGE,
    iter_modules,
    main_for,
    run_passes,
)
from cassmantle_tpu.analysis.lockorder import default_passes  # noqa: E402


def check(root: pathlib.Path = PACKAGE) -> List[str]:
    """All violations as human-readable strings; empty = clean."""
    return [str(f) for f in
            run_passes(iter_modules(root), default_passes())]


def main(argv=None) -> int:
    return main_for(default_passes(), argv, default_root=PACKAGE,
                    prog="check_concurrency")


if __name__ == "__main__":
    raise SystemExit(main())
