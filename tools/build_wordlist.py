"""Build data/wordlist.txt by mining English prose already on the host.

The reference vendors a 49,569-entry hunspell dictionary for client-side
spellcheck (reference data/en_US.dic, loaded at static/script.js:4-10).
This build generates its OWN lexicon — nothing is copied from the
reference tree — by mining the English text that ships with the system:
package documentation, README/LICENSE prose, and source docstrings
(/usr/share/doc + site-packages). That corpus is gigabytes of edited
English; document-frequency filtering keeps words that appear across
many independent files and drops one-off identifiers.

Filters (deterministic):
- lowercase alphabetic tokens, 2-15 chars, containing a vowel, no 5+
  consonant run, no letter tripled (kills ascii-art junk);
- document frequency >= --min-df (default 3); 2-letter tokens only from
  an explicit allowlist (prose initialisms dominate otherwise);
- a curated literary seed list covers story-prose vocabulary that
  technical corpora under-represent;
- words seen mostly Capitalized (> 3x more often than lowercase) are
  treated as proper nouns and dropped;
- the existing curated game list (data/wordlist.txt) is merged in, so
  regeneration never loses hand-picked vocabulary.

Output order is DOCUMENT FREQUENCY, most common first (ties, curated
seeds, and merged hand-picked words alphabetical at their frequency
tier): both spellcheckers (static/spell.js, utils/spell.py) rank
did-you-mean suggestions by list position, so a one-edit typo surfaces
the intended COMMON word ahead of an obscure one — the role hunspell's
replacement tables play in the reference's typo.js.

Usage:  python tools/build_wordlist.py [--out data/wordlist.txt]
            [--min-df 3] [--no-merge-existing]
"""

from __future__ import annotations

import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORD_RE = re.compile(r"[A-Za-z]{2,15}")
VOWELS = set("aeiouy")
CONS_RUN = re.compile(r"[bcdfghjklmnpqrstvwxz]{5,}")
REPEAT_RUN = re.compile(r"(.)\1\1")  # no English word triples a letter

# two-letter tokens in prose are mostly initialisms; only real words pass
TWO_LETTER = {
    "ah", "am", "an", "as", "at", "ax", "be", "by", "do", "eh", "ex",
    "go", "he", "hi", "id", "if", "in", "is", "it", "lo", "ma", "me",
    "my", "no", "of", "oh", "on", "or", "ow", "ox", "pa", "pi", "re",
    "so", "to", "up", "us", "we", "ye", "yo",
}

# Common literary/descriptive vocabulary that technical corpora
# under-represent but story prose (the game's actual content) uses
# constantly. Seeds the lexicon regardless of mining thresholds.
CURATED_LITERARY = """
amber ancient ash aurora autumn beacon blaze bloom blossom breeze brittle
bronze burnished canyon caravan cavern charcoal cinder cliff cobalt comet
coral crimson crystal dawn dew drift dusk ember emerald feather fern
flicker fog frost gale gleam glimmer glisten glow golden gossamer granite
grove halo harbor haze hearth heather hollow horizon hush indigo ivory
jade lagoon lantern lavender lighthouse lilac lullaby marble meadow mist
misty moonlit moss mossy murmur nebula nectar obsidian olive onyx opal
orchard pale pearl pebble petal pine plume prairie quartz quiver raven
reef ripple russet rust rustic saffron sapphire scarlet shatter shattered
shimmer shiver silken silver slate smolder snowy solace sorrow spark
sparkle spire starlit storm stormy stream summit sunset thistle thorn
thunder tide timber topaz tranquil twilight velvet verdant violet
wander wandering whisper wildflower willow wisp wistful zephyr
bramble furl unfurl eddy knoll dell glen fen heath crag vale copse
thicket bracken gorse sedge tarn scree brook rivulet hillock
outcrop updraft gloaming murk dapple dappled
""".split()

# Doc-corpus boilerplate that dominates raw document frequency (the
# mining roots are /usr/share/doc + site-packages, so license/README
# vocabulary tops every df count) but is near-useless as a
# spell-suggestion winner in a STORY game: both spellcheckers rank
# suggestions by list position, so "use" beating "fuse"/"muse" or
# "org" beating "fog" on a tie resolves typos toward tech vocabulary
# (VERDICT r5 weak #4). Membership is untouched — these words stay
# checkable — but they rank BELOW story vocabulary (demoted to the
# tail tier at write-out). English function words ("the", "and") are
# NOT here: they head the list legitimately and never collide with
# content-word typos of length >= 3.
DOC_STOPWORDS = frozenset("""
    org use software documentation copyright license licensed licenses
    version versions code source notice conditions warranty copies
    copy permission permissions http https www html url urls api apis
    config configuration module modules package packages library
    libraries install installed installation file files directory
    docs documented implied merchantability noninfringement sublicense
    redistribute redistribution disclaimer liability damages
    contributors derivative kind express limited obtained furnished
    python foundation stichting mathematisch centrum amsterdam
""".split())

TEXT_EXTS = (".py", ".md", ".rst", ".txt")
SKIP_DIRS = {"__pycache__", "nvidia", "node_modules", ".git"}
# per-file read cap: license/notice blobs repeat after this anyway, and
# it bounds the pass over multi-MB generated files
READ_CAP = 120_000

DEFAULT_ROOTS = (
    "/usr/share/doc",
    "/opt/venv/lib/python3.12/site-packages",
    "/usr/lib/python3",
    "/usr/lib/python3.12",
)

# default output resolves against the repo, not the cwd: the server
# reads the lexicon from the package-relative data/ directory
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(REPO_ROOT, "data", "wordlist.txt")


def iter_text_files(roots):
    for root in roots:
        if not os.path.isdir(root):
            continue
        for dirpath, dirnames, names in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d not in SKIP_DIRS)
            for f in sorted(names):
                if f.endswith(TEXT_EXTS) or "." not in f:
                    yield os.path.join(dirpath, f)


def mine(roots, progress_every: int = 10_000):
    """-> (document frequency, capitalized df, PROSE document frequency).

    ``prose_df`` counts only non-.py files (docs, READMEs, licenses):
    the inclusion filter uses the full corpus for coverage, but the
    RANKING signal must not let code identifiers ('def', 'args',
    'lset') outrank story-English — suggest() sorts by list position."""
    df: collections.Counter = collections.Counter()
    caps: collections.Counter = collections.Counter()
    prose_df: collections.Counter = collections.Counter()
    n = 0
    for path in iter_text_files(roots):
        try:
            text = open(path, "rb").read(READ_CAP).decode("utf-8", "ignore")
        except OSError:
            continue
        n += 1
        if progress_every and n % progress_every == 0:
            print(f"[build_wordlist] ... {n} files", file=sys.stderr)
        is_prose = not path.endswith(".py")
        lower, upper = set(), set()
        for m in WORD_RE.finditer(text):
            w = m.group(0)
            if w.islower():
                lower.add(w)
            elif w[0].isupper() and w[1:].islower():
                upper.add(w.lower())
        for w in lower:
            df[w] += 1
            if is_prose:
                prose_df[w] += 1
        for w in upper:
            caps[w] += 1
    print(f"[build_wordlist] scanned {n} files", file=sys.stderr)
    return df, caps, prose_df


def _shape_ok(w: str) -> bool:
    if len(w) < 2 or len(w) > 17:
        return False
    if len(w) == 2 and w not in TWO_LETTER:
        return False
    if not (set(w) & VOWELS):
        return False
    return not (CONS_RUN.search(w) or REPEAT_RUN.search(w))


def select(df, caps, min_df: int, prose_df=None):
    """Inclusion: full-corpus df >= min_df, OR prose df >= 2 — a word
    seen in two independent NON-code documents (READMEs, docs,
    licenses) is edited English even when the whole-corpus count misses
    the bar; code-file sightings are much weaker per-occurrence
    evidence (identifiers), so they keep the higher threshold."""
    prose_df = prose_df or {}
    out = []
    for w, c in df.items():
        if c < min_df and prose_df.get(w, 0) < 2:
            continue
        if not _shape_ok(w):
            continue
        # proper nouns: predominantly Capitalized in the corpus
        if caps.get(w, 0) > 3 * c:
            continue
        out.append(w)
    out.extend(CURATED_LITERARY)
    return out


def _affix_forms(w: str):
    """Regular English inflections/derivations of ``w``: plural,
    verbal -ed/-ing (e-drop, y->ie, consonant doubling — shared with
    the POS classifier's morphology), comparative/superlative, -ly,
    and un-/re- prefixes."""
    from cassmantle_tpu.engine.pos import _inflections

    forms = set(_inflections(w))
    if w.endswith(("s", "x", "z", "ch", "sh")):
        forms.add(w + "es")
    elif w.endswith("y") and len(w) > 2 and w[-2] not in "aeiou":
        forms.update((w[:-1] + "ies", w[:-1] + "ily",
                      w[:-1] + "ier", w[:-1] + "iest"))
    else:
        forms.add(w + "s")
    if w.endswith("e"):
        forms.update((w + "r", w + "st", w[:-1] + "y"))
    else:
        forms.update((w + "er", w + "est"))
    forms.update((w + "ly", "un" + w, "re" + w))
    return forms


def expand_inflections(accepted, df):
    """Affix expansion at build time, gated by corpus EVIDENCE: a
    regular inflection of an accepted word joins the lexicon when the
    corpus saw it at all (df >= 1), even under the min-df bar. This is
    the role hunspell's affix flags play in the reference's 49,569-entry
    en_US.dic (data/en_US.dic affix classes, expanded by typo.js) —
    derived here from morphology + at-least-one sighting instead of
    per-word flag curation, so rare-but-valid forms ("zephyrs",
    "shimmering") don't hold correct guesses hostage."""
    base = set(accepted)
    out = set()
    for w in base:
        for form in _affix_forms(w):
            if form in base or form in out:
                continue
            if df.get(form, 0) >= 1 and _shape_ok(form):
                out.add(form)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--min-df", type=int, default=3)
    ap.add_argument("--roots", nargs="*", default=list(DEFAULT_ROOTS))
    ap.add_argument("--no-merge-existing", action="store_true",
                    help="drop the current curated list instead of merging")
    args = ap.parse_args()

    df, caps, prose_df = mine(args.roots)
    words = set(select(df, caps, args.min_df, prose_df))
    mined = len(words)

    if not args.no_merge_existing and os.path.exists(args.out):
        # looser shape than the miner's: hand-curated entries may carry
        # apostrophes/hyphens or run long (spell.js accepts them), and
        # regeneration must never lose hand-picked vocabulary
        curated_re = re.compile(r"[a-z]+(?:[-'][a-z]+)*")
        for line in open(args.out, encoding="utf-8"):
            w = line.strip().lower()
            if w and curated_re.fullmatch(w):
                words.add(w)

    expanded = expand_inflections(words, df)
    words |= expanded
    print(f"[build_wordlist] affix expansion added {len(expanded)} "
          f"corpus-seen inflections", file=sys.stderr)

    # Rank by PROSE frequency first (code identifiers must not outrank
    # story-English), full-corpus frequency as the tie-break, then
    # alphabetical for determinism; words the miner never counted
    # (curated seeds, merged hand-picked entries) land at their tier
    # end. DOC_STOPWORDS lead the key: doc-corpus boilerplate demotes
    # to the tail tier so suggestion ties resolve toward game words.
    final = sorted(words, key=lambda w: (w in DOC_STOPWORDS,
                                         -prose_df.get(w, 0),
                                         -df.get(w, 0), w))
    with open(args.out, "w", encoding="utf-8") as f:
        f.write("\n".join(final) + "\n")
    print(f"[build_wordlist] {mined} mined + curated merge -> "
          f"{len(final)} words (frequency-ordered) at {args.out}")


if __name__ == "__main__":
    main()
