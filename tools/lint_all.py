"""All static passes, one exit code: metrics + concurrency + jax +
env flags + fault points + lifecycle.

The single CI/pre-commit gate: runs the metric-name pass
(``tools/check_metrics.py``), the three concurrency passes
(``tools/check_concurrency.py``), the four JAX dispatch-discipline
passes (``tools/check_jax.py`` — recompile hazards, tracer leaks,
buffer escapes, env-flag registry), the fault-point registry pass
(``analysis/faultpoints.py`` vs docs/CHAOS.md), and the three
exception-flow/lifecycle passes (``tools/check_lifecycle.py`` —
swallowed errors, future discipline, task/thread/resource leaks) over
the package in one module walk, and exits 1 if any pass finds
anything. Gated as a fast-tier test via
``tests/test_check_concurrency.py``, ``tests/test_check_jax.py``,
``tests/test_chaos.py``, and ``tests/test_check_lifecycle.py``.

Run standalone: ``python tools/lint_all.py [cassmantle_tpu/] [--json]``.

``--changed`` scopes the walk to package files touched in the working
tree (``git diff HEAD`` + untracked) — the pre-commit fast path. A
scoped walk skips the orphan directions (env flags documented but
never read, fault points registered but never called): those claims
are only meaningful over the whole package, the same root-aware rule
``core.main_for`` applies when pointed at a subtree.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.core import (  # noqa: E402
    PACKAGE,
    format_human,
    main_for,
    parse_source,
    run_passes,
    to_json,
)
from cassmantle_tpu.analysis.faultpoints import FaultPointPass  # noqa: E402
from cassmantle_tpu.analysis.lockorder import default_passes  # noqa: E402
from cassmantle_tpu.analysis.metric_names import MetricNamePass  # noqa: E402
from tools.check_jax import jax_passes  # noqa: E402
from tools.check_lifecycle import lifecycle_passes  # noqa: E402


def all_passes(root=PACKAGE):
    # same whole-package rule as the env-flag orphan check: "registered
    # but never called" is only meaningful when the walk covers the
    # package (tools/check_jax.py jax_passes documents the pattern)
    try:
        covers_package = PACKAGE.resolve().is_relative_to(
            pathlib.Path(root).resolve())
    except AttributeError:  # pragma: no cover - py<3.9
        covers_package = True
    return [MetricNamePass(), *default_passes(), *jax_passes(root),
            FaultPointPass(check_orphans=covers_package),
            *lifecycle_passes(root)]


def changed_modules():
    """Package modules touched in the working tree: ``git diff HEAD``
    (staged + unstaged) plus untracked files, filtered to
    ``cassmantle_tpu/*.py``. Deleted files drop out (nothing to
    parse)."""
    names = set()
    for args in (["git", "diff", "--name-only", "HEAD", "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        out = subprocess.run(args, cwd=REPO, capture_output=True,
                             text=True, check=True).stdout
        names.update(line.strip() for line in out.splitlines()
                     if line.strip())
    modules = []
    for rel in sorted(names):
        if not rel.endswith(".py") or \
                not rel.startswith("cassmantle_tpu/"):
            continue
        path = REPO / rel
        if path.exists():
            modules.append(parse_source(path.read_text(), rel))
    return modules


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="lint_all")
    parser.add_argument("root", nargs="?", default=str(PACKAGE))
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--changed", action="store_true",
                        help="lint only package files touched in the "
                             "working tree (git diff HEAD + untracked)")
    args = parser.parse_args(argv)
    if not args.changed:
        # the whole-tree run is exactly main_for's contract; delegate
        # so the CLI shape stays identical across every check_* tool
        forwarded = [args.root] + (["--json"] if args.json else [])
        return main_for(all_passes, forwarded, default_root=PACKAGE,
                        prog="lint_all")
    modules = changed_modules()
    # a non-package root pins covers_package False: a changed-files
    # walk never covers the package, so orphan directions stay off
    findings = run_passes(modules, all_passes(REPO / "tools"))
    if args.json:
        print(to_json(findings))
    else:
        print(f"{len(modules)} changed module(s)")
        print(format_human(findings),
              file=sys.stderr if findings else sys.stdout)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
