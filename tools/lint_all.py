"""All static passes, one exit code: metrics + concurrency + jax +
env flags + fault points.

The single CI/pre-commit gate: runs the metric-name pass
(``tools/check_metrics.py``), the three concurrency passes
(``tools/check_concurrency.py``), the four JAX dispatch-discipline
passes (``tools/check_jax.py`` — recompile hazards, tracer leaks,
buffer escapes, env-flag registry), and the fault-point registry pass
(``analysis/faultpoints.py`` vs docs/CHAOS.md) over the package in one
module walk, and exits 1 if any pass finds anything. Gated as a
fast-tier test via ``tests/test_check_concurrency.py``,
``tests/test_check_jax.py``, and ``tests/test_chaos.py``.

Run standalone: ``python tools/lint_all.py [cassmantle_tpu/] [--json]``.
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from cassmantle_tpu.analysis.core import PACKAGE, main_for  # noqa: E402
from cassmantle_tpu.analysis.faultpoints import FaultPointPass  # noqa: E402
from cassmantle_tpu.analysis.lockorder import default_passes  # noqa: E402
from cassmantle_tpu.analysis.metric_names import MetricNamePass  # noqa: E402
from tools.check_jax import jax_passes  # noqa: E402


def all_passes(root=PACKAGE):
    # same whole-package rule as the env-flag orphan check: "registered
    # but never called" is only meaningful when the walk covers the
    # package (tools/check_jax.py jax_passes documents the pattern)
    try:
        covers_package = PACKAGE.resolve().is_relative_to(
            pathlib.Path(root).resolve())
    except AttributeError:  # pragma: no cover - py<3.9
        covers_package = True
    return [MetricNamePass(), *default_passes(), *jax_passes(root),
            FaultPointPass(check_orphans=covers_package)]


def main(argv=None) -> int:
    return main_for(all_passes, argv, default_root=PACKAGE,
                    prog="lint_all")


if __name__ == "__main__":
    raise SystemExit(main())
