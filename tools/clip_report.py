"""CLIP-similarity report across serving presets (the quality gate).

BASELINE.md's gate is "CLIP-similarity parity": the fast presets
(DPM-Solver++(2M) @ 25 steps, deepcache) only count as wins if their
images score on par with the fixed DDIM-50 config under CLIP. This tool
generates the same prompts with each preset, scores every image against
its prompt with the local CLIP harness (eval/clip_parity.py — both
towers + projections load from clip_text.safetensors), and writes one
JSON report with per-preset means and ratios vs the ddim50 anchor.

The reference never measures image quality — it trusts a hosted SDXL
endpoint's output (/root/reference/src/backend.py:270-295); this harness
is that trust made falsifiable. ``real_weights`` is false when any CLIP
stage fell back to random init: such a run validates plumbing only and
must not be quoted as a quality number.

Usage:
    python tools/clip_report.py [--weights weights] [--out CLIP_REPORT.json]
        [--platform cpu] [--presets ddim50,dpmpp25,deepcache,turbo,int8,encprop]
        [--tiny]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

PROMPTS = [
    "A watercolor style piece depicting: a lighthouse over a stormy sea",
    "An art deco style piece depicting: a caravan crossing silver dunes",
    "A stained glass style piece depicting: an orchard under two moons",
    "A vaporwave style piece depicting: a night train between cities",
    "An ukiyo-e style piece depicting: cranes over a frozen river",
    "A chalk pastel style piece depicting: a market street in the rain",
    "A linocut style piece depicting: a fox asleep in a bell tower",
    "A gouache style piece depicting: terraced fields at first light",
]


def _with_unet_int8(cfg):
    import dataclasses

    return cfg.replace(
        models=dataclasses.replace(cfg.models, unet_int8=True))


def preset_factories(tiny: bool):
    if tiny:
        import dataclasses

        from cassmantle_tpu.config import test_config

        def tiny_kind(kind, **kw):
            def make():
                cfg = test_config()
                return cfg.replace(sampler=dataclasses.replace(
                    cfg.sampler, kind=kind, **kw))
            return make

        return {
            "ddim50": tiny_kind("ddim", num_steps=4),
            "dpmpp25": tiny_kind("dpmpp_2m", num_steps=2),
            "deepcache": tiny_kind("ddim", num_steps=4, deepcache=True),
            "turbo": tiny_kind("dpmpp_2m", num_steps=4, deepcache=True),
            "int8": lambda: _with_unet_int8(test_config()),
            "encprop": tiny_kind("ddim", num_steps=4, encprop=True,
                                 encprop_stride=2, encprop_dense_steps=0),
        }
    from cassmantle_tpu.config import (
        FrameworkConfig,
        deepcache_serving_config,
        encprop_serving_config,
        fast_serving_config,
        turbo_serving_config,
    )

    return {
        "ddim50": FrameworkConfig,
        "dpmpp25": fast_serving_config,
        "deepcache": deepcache_serving_config,
        "turbo": turbo_serving_config,
        # quality arm of the sd15_int8 bench A/B: same DDIM-50
        # trajectory, int8 UNet weights
        "int8": lambda: _with_unet_int8(FrameworkConfig()),
        # quality arm of the sd15_encprop bench A/B: DDIM-50 with
        # encoder propagation (20 key steps) + fused VAE decode
        "encprop": encprop_serving_config,
    }


def apply_quality_gate(report: dict, gate_cfg=None) -> list:
    """Annotate each gated preset with {threshold, passed} and return
    the list of human-readable failures (config.QualityGateConfig).
    Pure on the report dict — unit-tested without pipelines."""
    if gate_cfg is None:
        # default thresholds come from the framework config, so a
        # FrameworkConfig(quality=...) override is the single source
        from cassmantle_tpu.config import FrameworkConfig

        gate_cfg = FrameworkConfig().quality
    failures = []
    anchor = report["presets"].get("ddim50")
    if anchor:
        floor = gate_cfg.ddim50_min_sim
        anchor["gate"] = {"min_sim": floor,
                          "passed": anchor["clip_sim_mean"] >= floor}
        if not anchor["gate"]["passed"]:
            failures.append(
                f"ddim50 anchor clip_sim_mean "
                f"{anchor['clip_sim_mean']:.4f} < floor {floor}")
    for name, entry in report["presets"].items():
        threshold = gate_cfg.threshold_for(name)
        if threshold is None or "parity_vs_ddim50" not in entry:
            continue
        entry["gate"] = {"threshold": threshold,
                         "passed": entry["parity_vs_ddim50"] >= threshold}
        if not entry["gate"]["passed"]:
            failures.append(
                f"{name} parity_vs_ddim50 "
                f"{entry['parity_vs_ddim50']:.4f} < {threshold}")
    return failures


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    # default resolves against the repo (module-CLI runs from anywhere);
    # an explicit --weights keeps its shell meaning
    ap.add_argument("--weights",
                    default=os.path.join(REPO_ROOT, "weights"))
    ap.add_argument("--out", default=None,
                    help="report path; defaults to CLIP_REPORT.json, or "
                         "CLIP_REPORT.tiny.json under --tiny so a "
                         "plumbing smoke can never overwrite hardware "
                         "evidence (same split as bench.py's cpu-smoke "
                         "suite file)")
    ap.add_argument("--platform", default="auto", choices=["auto", "cpu"])
    ap.add_argument("--presets",
                    default="ddim50,dpmpp25,deepcache,turbo,int8,encprop")
    ap.add_argument("--seeds", type=int, default=2,
                    help="image batches per preset (n = seeds * 8 prompts)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny configs (plumbing smoke, not a measurement)")
    ap.add_argument("--enforce", action="store_true",
                    help="fail the quality gate even on random-init "
                         "runs (tests the enforcement path)")
    args = ap.parse_args()
    if args.out is None:
        args.out = os.path.join(
            REPO_ROOT,
            "CLIP_REPORT.tiny.json" if args.tiny else "CLIP_REPORT.json")

    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)

    from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    # --tiny is a plumbing smoke: tiny-config models must never try to
    # ingest a real full-size checkpoint (layer-prefix conversion would
    # "succeed" then fail at apply with shape errors)
    weights_dir = (None if args.tiny
                   else args.weights if os.path.isdir(args.weights)
                   else None)
    if args.tiny:
        from cassmantle_tpu.config import ClipTextConfig
        from cassmantle_tpu.models.clip_vision import ClipVisionConfig

        harness = ClipSimilarityHarness(
            text_cfg=ClipTextConfig(
                vocab_size=512, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, max_positions=16),
            vision_cfg=ClipVisionConfig.tiny(),
            weights_dir=None, pad_len=16)
    else:
        harness = ClipSimilarityHarness(weights_dir=weights_dir)

    factories = preset_factories(args.tiny)
    wanted = [p.strip() for p in args.presets.split(",") if p.strip()]
    unknown = sorted(set(wanted) - set(factories))
    if unknown:
        sys.exit(f"unknown presets: {unknown}; have {sorted(factories)}")

    import numpy as np

    report: dict = {
        "real_weights": harness.loaded_real_weights,
        "prompts": len(PROMPTS), "seeds": args.seeds,
        "presets": {},
    }
    from cassmantle_tpu.serving.pipeline import share_compatible

    anchors = []  # one anchor pipeline per distinct architecture
    for name in wanted:
        cfg = factories[name]()
        share = next(
            (p for p in anchors
             if share_compatible(p.cfg.models, cfg.models)),
            None)
        pipe = Text2ImagePipeline(cfg, weights_dir=weights_dir,
                                  share_params_with=share)
        if share is None:
            anchors.append(pipe)
        sims = []
        for seed in range(args.seeds):
            images = pipe.generate(PROMPTS, seed=seed)
            sims.extend(harness.similarity(images, PROMPTS).tolist())
        entry = {
            "clip_sim_mean": float(np.mean(sims)),
            "clip_sim_std": float(np.std(sims)),
            "n": len(sims),
            "pipeline_real_weights": pipe.loaded_real_weights,
        }
        # the headline flag means "this whole report is a measurement":
        # scorer AND every generator loaded from checkpoints
        report["real_weights"] = (
            report["real_weights"] and pipe.loaded_real_weights
        )
        report["presets"][name] = entry
        print(f"[clip_report] {name}: mean={entry['clip_sim_mean']:.4f} "
              f"std={entry['clip_sim_std']:.4f} n={entry['n']}")

    anchor = report["presets"].get("ddim50")
    if anchor:
        for name, entry in report["presets"].items():
            if name != "ddim50" and anchor["clip_sim_mean"]:
                entry["parity_vs_ddim50"] = float(
                    entry["clip_sim_mean"] / anchor["clip_sim_mean"])

    # Quality-gate enforcement (config.QualityGateConfig): thresholds
    # are asserted whenever this report is a real measurement — random
    # init similarity is noise, so plumbing runs report advisory-only
    # unless --enforce forces the gate (CI of the enforcement path).
    enforce = report["real_weights"] or args.enforce
    failures = apply_quality_gate(report)
    report["gate_enforced"] = bool(enforce)
    report["gate_failures"] = failures

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[clip_report] wrote {args.out} "
          f"(real_weights={report['real_weights']})")
    if failures:
        verdict = "FAILED" if enforce else "advisory (random weights)"
        print(f"[clip_report] quality gate {verdict}:", file=sys.stderr)
        for f_ in failures:
            print(f"[clip_report]   {f_}", file=sys.stderr)
        if enforce:
            sys.exit(2)
    elif anchor:
        print("[clip_report] quality gate passed "
              f"({'enforced' if enforce else 'advisory'})")


if __name__ == "__main__":
    main()
