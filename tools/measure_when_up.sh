#!/usr/bin/env bash
# Tunnel watcher: poll the accelerator; the moment it answers, run the
# full measurement stack and leave the artifacts in the repo root
# (BENCH_SUITE.json, PROFILE_UNET.txt, LM_INT8_AB.json). Used when the
# device tunnel has been down for hours and measurements must start
# unattended the moment it recovers (see docs/DEPLOY.md §5 for the
# attended version). Exits 0 after measuring, 2 if the deadline passes
# with the tunnel still down.
set -u
cd "$(dirname "$0")/.."

DEADLINE_S=${DEADLINE_S:-14400}   # give up after 4h by default
POLL_S=${POLL_S:-60}              # outage windows end mid-poll; 60 s
                                  # costs nothing and catches short
                                  # tunnel windows a 5 min poll misses
start=$(date +%s)

probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; x = jnp.ones((128, 128)); \
     (x @ x).block_until_ready(); print(jax.devices())" \
    >/dev/null 2>&1
}

attempts=0
while true; do
  if probe; then
    echo "[watcher] tunnel UP at $(date -u +%H:%M:%S) after $attempts failed probes — measuring"
    break
  fi
  attempts=$((attempts + 1))
  now=$(date +%s)
  # one line per failed probe: a zero-byte log after an outage round
  # proved the watcher ran at all only by its exit code (round 4) —
  # the poll trail itself is the outage evidence
  echo "[watcher] $(date -u +%H:%M:%SZ) probe $attempts failed ($(((now - start) / 60))/$((DEADLINE_S / 60)) min); tunnel down"
  if [ $((now - start)) -ge "$DEADLINE_S" ]; then
    echo "[watcher] deadline reached after $attempts failed probes; tunnel down the whole window"
    exit 2
  fi
  sleep "$POLL_S"
done

set -x
ENTRY_TIMEOUT=${BENCH_ENTRY_TIMEOUT:-2000}
# entry count drives the outer timeout: derive it from bench.py (or the
# entry selection, when one is set) so a suite grown since this line was
# written can't be silently under-budgeted and killed mid-run
if [ -n "${BENCH_SUITE_ENTRIES:-}" ]; then
  ENTRIES=$(python -c "import os; print(len([e for e in \
    os.environ['BENCH_SUITE_ENTRIES'].split(',') if e.strip()]))")
else
  ENTRIES=$(python -c 'import bench; print(len(bench.SUITE))')
fi
[ -n "$ENTRIES" ] || { echo "[watcher] could not count suite entries"; exit 1; }
# per-entry retries are budgeted INSIDE each entry's timeout, so the
# suite's worst case is entries x timeout, plus bench.py's own probe
# window (the tunnel can flap between our probe and bench's) and 1h
# slack for io
SUITE_TIMEOUT=$((ENTRIES * ENTRY_TIMEOUT + ${BENCH_PROBE_DEADLINE_S:-2700} + 3600))
# North-star fast path FIRST: sd15 + sd15_turbo at 1 timed round, short
# probe (our own probe just passed). A tunnel window only minutes long
# still lands the two numbers the perf case turns on; the full suite
# then re-measures them at full reps (fresh success overwrites). An
# operator-scoped run (BENCH_SUITE_ENTRIES) skips it — a scorer-only
# re-measure must not spend its window on two image benches.
if [ -z "${BENCH_SUITE_ENTRIES:-}" ]; then
  BENCH_PROBE_DEADLINE_S=120 BENCH_ENTRY_TIMEOUT=$ENTRY_TIMEOUT \
    timeout $((2 * ENTRY_TIMEOUT + 600)) python bench.py --north-star-only \
    2>BENCH_NORTH_STAR.stderr.log
fi
BENCH_ENTRY_TIMEOUT=$ENTRY_TIMEOUT \
  timeout "$SUITE_TIMEOUT" python bench.py --suite \
  2>BENCH_SUITE.stderr.log
timeout 3600 python tools/profile_unet.py 2>&1 | tee PROFILE_UNET.txt
# flash tile-size sweep (CASSMANTLE_FLASH_BLOCK_*, ops/flash_attention.py):
# the 1024 default was tuned round 1 and never re-verified after the
# flash-cross/fallback changes; ineligible sites fall back labeled
for bq in 512 2048; do
  CASSMANTLE_FLASH_BLOCK_Q=$bq CASSMANTLE_FLASH_BLOCK_K=$bq \
    timeout 1800 python tools/profile_unet.py 2>&1 \
    | tee "PROFILE_UNET_B${bq}.txt"
done
timeout 3600 python tools/lm_int8_ab.py --tokens 64 --out LM_INT8_AB.json
# Quality gate: on a weights-provisioned host this same command emits
# the real_weights=true CLIP parity verdict (ddim50 vs dpmpp25 vs
# deepcache vs turbo vs int8 — parity_vs_ddim50 per preset). Without
# checkpoints a CLIP report would be plumbing-only noise, so skip it.
# real_weights=true needs EVERY stage from a checkpoint (pipeline +
# CLIP harness); a partial provision would burn a 2h run on a
# plumbing-only report, so require all three — whole files or the
# sharded form (<stem>-*.safetensors) that load_checkpoint_tensors merges
have_ckpt() {
  ls "weights/$1.safetensors" "weights/$1"-*.safetensors >/dev/null 2>&1
}
if have_ckpt clip_text && have_ckpt unet && have_ckpt vae; then
  # real_weights=true -> tools/clip_report.py ENFORCES the per-preset
  # thresholds (config.QualityGateConfig) and exits 2 on a miss; a
  # failed gate fails the whole watcher run so the fast presets'
  # throughput numbers can't be quoted without their quality evidence
  timeout 7200 python tools/clip_report.py --seeds 2 || {
    rc=$?
    # exit 2 is clip_report's explicit gate verdict; anything else
    # (timeout 124, crash) is infra — report it as such, never as a
    # quality miss
    if [ "$rc" -eq 2 ]; then
      echo "[watcher] CLIP quality gate FAILED (threshold miss)"
      exit 3
    fi
    echo "[watcher] CLIP report errored (exit $rc) — infra, not a gate verdict"
    exit 5
  }
  # LM-decoded-round drill leg: one full game round whose prompt text
  # genuinely came from the LM (no template fallback) — the seam the
  # virtual-mesh dryrun can only exercise with random weights. Needs
  # the LM checkpoint on top of the image stack; a partial provision
  # (images only) skips rather than failing hours of good measurements
  if have_ckpt gpt2 || have_ckpt mistral; then
    timeout 3600 python -m cassmantle_tpu weights-drill \
      --skip-fetch --skip-quantize --skip-clip --skip-lm-ab || {
      echo "[watcher] LM-decoded round drill FAILED"
      exit 4
    }
  else
    echo "[watcher] no LM checkpoint — skipping the LM-decoded round leg"
  fi
else
  echo "[watcher] weights/ missing checkpoints — skipping CLIP quality report"
fi
set +x
echo "[watcher] measurements complete"
