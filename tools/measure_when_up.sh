#!/usr/bin/env bash
# Tunnel watcher: poll the accelerator; the moment it answers, run the
# full measurement stack and leave the artifacts in the repo root
# (BENCH_SUITE.json, PROFILE_UNET.txt, LM_INT8_AB.json). Used when the
# device tunnel has been down for hours and measurements must start
# unattended the moment it recovers (see docs/DEPLOY.md §5 for the
# attended version). Exits 0 after measuring, 2 if the deadline passes
# with the tunnel still down.
set -u
cd "$(dirname "$0")/.."

DEADLINE_S=${DEADLINE_S:-14400}   # give up after 4h by default
POLL_S=${POLL_S:-300}
start=$(date +%s)

probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; x = jnp.ones((128, 128)); \
     (x @ x).block_until_ready(); print(jax.devices())" \
    >/dev/null 2>&1
}

while true; do
  if probe; then
    echo "[watcher] tunnel UP at $(date -u +%H:%M:%S) — measuring"
    break
  fi
  now=$(date +%s)
  if [ $((now - start)) -ge "$DEADLINE_S" ]; then
    echo "[watcher] deadline reached; tunnel still down"
    exit 2
  fi
  sleep "$POLL_S"
done

set -x
ENTRY_TIMEOUT=${BENCH_ENTRY_TIMEOUT:-2000}
ENTRIES=11
# per-entry retries are budgeted INSIDE each entry's timeout, so the
# suite's worst case is entries x timeout; +1h slack for probes/io
SUITE_TIMEOUT=$((ENTRIES * ENTRY_TIMEOUT + 3600))
BENCH_ENTRY_TIMEOUT=$ENTRY_TIMEOUT \
  timeout "$SUITE_TIMEOUT" python bench.py --suite \
  2>BENCH_SUITE.stderr.log
timeout 3600 python tools/profile_unet.py 2>&1 | tee PROFILE_UNET.txt
timeout 3600 python tools/lm_int8_ab.py --tokens 64 --out LM_INT8_AB.json
set +x
echo "[watcher] measurements complete"
