"""Profile the SD1.5 UNet denoise step on the attached TPU.

Prints per-config step time, achieved TFLOP/s (from XLA's cost analysis),
and a flash-vs-XLA attention A/B at each spatial resolution, to target
optimization work.

Usage: python tools/profile_unet.py [batch] [--dump-hlo]

--dump-hlo additionally writes the backend-optimized HLO module (what
the TPU actually runs) to UNET_HLO.txt at the repo root.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import jax
import jax.numpy as jnp

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.ops import attention as attn_mod
from cassmantle_tpu.utils.compile_cache import enable_compile_cache


def timeit(fn, *args, reps=10):
    """Thin adapter over tools/bench_parts.timeit (one timing
    methodology for all profilers), silencing its per-line print."""
    import contextlib
    import io

    try:
        from tools.bench_parts import timeit as _timeit
    except ImportError:  # run as `python tools/profile_unet.py`
        from bench_parts import timeit as _timeit

    with contextlib.redirect_stdout(io.StringIO()):
        return _timeit("", fn, *args, reps=reps)


def cost_table(fn, *args, top: int = 10):
    """Analytic per-op cost table from the jaxpr: FLOPs for every
    dot/conv (shape-derived — backend-independent, so it is valid even
    when compiled on CPU), grouped by (primitive, operand shapes),
    sorted by total FLOPs. The HARDWARE complement is the optimized-HLO
    dump (--dump-hlo) plus PROFILE_UNET.txt timings: this table says
    where the FLOPs are; the dump says what XLA fused around them.

    The per-eqn FLOP math is shared with the runtime cost model
    (cassmantle_tpu/obs/costmodel.py::eqn_flops), so this table, the
    committed cost-model artifact, and the live `pipeline.mxu_*`
    attribution can never disagree on what an op costs."""
    import collections

    from cassmantle_tpu.obs.costmodel import eqn_flops

    jaxpr = jax.make_jaxpr(fn)(*args)
    groups = collections.defaultdict(lambda: [0, 0.0])  # count, flops

    def visit(jx, mult: float = 1.0):
        for eqn in jx.eqns:
            # a scan body executes `length` times: its ops cost
            # length x (the full 50-step denoise loop would otherwise
            # count as one step)
            inner = mult
            if eqn.primitive.name == "scan":
                inner = mult * float(eqn.params.get("length", 1))
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    visit(sub.jaxpr, inner)
                elif isinstance(sub, (list, tuple)):
                    for s in sub:
                        if hasattr(s, "jaxpr"):
                            visit(s.jaxpr, inner)
            name = eqn.primitive.name
            if name not in ("dot_general", "conv_general_dilated"):
                continue
            shapes = tuple(tuple(getattr(v.aval, "shape", ()))
                           for v in eqn.invars)
            flops = eqn_flops(eqn)
            key = (name, shapes)
            groups[key][0] += mult
            groups[key][1] += flops * mult

    visit(jaxpr.jaxpr)
    rows = sorted(groups.items(), key=lambda kv: -kv[1][1])
    total = sum(v[1] for v in groups.values())
    out_rows = []
    for (name, shapes), (count, flops) in rows[:top]:
        out_rows.append({
            "op": name,
            "shapes": "x".join(str(list(s)) for s in shapes[:2]),
            "count": int(count),
            "gflops": round(flops / 1e9, 2),
            "pct": round(100 * flops / total, 1) if total else 0.0,
        })
    return out_rows, total


def encoder_decoder_split(model, params, lat, ts, ctx, add=None):
    """(encoder TF, decoder TF, total TF) per UNet forward: the decoder
    figure comes from costing the decoder-only apply (``skips_cache``
    mode, models/unet.py — exactly what an encprop propagated step
    runs) against an eval_shape'd encoder cache; encoder = total −
    decoder. Shape-derived, so valid on any backend."""
    args = (lat, ts, ctx) + ((add,) if add is not None else ())
    _, cache = jax.eval_shape(
        lambda p, *a: model.apply(p, *a, return_skips=True), params, *args)

    def decoder_only(p, cache_, t, c, *a):
        return model.apply(p, None, t, c, *a, skips_cache=cache_)

    dec_args = (params, cache, ts, ctx) + ((add,) if add is not None
                                           else ())
    _, dec_total = cost_table(decoder_only, *dec_args)
    _, total = cost_table(
        lambda p, *a: model.apply(p, *a), params, *args)
    return total - dec_total, dec_total, total


def vae_decode_cost(vae_cfg, image_size: int, batch: int):
    """(VAE decode TF/image, mid-attention TF/image, token count) at the
    given output resolution — the decode-side rows of the cost table.
    The attention figure is the analytic dot cost of VAEAttnBlock at
    the mid-block geometry (4 S×C² projections + the 2 S²×C attention
    einsums over S = latent H·W tokens), i.e. what the naive path pays
    and what the flash-VAE-attn route keeps out of HBM."""
    from cassmantle_tpu.models.vae import VAEDecoder

    vae = VAEDecoder(vae_cfg)
    scale = 2 ** (len(vae_cfg.channel_mults) - 1)
    lat_hw = image_size // scale
    lat = jax.ShapeDtypeStruct((batch, lat_hw, lat_hw, 4), jnp.float32)
    params = jax.eval_shape(
        vae.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, lat_hw, lat_hw, 4), jnp.float32))
    _, total = cost_table(lambda p, z: vae.apply(p, z), params, lat)
    s_tokens = lat_hw * lat_hw
    c = vae_cfg.base_channels * vae_cfg.channel_mults[-1]
    attn = batch * (4 * 2.0 * s_tokens * c * c
                    + 2 * 2.0 * s_tokens * s_tokens * c)
    return total / batch, attn / batch, s_tokens


def print_encprop_accounting(encoder, decoder, total, vae_tf, vae_attn,
                             s_tokens, sampler_cfg, chip_tflops=197e12):
    """The encprop analytic bound, from the same numbers the per-image
    TF figure came from: full forwards at the key steps of the
    configured schedule, decoder-only forwards elsewhere (CFG doubles
    both), plus the VAE decode — the PERF_NOTES 'Encoder propagation
    accounting' model."""
    from cassmantle_tpu.ops.ddim import encprop_key_indices

    n = sampler_cfg.num_steps
    keys = len(encprop_key_indices(n, sampler_cfg.encprop_stride,
                                   sampler_cfg.encprop_dense_steps))
    full_img = 2 * n * total
    enc_img = 2 * (keys * total + (n - keys) * decoder) + vae_tf
    print(f"UNet split/forward: encoder(conv_in+down+mid) "
          f"{encoder / 1e12:.3f} TF ({100 * encoder / total:.0f}%)  "
          f"decoder(up+out) {decoder / 1e12:.3f} TF "
          f"({100 * decoder / total:.0f}%)")
    print(f"VAE decode: {vae_tf / 1e12:.2f} TF/image  "
          f"(mid attention {vae_attn / 1e12:.2f} TF at S={s_tokens})")
    print(f"encprop bound @ stride {sampler_cfg.encprop_stride} "
          f"+{sampler_cfg.encprop_dense_steps} dense ({keys} keys / {n} "
          f"steps): {enc_img / 1e12:.1f} TF/image vs "
          f"{(full_img + vae_tf) / 1e12:.1f} full "
          f"({100 * enc_img / (full_img + vae_tf):.0f}%) -> ceiling "
          f"{chip_tflops / enc_img:.3f} img/s/chip vs "
          f"{chip_tflops / (full_img + vae_tf):.3f}")


def _image_cost_entry(kind: str, cfg) -> dict:
    """Per-stage analytic cost of one image pipeline (``t2i``/``sdxl``)
    at batch 1: eval_shape'd params (no init — the SDXL entry covers a
    2.6B tree in seconds on CPU), stage FLOPs/HBM-bytes from the same
    jaxpr walk the runtime uses (obs/costmodel.py::trace_cost). CFG
    factors are baked in per image: conditioning encodes cond+uncond
    (×2), the denoise stage runs 2·num_steps UNet forwards."""
    from cassmantle_tpu.models.clip_text import ClipTextEncoder
    from cassmantle_tpu.models.vae import VAEDecoder
    from cassmantle_tpu.obs import costmodel

    m = cfg.models
    s = cfg.sampler
    dtype = jnp.dtype(m.param_dtype)
    pad_len = min(s.prompt_pad_len, m.clip_text.max_positions)
    if kind == "sdxl":
        pad_len = min(pad_len, m.clip_text_2.max_positions)
    vae_scale = 2 ** (len(m.vae.channel_mults) - 1)
    lat_hw = s.image_size // vae_scale
    rng = jax.random.PRNGKey(0)
    ids = jax.ShapeDtypeStruct((1, pad_len), jnp.int32)
    lat = jax.ShapeDtypeStruct((1, lat_hw, lat_hw, 4), dtype)
    ts = jax.ShapeDtypeStruct((1,), jnp.int32)
    ctx = jax.ShapeDtypeStruct((1, pad_len, m.unet.context_dim), dtype)

    clip = ClipTextEncoder(m.clip_text)
    clip_params = jax.eval_shape(clip.init, rng, ids)
    enc_f, enc_b = costmodel.trace_cost(
        lambda p, i: clip.apply(p, i), clip_params, ids)
    unet = UNet(m.unet)
    if kind == "sdxl":
        clip2 = ClipTextEncoder(m.clip_text_2)
        clip2_params = jax.eval_shape(clip2.init, rng, ids)
        f2, b2 = costmodel.trace_cost(
            lambda p, i: clip2.apply(p, i), clip2_params, ids)
        enc_f, enc_b = enc_f + f2, enc_b + b2
        add = jax.ShapeDtypeStruct((1, m.unet.addition_embed_dim), dtype)
        unet_params = jax.eval_shape(unet.init, rng, lat, ts, ctx, add)
        unet_f, unet_b = costmodel.trace_cost(
            lambda p, l, t, c, a: unet.apply(p, l, t, c, a),
            unet_params, lat, ts, ctx, add)
        signature = costmodel.sdxl_signature(cfg)
    else:
        unet_params = jax.eval_shape(unet.init, rng, lat, ts, ctx)
        unet_f, unet_b = costmodel.trace_cost(
            lambda p, l, t, c: unet.apply(p, l, t, c),
            unet_params, lat, ts, ctx)
        signature = costmodel.t2i_signature(cfg)
    vae = VAEDecoder(m.vae)
    vae_params = jax.eval_shape(vae.init, rng, lat)
    vae_f, vae_b = costmodel.trace_cost(
        lambda p, z: vae.apply(p, z), vae_params, lat)

    # W8A8 serving (ISSUE 20): the fp trace above is still the FLOPs
    # proxy (the int8 kernels run the same dot/conv math on the MXU's
    # doubled int8 rate — a throughput factor, not an op-count change),
    # but weight-side HBM traffic halves at every quantized site: the
    # param read streams int8 instead of param_dtype per forward.
    w8a8 = _image_w8a8_armed(m)
    w8a8_elems = _w8a8_site_elements(unet_params, m.w8a8_min_size) \
        if w8a8 else 0
    unet_saved = w8a8_elems * (jnp.dtype(m.param_dtype).itemsize - 1)
    stages = {
        # cond + uncond conditioning per image
        "clip_encode": {"flops": int(2 * enc_f),
                        "hbm_bytes": int(2 * enc_b)},
        # CFG doubles every denoise forward
        "denoise": {"flops": int(2 * s.num_steps * unet_f),
                    "hbm_bytes": int(2 * s.num_steps
                                     * (unet_b - unet_saved))},
        "vae_decode": {"flops": int(vae_f), "hbm_bytes": int(vae_b)},
    }
    total_f = sum(st["flops"] for st in stages.values())
    total_b = sum(st["hbm_bytes"] for st in stages.values())
    buckets = (1, 2, 4, 8)
    return {
        "signature": signature,
        "image_size": s.image_size,
        "num_steps": s.num_steps,
        "sampler": s.kind,
        # few-step consistency preset (ISSUE 15): num_steps direct
        # forwards of the same UNet — the denoise math above already
        # covers it (2·num_steps CFG forwards)
        "consistency": bool(s.consistency),
        "w8a8": w8a8,
        "stages": stages,
        "flops_per_item": total_f,
        "hbm_bytes_per_item": total_b,
        # batch-linear (dot/conv flops scale with B): per-bucket totals
        "buckets": {str(b): total_f * b for b in buckets},
    }


def _image_w8a8_armed(models_cfg) -> bool:
    from cassmantle_tpu.serving.pipeline import unet_w8a8_armed

    return unet_w8a8_armed(models_cfg)


def _w8a8_site_elements(params, min_size: int) -> int:
    """Total weight-element count of w8a8-quantizable kernel sites in
    an eval_shape'd tree — the elements that stream int8 (1 byte)
    instead of param_dtype under W8A8 serving."""
    import math

    from cassmantle_tpu.ops.quant import w8a8_default_predicate

    total = 0

    def walk(tree, path=()):
        nonlocal total
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(v, path + (k,))
        elif hasattr(tree, "shape") and w8a8_default_predicate(
                path, tree, min_size=min_size):
            total += math.prod(tree.shape)

    walk(params)
    return total


def _lm_cost_entry(cfg) -> dict:
    """Prompt-LM analytic cost: dense decode reads every weight per
    token — 2·N FLOPs and N·itemsize HBM bytes per token processed
    (PERF_NOTES "LM decode accounting"); N from an eval_shape init."""
    from cassmantle_tpu.models.gpt2 import GPT2LM
    from cassmantle_tpu.obs import costmodel
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    m = cfg.models.gpt2
    model = GPT2LM(m)
    params = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, 8), jnp.int32))
    n = costmodel.params_count(params)
    per_token = 2 * n
    itemsize = jnp.dtype(cfg.models.param_dtype).itemsize
    # W8A8 (ISSUE 20): quantized matmul sites stream int8 weights —
    # same 2·N FLOPs per token, fewer weight-read bytes
    from cassmantle_tpu.serving.pipeline import lm_w8a8_armed

    w8a8 = lm_w8a8_armed(cfg.models)
    saved = _w8a8_site_elements(
        params, cfg.models.w8a8_min_size) * (itemsize - 1) if w8a8 else 0
    return {
        "signature": costmodel.lm_signature(m, w8a8=w8a8),
        "model": "gpt2",
        "params": n,
        "w8a8": w8a8,
        "flops_per_item": per_token,           # per token processed
        "hbm_bytes_per_item": n * itemsize - saved,
        "prompt_buckets": list(PromptGenerator.PROMPT_BUCKETS),
        "batch_buckets": list(PromptGenerator.BATCH_BUCKETS),
        "buckets": {str(b): per_token * b
                    for b in PromptGenerator.PROMPT_BUCKETS},
    }


def _scorer_cost_entry(cfg, seq_len: int = 16) -> dict:
    """MiniLM scorer analytic cost per encoded row (seq_len tokens)."""
    from cassmantle_tpu.models.minilm import MiniLMEncoder
    from cassmantle_tpu.obs import costmodel

    m = cfg.models.minilm
    model = MiniLMEncoder(m)
    seq_len = min(seq_len, m.max_positions)
    ids = jax.ShapeDtypeStruct((1, seq_len), jnp.int32)
    mask = jax.ShapeDtypeStruct((1, seq_len), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids, mask)
    n = costmodel.params_count(params)
    per_row = 2 * n * seq_len
    return {
        "signature": costmodel.scorer_signature(m, seq_len),
        "model": "minilm",
        "params": n,
        "seq_len": seq_len,
        "flops_per_item": per_row,             # per encoded row
        "hbm_bytes_per_item": n * 4,           # fp32 weight read
        "buckets": {str(b): per_row * b
                    for b in cfg.serving.score_batch_sizes},
    }


def emit_cost_model(path: str) -> dict:
    """``--emit-cost-model``: write the machine-readable analytic cost
    model (FLOPs + HBM-bytes proxy per pipeline/stage/bucket for the
    PRODUCTION configs) the serving pipelines load at dispatch time
    (obs/costmodel.py). Everything is shape-derived under eval_shape —
    deterministic integers, no weights, runs on any backend in seconds —
    so the committed ``data/cost_model.json`` doubles as a drift gate
    (tests/test_obs_device.py regenerates and compares)."""
    import dataclasses

    from cassmantle_tpu.config import (
        FrameworkConfig,
        lcm_serving_config,
        sdxl_config,
        w8a8_serving_config,
    )
    from cassmantle_tpu.obs import costmodel

    # the SDXL W8A8 arm: production SDXL geometry with the quantized
    # UNet path armed (same knobs w8a8_serving_config sets for SD1.5)
    sdxl_base = sdxl_config()
    sdxl_w8a8 = dataclasses.replace(
        sdxl_base, models=dataclasses.replace(
            sdxl_base.models,
            unet=dataclasses.replace(sdxl_base.models.unet,
                                     fused_conv=True, conv_pad_to=128),
            unet_w8a8=True))
    model = {
        "version": 1,
        "generated_by": "python tools/profile_unet.py --emit-cost-model",
        "chip_tflops": costmodel.DEFAULT_CHIP_TFLOPS,
        "note": ("analytic dot/conv FLOPs (obs/costmodel.py trace_cost; "
                 "same math as --cost-table); hbm_bytes is a roofline "
                 "proxy (operand+result buffer bytes, fusion ignored — "
                 "an upper bound on true traffic)"),
        "pipelines": {
            "t2i": _image_cost_entry("t2i", FrameworkConfig()),
            # the few-step consistency preset: same pipeline kind, the
            # committed 4-step geometry (resolved by signature scan —
            # obs/costmodel.py::committed_entry)
            "t2i_lcm": _image_cost_entry("t2i", lcm_serving_config()),
            "sdxl": _image_cost_entry("sdxl", sdxl_config()),
            "prompt": _lm_cost_entry(FrameworkConfig()),
            "scorer": _scorer_cost_entry(FrameworkConfig()),
            # W8A8 serving variants (ISSUE 20): same analytic FLOPs,
            # weight-side HBM bytes halved at quantized sites — their
            # signatures differ (the armed w8a8 state digests in), so
            # quantized pipelines resolve these entries by scan
            "t2i_w8a8": _image_cost_entry("t2i", w8a8_serving_config()),
            "sdxl_w8a8": _image_cost_entry("sdxl", sdxl_w8a8),
            "prompt_w8a8": _lm_cost_entry(w8a8_serving_config()),
        },
    }
    import json

    with open(path, "w") as f:
        json.dump(model, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"cost model -> {path}")
    return model


def main():
    import argparse

    ap = argparse.ArgumentParser(
        description="Profile the SD1.5 UNet denoise step on the TPU")
    ap.add_argument("batch", nargs="?", type=int, default=8)
    ap.add_argument("--dump-hlo", action="store_true",
                    help="write the backend-optimized HLO to UNET_HLO.txt")
    ap.add_argument("--cost-table", action="store_true",
                    help="print the top-op analytic FLOP table "
                         "(shape-derived; valid on any backend) and exit")
    ap.add_argument("--full-pipeline", action="store_true",
                    help="with --cost-table: trace the WHOLE north-star "
                         "graph (CLIP encode + N-step CFG denoise scan, "
                         "scan body costs multiplied by its trip count, "
                         "+ VAE decode) instead of one UNet forward")
    ap.add_argument("--platform", default="auto", choices=("auto", "cpu"))
    ap.add_argument("--emit-cost-model", metavar="PATH",
                    help="write the machine-readable analytic cost model "
                         "(FLOPs + HBM bytes per pipeline/stage/bucket, "
                         "production configs, eval_shape only) the "
                         "serving pipelines load for live roofline "
                         "attribution, then exit; the committed copy is "
                         "data/cost_model.json")
    ap.add_argument("--sdxl", action="store_true",
                    help="with --cost-table: analyze the SDXL-base "
                         "geometry at 1024 instead of SD1.5-512 — the "
                         "SDXL ceiling accounting (VERDICT r5 weak #7). "
                         "Shape-only (jax.eval_shape params), so it "
                         "runs on any backend without the 2.6B init")
    opts = ap.parse_args()  # rejects unknown/typo'd flags
    if opts.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)
    enable_compile_cache()
    if opts.emit_cost_model:
        emit_cost_model(opts.emit_cost_model)
        return
    batch = opts.batch
    if opts.sdxl:
        # Analytic-only path: abstract params via eval_shape (make_jaxpr
        # traces abstractly, so ShapeDtypeStructs suffice) — no init of
        # the 2.6B-param tree, runs in seconds on CPU.
        assert opts.cost_table, "--sdxl is a --cost-table mode"
        from cassmantle_tpu.config import sdxl_config

        xcfg = sdxl_config()
        ucfg = xcfg.models.unet
        model = UNet(ucfg)
        lat_hw = xcfg.sampler.image_size // 8  # 128 at 1024
        lat = jax.ShapeDtypeStruct((batch, lat_hw, lat_hw, 4),
                                   jnp.bfloat16)
        ts = jax.ShapeDtypeStruct((batch,), jnp.int32)
        ctx = jax.ShapeDtypeStruct((batch, 77, ucfg.context_dim),
                                   jnp.bfloat16)
        add = jax.ShapeDtypeStruct((batch, ucfg.addition_embed_dim),
                                   jnp.bfloat16)
        params = jax.eval_shape(
            model.init, jax.random.PRNGKey(0),
            jax.ShapeDtypeStruct((1, lat_hw, lat_hw, 4), jnp.bfloat16),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1, 77, ucfg.context_dim), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, ucfg.addition_embed_dim),
                                 jnp.bfloat16))
        rows, total = cost_table(
            lambda p, l, t, c, a: model.apply(p, l, t, c, a),
            params, lat, ts, ctx, add)
        steps = xcfg.sampler.num_steps
        per_img = total / batch * 2 * steps  # CFG doubles the forwards
        print(f"SDXL-base UNet forward, batch={batch}, "
              f"{xcfg.sampler.image_size}px: {total / 1e12 / batch:.3f} "
              f"analytic TFLOPs/forward (dot/conv)  -> "
              f"{per_img / 1e12:.1f} TF/image at {steps}-step CFG")
        print(f"{'op':22s} {'operand shapes':46s} "
              f"{'count':>5s} {'GFLOP':>9s} {'%':>5s}")
        for r in rows:
            print(f"{r['op']:22s} {r['shapes']:46s} "
                  f"{r['count']:5d} {r['gflops']:9.1f} {r['pct']:5.1f}")
        enc, dec, tot = encoder_decoder_split(
            model, params, lat, ts, ctx, add)
        vae_tf, vae_attn, s_tokens = vae_decode_cost(
            xcfg.models.vae, xcfg.sampler.image_size, batch)
        print_encprop_accounting(
            enc / batch, dec / batch, tot / batch, vae_tf, vae_attn,
            s_tokens, xcfg.sampler)
        return
    cfg = FrameworkConfig()
    ucfg = cfg.models.unet
    model = UNet(ucfg)

    rng = jax.random.PRNGKey(0)
    lat = jax.random.normal(rng, (batch, 64, 64, 4), jnp.bfloat16)
    ts = jnp.full((batch,), 500, jnp.int32)
    ctx = jax.random.normal(rng, (batch, 77, ucfg.context_dim), jnp.bfloat16)

    from cassmantle_tpu.models.weights import init_params_cached
    from cassmantle_tpu.utils.compile_cache import param_cache_path

    params = init_params_cached(
        model, 2, lat[:1], ts[:1], ctx[:1],
        cache_path=param_cache_path("unet", ucfg),
        cast_to="bfloat16")

    step = jax.jit(lambda p, l, t, c: model.apply(p, l, t, c))

    if opts.cost_table:
        if opts.full_pipeline:
            from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

            pipe = Text2ImagePipeline(cfg)
            ids = jnp.zeros((batch, pipe.pad_len), jnp.int32)
            rows, total = cost_table(
                pipe._sample_impl, pipe._params, ids, ids,
                jax.random.PRNGKey(0))
            label = (f"full pipeline (CLIP + "
                     f"{cfg.sampler.num_steps}-step CFG scan + VAE), "
                     f"batch={batch}")
            per_img = total / batch
            extra = (f"  = {per_img / 1e12:.2f} TF/image "
                     f"(UNet-only ceiling math assumed "
                     f"{0.78 * 2 * cfg.sampler.num_steps:.1f})")
        else:
            rows, total = cost_table(
                lambda p, l, t, c: model.apply(p, l, t, c),
                params, lat, ts, ctx)
            label = f"UNet forward, batch={batch}"
            extra = ""
        print(f"{label}: {total / 1e12:.3f} analytic TFLOPs "
              f"(dot/conv){extra}")
        print(f"{'op':22s} {'operand shapes':46s} "
              f"{'count':>5s} {'GFLOP':>9s} {'%':>5s}")
        for r in rows:
            print(f"{r['op']:22s} {r['shapes']:46s} "
                  f"{r['count']:5d} {r['gflops']:9.1f} {r['pct']:5.1f}")
        if not opts.full_pipeline:
            enc, dec, tot = encoder_decoder_split(model, params, lat, ts,
                                                  ctx)
            vae_tf, vae_attn, s_tokens = vae_decode_cost(
                cfg.models.vae, cfg.sampler.image_size, batch)
            print_encprop_accounting(
                enc / batch, dec / batch, tot / batch, vae_tf, vae_attn,
                s_tokens, cfg.sampler)
        return

    lowered = step.lower(params, lat, ts, ctx)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = ca.get("flops", 0.0)
    bytes_ = ca.get("bytes accessed", 0.0)

    if opts.dump_hlo:
        # the backend-optimized module: what the TPU actually runs —
        # fusion boundaries, layouts, pad/transpose insertions. Big
        # (tens of MB for the full UNet), hence opt-in.
        path = os.path.join(REPO_ROOT, "UNET_HLO.txt")
        with open(path, "w") as f:
            f.write(compiled.as_text())
        print(f"optimized HLO -> {path}")

    dt = timeit(step, params, lat, ts, ctx)
    print(f"batch={batch} step={dt*1e3:.2f} ms  "
          f"flops={flops/1e12:.3f} TF  -> {flops/dt/1e12:.1f} TFLOP/s  "
          f"bytes={bytes_/1e9:.2f} GB -> {bytes_/dt/1e9:.0f} GB/s")

    # flash vs XLA attention A/B per UNet resolution — self-attn AND the
    # S_k=77 cross-attn site (ragged-KV flash: ops/flash_attention.py::
    # flash_cross_attention). Rows whose shape a kernel won't take fall
    # back to the XLA path inside the dispatcher — label them so the
    # A/B can't lie.
    from cassmantle_tpu.ops.flash_attention import (
        flash_attention_ok,
        flash_cross_ok,
    )

    for (s, heads, d) in [(4096, 8, 40), (1024, 8, 80), (256, 8, 160),
                          (64, 8, 160)]:
        q = jax.random.normal(rng, (batch, s, heads, d), jnp.bfloat16)
        fa = jax.jit(lambda q, k, v: attn_mod.multi_head_attention(
            q, k, v, use_flash=True))
        xa = jax.jit(lambda q, k, v: attn_mod.multi_head_attention(
            q, k, v, use_flash=False))
        flabel = "flash" if flash_attention_ok(q, q) else "xla-fallback"
        tf_ = timeit(fa, q, q, q)
        tx = timeit(xa, q, q, q)
        # cross-attn: kv len 77 (flash_cross vs XLA)
        k77 = jax.random.normal(rng, (batch, 77, heads, d), jnp.bfloat16)
        clabel = ("flash-cross" if flash_cross_ok(q, k77)
                  else "xla-fallback")
        tfc = timeit(fa, q, k77, k77)
        txc = timeit(xa, q, k77, k77)
        print(f"S={s:5d} D={d:3d}: {flabel}={tf_*1e6:8.1f} us  "
              f"xla={tx*1e6:8.1f} us  cross77({clabel})={tfc*1e6:8.1f} us"
              f"  cross77(xla)={txc*1e6:8.1f} us")


if __name__ == "__main__":
    main()
