"""Bench regression sentinel: diff a fresh BENCH_SUITE.json against the
committed baseline with noise-aware tolerances (ISSUE 14).

BENCH_SUITE.json has carried the repo's hardware evidence since round 1,
but nothing ever *read* the trajectory — a 20% throughput regression
shipped as a smaller number in a JSON file nobody compared. This tool
makes the trajectory self-auditing:

    python tools/bench_diff.py FRESH.json                 # vs committed
    python tools/bench_diff.py FRESH.json --entry sd15    # one entry
    python tools/bench_diff.py run.json --baseline OLD.json

Per entry the verdict is one of:

- ``regression``   — the value moved beyond tolerance in the BAD
  direction (lower for ``*/sec`` units, higher for ``seconds``);
  **exits nonzero**, naming the entry, and prints the diagnosis
  ``counter_deltas`` the round-14 bench entries record (a drop arriving
  with a ``jit.recompiles`` delta explains itself without a rerun);
- ``improvement``  — beyond tolerance in the good direction;
- ``within_noise`` — inside the tolerance band;
- ``missing``      — the baseline has a measured value the fresh file
  lacks (a vanished entry breaks the trajectory; **exits nonzero**);
- ``error``        — the fresh run failed where the baseline had a
  measurement (**exits nonzero**);
- ``skipped``      — the baseline entry is itself unmeasured (the
  pending-hardware annotations) — nothing to regress against;
- ``new``          — fresh entry with no baseline counterpart.

Tolerances are **carried per entry**: a ``noise_tolerance`` field on
the fresh record, else on the baseline record, else ``--tolerance``
(default 0.10 — run-to-run variance of the bench entries on shared
hosts is well under 10%; entries known noisier carry their own).

``bench.py --suite`` prints this diff table at the end of every run
(non-gating there — the suite's own exit semantics are unchanged), so
the operator reading a fresh suite sees the trend, not just the values.

stdlib-only: importable without jax (CI, laptops, hooks).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

DEFAULT_TOLERANCE = 0.10

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(_REPO, "BENCH_SUITE.json")

#: verdicts that make the CLI exit nonzero
FAILING = ("regression", "error", "missing")


def _value(entry) -> Optional[float]:
    if not isinstance(entry, dict) or "error" in entry:
        return None
    v = entry.get("value")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def higher_is_better(entry: dict) -> bool:
    """Direction from the entry's unit: throughput units (``*/sec``,
    ``*/s``) are higher-better; ``seconds`` (latency/recovery clocks)
    are lower-better. Unknown units default to higher-better."""
    unit = str(entry.get("unit", "")).lower()
    return unit not in ("seconds", "second", "sec", "s", "ms")


def _tolerance(base, fresh, default: float) -> float:
    for entry in (fresh, base):
        if isinstance(entry, dict) and "noise_tolerance" in entry:
            try:
                return float(entry["noise_tolerance"])
            except (TypeError, ValueError):
                pass
    return default


def _delta_diagnosis(base, fresh) -> Dict[str, object]:
    """Diagnosis-counter changes between the two records'
    ``counter_deltas`` blocks: new counters and changed values — the
    round-14 entries record exactly the counters (jit recompiles,
    dispatch hangs, cache misses) that explain a throughput move."""
    base_d = (base or {}).get("counter_deltas") or {}
    fresh_d = (fresh or {}).get("counter_deltas") or {}
    out = {}
    for key in sorted(set(base_d) | set(fresh_d)):
        if base_d.get(key) != fresh_d.get(key):
            out[key] = {"baseline": base_d.get(key),
                        "fresh": fresh_d.get(key)}
    return out


def diff_entry(name: str, base, fresh,
               default_tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """One entry's verdict row (see module docstring for the grammar)."""
    row = {"entry": name, "verdict": "within_noise",
           "tolerance": _tolerance(base, fresh, default_tolerance)}
    base_v = _value(base)
    if base_v is None:
        # the baseline never measured this (pending-hardware rows) or
        # doesn't know it: nothing to regress against
        row["verdict"] = "skipped" if isinstance(base, dict) else "new"
        return row
    row["baseline"] = base_v
    row["unit"] = base.get("unit", "")
    if fresh is None:
        row["verdict"] = "missing"
        return row
    fresh_v = _value(fresh)
    if fresh_v is None:
        row["verdict"] = "error"
        row["error"] = str(fresh.get("error", "no value"))[:200]
        return row
    row["fresh"] = fresh_v
    if base_v == 0:
        return row
    change = (fresh_v - base_v) / abs(base_v)
    row["change_pct"] = round(100.0 * change, 2)
    signed = change if higher_is_better(base) else -change
    if signed < -row["tolerance"]:
        row["verdict"] = "regression"
        diag = _delta_diagnosis(base, fresh)
        if diag:
            row["counter_delta_changes"] = diag
    elif signed > row["tolerance"]:
        row["verdict"] = "improvement"
    return row


def diff_suites(baseline: Dict[str, dict], fresh: Dict[str, dict],
                entries: Optional[List[str]] = None,
                default_tolerance: float = DEFAULT_TOLERANCE
                ) -> List[dict]:
    """Verdict rows for every baseline entry (plus fresh-only ones),
    restricted to ``entries`` when given."""
    names = entries if entries is not None else \
        sorted(set(baseline) | set(fresh))
    return [diff_entry(name, baseline.get(name), fresh.get(name),
                       default_tolerance)
            for name in names]


def format_table(rows: List[dict]) -> str:
    lines = [f"{'entry':22s} {'verdict':13s} {'baseline':>12s} "
             f"{'fresh':>12s} {'change':>8s}  unit"]
    for row in rows:
        base = row.get("baseline")
        fresh = row.get("fresh")
        change = row.get("change_pct")
        lines.append(
            f"{row['entry']:22s} {row['verdict']:13s} "
            f"{('%.4g' % base) if base is not None else '-':>12s} "
            f"{('%.4g' % fresh) if fresh is not None else '-':>12s} "
            f"{('%+.1f%%' % change) if change is not None else '-':>8s}"
            f"  {row.get('unit', '')}")
        for key, delta in (row.get("counter_delta_changes") or {}).items():
            lines.append(f"    diagnosis {key}: "
                         f"{delta['baseline']} -> {delta['fresh']}")
        if row["verdict"] == "error":
            lines.append(f"    error: {row.get('error', '')}")
    return "\n".join(lines)


def _load(path: str) -> Dict[str, dict]:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise SystemExit(f"{path}: expected a JSON object")
    if isinstance(data.get("metric"), str):
        # a single bench.py --entry record (its "metric" field is the
        # metric NAME string; a suite mapping's values are all entry
        # dicts, so a suite can never match this — and a single record
        # may well carry dict-valued fields like counter_deltas).
        # Callers pass --entry NAME to say which suite slot it fills.
        return {"__single__": data}
    return data


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff a fresh BENCH_SUITE.json against the "
                    "committed baseline with noise-aware tolerances")
    ap.add_argument("fresh", help="fresh suite JSON (or a single "
                                  "--entry record)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline suite (default: the committed "
                         "BENCH_SUITE.json)")
    ap.add_argument("--entry", default=None,
                    help="compare only this entry (the fresh file may "
                         "be a single bench.py --entry record)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="default relative noise tolerance (entries "
                         "carrying noise_tolerance override it)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict rows as JSON instead of the "
                         "table")
    opts = ap.parse_args(argv)
    baseline = _load(opts.baseline)
    fresh = _load(opts.fresh)
    if "__single__" in fresh:
        if not opts.entry:
            raise SystemExit(
                "the fresh file is a single bench record; pass "
                "--entry NAME to place it")
        fresh = {opts.entry: fresh["__single__"]}
    entries = [opts.entry] if opts.entry else None
    rows = diff_suites(baseline, fresh, entries=entries,
                       default_tolerance=opts.tolerance)
    if opts.json:
        print(json.dumps(rows, indent=2))
    else:
        print(format_table(rows))
    failing = [r for r in rows if r["verdict"] in FAILING]
    if failing:
        names = ", ".join(f"{r['entry']} ({r['verdict']})"
                          for r in failing)
        print(f"FAIL: {names}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
