"""Emit the committed int8 wordlist embedding table (data/embed_table.bin).

Embeds the FULL game vocabulary (server/assets.load_wordlist: the mined
wordlist plus seed/style tokens) with the real production
EmbeddingScorer, quantizes per ops/embed_table.quantize_rows, and
writes the signature-stamped artifact the runtime scorer memory-maps as
the first rung of the scoring ladder. The signature digests the
wordlist content, the scorer config, and the weights identity — the
same drift discipline as tools/profile_unet.py --emit-cost-model — and
a tier-1 gate (tests/test_embed_table.py) fails whenever the committed
artifact no longer matches what this tool would regenerate.

Usage:  python -m cassmantle_tpu build-embed-table --emit
        python tools/build_embed_table.py --out /tmp/t.bin [--weights D]
            [--seq-len 16] [--batch 1024]

Without --emit/--out it prints the committed artifact's signature vs
the expected one (the check mode the drift gate runs in-process).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def expected_signature(mcfg=None, seq_len: int = 16, weights_dir=None,
                       words=None) -> str:
    """Signature this tool WOULD stamp for the given config — jax-free
    (hashes the wordlist + config + weights identity; embeds nothing),
    so the tier-1 drift gate stays cheap."""
    from cassmantle_tpu.ops import embed_table as et

    if mcfg is None:
        from cassmantle_tpu.config import FrameworkConfig

        mcfg = FrameworkConfig().models.minilm
    if words is None:
        from cassmantle_tpu.server.assets import load_wordlist

        words = load_wordlist()
    norm = [et.normalize_key(w) for w in words]
    return et.table_signature(
        mcfg, seq_len, norm, et.weights_fingerprint(weights_dir))


def emit_embed_table(path=None, cfg=None, weights_dir=None,
                     seq_len: int = 16, scorer=None, batch: int = 1024,
                     words=None, quiet: bool = False):
    """Embed the vocabulary and write the artifact. Returns the header.

    ``scorer`` injection lets tests emit with the tiny test-config
    encoder; production emits build a fresh scorer with the table rung
    OFF (embedding through an armed stale table would launder its rows
    into the new artifact)."""
    import numpy as np

    from cassmantle_tpu.ops import embed_table as et

    if cfg is None:
        from cassmantle_tpu.config import FrameworkConfig

        cfg = FrameworkConfig()
    if path is None:
        path = et.EMBED_TABLE_PATH
    if words is None:
        from cassmantle_tpu.server.assets import load_wordlist

        words = load_wordlist()
    words = [et.normalize_key(w) for w in words]
    if scorer is None:
        from cassmantle_tpu.ops.scorer import EmbeddingScorer

        scorer = EmbeddingScorer(
            cfg.models.minilm, weights_dir=weights_dir, seq_len=seq_len,
            batch_buckets=(batch,), embed_cache_size=0, table=False)
    chunks = []
    t0 = time.time()
    for start in range(0, len(words), batch):
        chunks.append(np.asarray(
            scorer.embed(words[start:start + batch]), dtype=np.float32))
        if not quiet and (start // batch) % 8 == 0:
            done = start + len(chunks[-1])
            rate = done / max(time.time() - t0, 1e-9)
            print(f"  embedded {done}/{len(words)} "
                  f"({rate:.0f} rows/s)", flush=True)
    emb = np.concatenate(chunks, axis=0)
    header = et.write_table(
        path, words, emb, cfg.models.minilm, scorer.seq_len,
        et.weights_fingerprint(weights_dir))
    if not quiet:
        print(f"wrote {path}: {header['count']} x {header['dim']} int8 "
              f"rows, signature {header['signature']} "
              f"({os.path.getsize(path) / 1e6:.1f} MB, "
              f"{time.time() - t0:.0f}s)")
    return header


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--emit", action="store_true",
                    help="write the committed data/embed_table.bin")
    ap.add_argument("--out", default=None,
                    help="write to an explicit path instead")
    ap.add_argument("--weights", default=None,
                    help="weights dir (minilm.safetensors); default "
                         "deterministic random-init")
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch", type=int, default=1024)
    args = ap.parse_args(argv)

    from cassmantle_tpu.ops import embed_table as et

    if not args.emit and args.out is None:
        expect = expected_signature(seq_len=args.seq_len,
                                    weights_dir=args.weights)
        try:
            committed = et.read_header(et.EMBED_TABLE_PATH)["signature"]
        except (OSError, ValueError):
            committed = "<absent>"
        print(f"expected signature:  {expect}")
        print(f"committed signature: {committed}")
        if committed != expect:
            print("DRIFT — rerun with --emit to rebuild")
            return 1
        return 0

    path = args.out or et.EMBED_TABLE_PATH
    emit_embed_table(path=path, weights_dir=args.weights,
                     seq_len=args.seq_len, batch=args.batch)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
