"""Store parity matrix: MemoryStore and the native mantlestore must
agree on the whole command surface.

Replication replay (native REPL verbs + engine/store.ReplicatedStore)
re-executes the leader's command log on followers, and tests routinely
swap MemoryStore for the native store — both only work if the two
backends compute IDENTICAL results for the same command script. One
table-driven script runs against each backend and the full result
traces are compared: strings/TTL, hashes (incl. strtoll-lenient
HINCRBY), sets, wrong-type read/write discipline, and the lock verbs
with the ``:2`` overrun and tombstone-grace hazard taxonomy.

Divergences this matrix found (fixed in this round, pinned here):

- wrong-kind writes used to half-apply on the native store (HSET over
  a string key wrote fields no HGET could see) and ASSERT on
  MemoryStore; both now REPLACE the entry with a fresh one of the new
  kind (TTL cleared);
- wrong-kind reads used to assert on MemoryStore; both now read as a
  missing key;
- HINCRBY on a non-numeric field raised on MemoryStore but parsed a
  leading integer (C strtoll) natively; both are strtoll-lenient now.
"""

import asyncio

import pytest

from cassmantle_tpu.engine import store as store_mod
from cassmantle_tpu.engine.store import LockTimeout, MemoryStore
from cassmantle_tpu.native.client import MantleStore, ensure_built, spawn_server

PORT = 7181

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="module")
def server():
    proc = spawn_server(PORT)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


async def _flush_native():
    c = MantleStore(port=PORT)
    await c.flushall()
    await c.close()


async def run_script(store, hazards):
    """The parity script. Every step appends a comparable record to the
    trace; lock-hazard telemetry lands in ``hazards`` via the patched
    reporter. TTL values are recorded coarsely (sign/zero class) — the
    two backends share a wall clock but not a microsecond."""
    out = []

    # -- strings + TTL -----------------------------------------------------
    await store.set("k", "v1")
    out.append(await store.get("k"))
    out.append(await store.exists("k"))
    out.append(await store.ttl("k"))                 # -1: no expiry
    await store.setex("tk", 0.25, "temp")
    out.append((await store.ttl("tk")) > 0)
    await store.expire("k", 0.25)
    out.append((await store.ttl("k")) > 0)
    await asyncio.sleep(0.35)
    out.append(await store.get("tk"))                # expired -> None
    out.append(await store.ttl("tk"))                # -2: missing
    out.append(await store.exists("k"))              # expired too
    await store.set("k", "v2")                       # rewrite clears TTL
    out.append(await store.ttl("k"))
    await store.delete("k", "never-existed")
    out.append(await store.get("k"))
    out.append(await store.get("missing"))

    # -- hashes ------------------------------------------------------------
    await store.hset("h", "f1", "a")
    await store.hset("h", mapping={"f2": "b", "f3": 3})
    out.append(await store.hget("h", "f1"))
    out.append(await store.hget("h", "nope"))
    out.append(sorted((await store.hgetall("h")).items()))
    await store.hdel("h", "f2", "ghost")
    out.append(sorted((await store.hgetall("h")).items()))
    out.append(await store.hincrby("h", "cnt", 5))
    out.append(await store.hincrby("h", "cnt", -2))
    # strtoll leniency: leading integer parses, garbage counts from 0
    await store.hset("h", "messy", "12abc")
    out.append(await store.hincrby("h", "messy", 5))
    await store.hset("h", "junk", "abc")
    out.append(await store.hincrby("h", "junk", 7))
    out.append(await store.hgetall("missing-hash"))

    # -- sets ----------------------------------------------------------------
    await store.sadd("s", "a", "b")
    await store.sadd("s", "b", "c")
    out.append(sorted(await store.smembers("s")))
    out.append(await store.sismember("s", "a"))
    out.append(await store.sismember("s", "z"))
    await store.srem("s", "a", "ghost")
    out.append(sorted(await store.smembers("s")))
    out.append(sorted(await store.smembers("missing-set")))

    # -- wrong-type discipline ---------------------------------------------
    # reads of another kind behave like a missing key
    out.append(await store.get("h"))                 # string-read of hash
    out.append(await store.hget("s", "f"))           # hash-read of set
    out.append(sorted(await store.smembers("h")))    # set-read of hash
    out.append(await store.hgetall("s"))             # hash-read of set
    # writes of another kind REPLACE the entry (fresh kind, TTL cleared)
    await store.setex("conv", 30.0, "stringval")
    await store.hset("conv", "f", "x")               # string -> hash
    out.append(await store.hget("conv", "f"))
    out.append(await store.get("conv"))
    out.append(await store.ttl("conv"))              # -1: fresh entry
    await store.sadd("conv", "m")                    # hash -> set
    out.append(sorted(await store.smembers("conv")))
    out.append(await store.hget("conv", "f"))
    await store.set("conv", "back")                  # set -> string
    out.append(await store.get("conv"))
    out.append(sorted(await store.smembers("conv")))
    out.append(await store.hincrby("conv", "n", 2))  # string -> hash again
    out.append(await store.get("conv"))

    # -- locks ---------------------------------------------------------------
    async with store.lock("L", timeout=5.0, blocking_timeout=0.2):
        out.append("held")
        try:
            async with store.lock("L", timeout=5.0, blocking_timeout=0.15):
                out.append("double-acquired")
        except LockTimeout:
            out.append("LockTimeout")
    # released: immediate re-acquire works
    async with store.lock("L", timeout=5.0, blocking_timeout=0.2):
        out.append("re-held")

    # overrun: hold past the TTL -> ':2' verdict -> "overrun" hazard
    async with store.lock("over", timeout=0.2, blocking_timeout=0.2):
        await asyncio.sleep(0.35)
    # expired mid-hold AND re-acquired by another holder -> ':0' ->
    # "expired_in_hold" (the tombstone grace is what keeps the lapsed
    # owner's verdict distinguishable on the native store)
    ctx = store.lock("steal", timeout=0.2, blocking_timeout=0.2)
    await ctx.__aenter__()
    await asyncio.sleep(0.3)
    async with store.lock("steal", timeout=5.0, blocking_timeout=0.3):
        out.append("stolen-after-expiry")
        await ctx.__aexit__(None, None, None)
    out.append(sorted(hazards))
    return out


@pytest.mark.asyncio
async def test_memory_and_native_store_agree(server, monkeypatch):
    traces = {}
    for kind in ("memory", "native"):
        hazards = []

        def record(h, name, _bucket=hazards):
            _bucket.append((h, name))

        # both backends report through the one shared reporter (the
        # polled lock protocol itself is shared, engine/store.py)
        monkeypatch.setattr(store_mod, "_report_lock_hazard", record)
        if kind == "memory":
            store = MemoryStore()
            traces[kind] = await run_script(store, hazards)
        else:
            await _flush_native()
            store = MantleStore(port=PORT)
            try:
                traces[kind] = await run_script(store, hazards)
            finally:
                await store.close()
                await _flush_native()
    assert traces["memory"] == traces["native"], (
        "backend divergence:\n  memory: %r\n  native: %r"
        % (traces["memory"], traces["native"])
    )


@pytest.mark.asyncio
async def test_wrong_type_discipline_memory_only():
    """The wrong-type rules hold on MemoryStore alone (the default test
    backend) even where the native arm is skipped for lack of a
    toolchain."""
    store = MemoryStore()
    await store.hset("h", "f", "v")
    assert await store.get("h") is None
    await store.set("h", "now-a-string")
    assert await store.hget("h", "f") is None
    assert await store.get("h") == b"now-a-string"
    assert await store.hincrby("weird", "n", 3) == 3
    await store.hset("weird", "s", "9 lives")
    assert await store.hincrby("weird", "s", 1) == 10
