"""Encoder propagation (Faster Diffusion-style serving acceleration).

The invariants that make the approximation trustworthy (PARITY.md):
1. the UNet's encoder/decoder split is EXACT when the cache comes from
   the same step (decoder_only(cache_of(x)) == full(x)), and the
   decoder-only pass really never reads encoder parameters;
2. the key schedule is exact accounting: full forwards at EXACTLY the
   indices of ``encprop_key_indices``, decoder-only forwards elsewhere,
   for every sampler kind — at stride 1 the loop is bit-identical to
   the plain sampler (on SD1.5 and SDXL shapes);
3. batching a segment's propagated decoder passes into one forward is
   equivalent to running them sequentially (the decoder never reads
   x_t, so the batch rows are computation-independent);
4. the deepcache composition refreshes deep caches only at encoder key
   steps (deep cache keys ⊆ encoder keys).
The only approximation in production is reusing a key step's encoder
features at later steps — everything structural is pinned here, along
with the decode-side kernels (fused VAE ResBlocks, wide-head flash VAE
attention) and the serving wiring (kill switch, staged fallback,
diagnosis counters, jit-sentinel steady state).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import (
    test_config as _tiny_config,
    test_sdxl_config as _tiny_sdxl_config,
)
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.ops.ddim import (
    DDIMSchedule,
    ddim_sample,
    ddim_sample_encprop,
    ddim_update,
    encprop_key_indices,
    make_cfg_denoiser,
    make_cfg_denoiser_encprop,
)
from cassmantle_tpu.ops.samplers import make_encprop_sampler, make_sampler


def _tiny_unet(sdxl: bool = False):
    cfg = (_tiny_sdxl_config() if sdxl else _tiny_config()).models.unet
    model = UNet(cfg)
    lat = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))
    t = jnp.array([5, 9], jnp.int32)
    ctx = jax.random.normal(jax.random.PRNGKey(1), (2, 6, cfg.context_dim))
    add = None
    if cfg.addition_embed_dim:
        add = jax.random.normal(jax.random.PRNGKey(2),
                                (2, cfg.addition_embed_dim))
    params = init_params(model, 0, lat, t, ctx, add)
    return model, params, lat, t, ctx, add


# -- 1. the encoder/decoder split is exact -----------------------------------


@pytest.mark.parametrize("sdxl", [False, True], ids=["sd15", "sdxl"])
def test_decoder_only_exact_with_same_step_cache(sdxl):
    model, params, lat, t, ctx, add = _tiny_unet(sdxl)
    eps_full, cache = model.apply(params, lat, t, ctx, add,
                                  return_skips=True)
    eps_dec = model.apply(params, None, t, ctx, add, skips_cache=cache)
    np.testing.assert_array_equal(np.asarray(eps_dec), np.asarray(eps_full))


def test_decoder_only_skips_encoder_params():
    """The decoder-only pass must not depend on encoder parameters:
    zeroing conv_in AND the mid block changes the full pass but not the
    decoder-only one (the encprop twin of the deepcache test)."""
    model, params, lat, t, ctx, add = _tiny_unet()
    _, cache = model.apply(params, lat, t, ctx, add, return_skips=True)

    import flax

    broken = flax.core.unfreeze(params) if hasattr(flax.core, "unfreeze") \
        else jax.tree_util.tree_map(lambda x: x, params)
    for name in ("conv_in", "mid_res_0"):
        sub = broken["params"][name]
        key = "kernel" if "kernel" in sub else "conv1"
        if key == "conv1":
            sub = sub["conv1"]
            key = "kernel"
        sub[key] = jnp.zeros_like(sub[key])

    dec_ok = model.apply(params, None, t, ctx, add, skips_cache=cache)
    dec_broken = model.apply(broken, None, t, ctx, add, skips_cache=cache)
    np.testing.assert_array_equal(np.asarray(dec_ok),
                                  np.asarray(dec_broken))
    full_ok = model.apply(params, lat, t, ctx, add)
    full_broken = model.apply(broken, lat, t, ctx, add)
    assert not np.allclose(np.asarray(full_ok), np.asarray(full_broken))


def test_combined_return_deep_and_skips():
    """Key steps of the composed deepcache+encprop loop capture BOTH
    caches from one forward, without changing eps."""
    model, params, lat, t, ctx, add = _tiny_unet()
    eps_ref = model.apply(params, lat, t, ctx, add)
    eps, deep, cache = model.apply(params, lat, t, ctx, add,
                                   return_deep=True, return_skips=True)
    np.testing.assert_array_equal(np.asarray(eps), np.asarray(eps_ref))
    eps_shallow = model.apply(params, lat, t, ctx, add, deep)
    np.testing.assert_allclose(np.asarray(eps_shallow), np.asarray(eps_ref),
                               atol=1e-5, rtol=1e-5)
    eps_dec = model.apply(params, None, t, ctx, add, skips_cache=cache)
    np.testing.assert_array_equal(np.asarray(eps_dec), np.asarray(eps_ref))


# -- 2. key-schedule accounting ----------------------------------------------


@pytest.mark.parametrize("n,stride,dense,expect_k", [
    (50, 3, 5, 20),   # the default serving schedule: 60% of steps skipped
    (10, 3, 2, 5),
    (8, 1, 0, 8),     # stride 1 = every step a key
    (8, 8, 0, 1),     # one key, seven propagated
    (6, 2, 6, 6),     # dense prefix covering everything
])
def test_key_schedule_accounting(n, stride, dense, expect_k):
    keys = encprop_key_indices(n, stride, dense)
    assert len(keys) == expect_k
    assert keys[0] == 0                      # step 0 always a key
    assert list(keys[:dense]) == list(range(dense))
    after = [k for k in keys if k >= dense]
    assert after == list(range(dense, n, stride))


@pytest.mark.parametrize("n,stride,dense,deepcache,expect", [
    (50, 3, 5, False, (20, 0, 30)),   # default schedule, pure encprop
    (50, 3, 5, True, (20, 15, 15)),   # composed: 1 shallow per segment
    (8, 4, 0, True, (2, 2, 4)),
    (8, 1, 0, True, (8, 0, 0)),       # stride 1: no shallow, no props
    (10, 3, 2, True, (5, 3, 2)),      # tail segment of 2: key + shallow
])
def test_step_count_accounting(n, stride, dense, deepcache, expect):
    """The (key, shallow, propagated) triple the diagnosis counters
    report: in the composed deepcache+encprop loop the second step of
    every length-≥2 segment is a DeepCache SHALLOW pass (reads x_t),
    not a decoder-only propagated forward — the counters must not
    conflate the two."""
    from cassmantle_tpu.ops.ddim import encprop_step_counts

    assert encprop_step_counts(n, stride, dense, deepcache) == expect
    keys, shallow, props = expect
    assert keys + shallow + props == n


def test_sampler_runs_keys_and_props_exactly_where_scheduled():
    """The engine's executed step types match ``encprop_key_indices``
    EXACTLY: a key denoiser and a (x-independent) prop denoiser with
    distinguishable outputs reproduce a hand-rolled reference loop that
    switches on the key mask — so K encoder forwards for N steps is an
    execution property, not just an index-list property."""
    n, stride, dense = 10, 3, 2
    keys = set(encprop_key_indices(n, stride, dense).tolist())
    schedule = DDIMSchedule.create(n)
    lat = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 8, 4))

    def key_eps(x, t):
        return 0.1 * x + 0.01 * t.astype(jnp.float32)

    def prop_eps_at(t):
        return 0.02 * t.astype(jnp.float32) * jnp.ones(lat.shape)

    out = ddim_sample_encprop(
        lambda x, t: (key_eps(x, t), jnp.float32(0.0)),
        lambda cache, ts: jnp.stack([prop_eps_at(t) for t in ts]),
        lat, schedule, stride=stride, dense_steps=dense)

    x = lat
    for i in range(n):
        t = schedule.timesteps[i]
        eps = key_eps(x, t) if i in keys else prop_eps_at(t)
        x = ddim_update(x, eps, schedule.alpha_bars[i],
                        schedule.alpha_bars_prev[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("kind", ["ddim", "euler", "dpmpp_2m"])
def test_stride1_bitparity_every_sampler_kind(kind):
    """At stride 1 every step is a key step: the encprop loop must be
    bit-identical to the plain sampler for every deterministic kind."""
    lat = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 8, 4))

    def denoise(x, t):
        return 0.1 * x + 0.01 * t.astype(jnp.float32)

    ref = make_sampler(kind, 8)(denoise, lat)
    sample = make_encprop_sampler(kind, 8, stride=1, dense_steps=0)
    out = sample(lambda x, t: (denoise(x, t), jnp.float32(0.0)),
                 lambda cache, ts: jnp.zeros((ts.shape[0],) + lat.shape),
                 lat)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("sdxl", [False, True], ids=["sd15", "sdxl"])
def test_stride1_bitparity_real_unet_shapes(sdxl):
    """Stride-1 bit-parity against the plain CFG sampler with the REAL
    (tiny) UNet on both SD1.5 and SDXL geometries — the tier-1
    acceptance bar at the sampler level (the whole-pipeline uint8 pin
    is test_pipeline_stride1_parity_and_quality_gate below)."""
    model, params, lat_b2, t, ctx, add = _tiny_unet(sdxl)
    lat = jax.random.normal(jax.random.PRNGKey(7), (1, 8, 8, 4))
    cond = ctx[:1]
    uncond = jnp.zeros_like(cond)
    add_c = add[:1] if add is not None else None
    uadd = jnp.zeros_like(add_c) if add_c is not None else None
    schedule = DDIMSchedule.create(4)

    denoise = make_cfg_denoiser(model.apply, params, cond, uncond, 5.0,
                                addition_embeds=add_c,
                                uncond_addition_embeds=uadd)
    ref = ddim_sample(denoise, lat, schedule)

    dk, dp, dsh = make_cfg_denoiser_encprop(
        model.apply, params, cond, uncond, 5.0,
        addition_embeds=add_c, uncond_addition_embeds=uadd)
    assert dsh is None
    out = ddim_sample_encprop(dk, dp, lat, schedule, stride=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# -- 3. batched propagated decoder == sequential -----------------------------



# -- 4. deepcache composition ------------------------------------------------


def test_deepcache_composition_structure():
    """Composed loop: full forward at key steps (deep cache refreshes
    there and ONLY there — deep keys ⊆ encoder keys), a deepcache
    shallow pass at the second step of each segment, decoder-only
    propagation after — pinned against a hand-rolled reference with
    distinguishable step types."""
    n, stride = 8, 4
    keys = set(encprop_key_indices(n, stride, 0).tolist())
    schedule = DDIMSchedule.create(n)
    lat = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 8, 4))

    def key_eps(x, t):
        return 0.1 * x + 0.01 * t.astype(jnp.float32)

    def shallow_eps(x, t):
        return 0.05 * x + 0.03 * t.astype(jnp.float32)

    def prop_eps_at(t):
        return 0.02 * t.astype(jnp.float32) * jnp.ones(lat.shape)

    sample = make_encprop_sampler("ddim", n, stride, 0, deepcache=True)
    out = sample(
        lambda x, t: (key_eps(x, t), jnp.float32(0.0), jnp.float32(0.0)),
        lambda cache, ts: jnp.stack([prop_eps_at(t) for t in ts]),
        lat,
        denoise_shallow=lambda x, t, deep: shallow_eps(x, t))

    x = lat
    for i in range(n):
        t = schedule.timesteps[i]
        if i in keys:
            eps = key_eps(x, t)
        elif (i - 1) in keys:           # second step of a segment
            eps = shallow_eps(x, t)
        else:
            eps = prop_eps_at(t)
        x = ddim_update(x, eps, schedule.alpha_bars[i],
                        schedule.alpha_bars_prev[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               atol=1e-6, rtol=1e-6)



# -- 5. serving wiring -------------------------------------------------------


@pytest.fixture(scope="module")
def plain_pipe():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    return Text2ImagePipeline(_tiny_config())


def _encprop_cfg(stride=1, dense=0, **sampler_kw):
    cfg = _tiny_config()
    return cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, encprop=True, encprop_stride=stride,
        encprop_dense_steps=dense, **sampler_kw))


def test_pipeline_stride1_parity_and_quality_gate(plain_pipe):
    """Tier-1 acceptance: stride-1 encprop uint8 output is bit-identical
    to the plain sampler, and the eval/clip_parity.py encprop gate
    reports exact parity passing the pinned floor (similarity of
    identical batches is 1.0 regardless of weights, so this pins the
    gate mechanism deterministically even on random init)."""
    from cassmantle_tpu.eval.clip_parity import (
        ClipSimilarityHarness,
        ENCPROP_IMAGE_SIM_FLOOR,
        encprop_quality_report,
    )
    from cassmantle_tpu.models.clip_vision import ClipVisionConfig
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    prompts = ["a quiet harbor at dawn"]
    enc = Text2ImagePipeline(_encprop_cfg(stride=1),
                             share_params_with=plain_pipe)
    a = plain_pipe.generate(prompts, seed=3)
    b = enc.generate(prompts, seed=3)
    np.testing.assert_array_equal(a, b)

    tiny_cfg = _tiny_config().models.clip_text
    harness = ClipSimilarityHarness(
        text_cfg=tiny_cfg,
        vision_cfg=ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4,
            projection_dim=64),
        pad_len=16)
    report = encprop_quality_report(harness, b, a, prompts)
    assert report["exact"] is True
    assert report["image_sim_mean"] >= ENCPROP_IMAGE_SIM_FLOOR
    assert report["passes_floor"] is True
    assert report["gate_enforced"] is False  # random init: advisory only





def test_warmed_encprop_loop_never_recompiles(plain_pipe):
    """Jit sentinel pinned on the warmed encprop serving loop: the
    key→propagated transition is internal scan structure, so a second
    same-bucket generate must hit the jit cache with ZERO new
    compiles."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils import jit_sentinel

    enc = Text2ImagePipeline(_encprop_cfg(stride=2, dense=0),
                             share_params_with=plain_pipe)
    enc.generate(["a quiet harbor at dawn"], seed=5)      # warmup compile
    with jit_sentinel.no_new_compiles():
        enc.generate(["a stormy night at sea"], seed=6)


def test_rejections():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    with pytest.raises(AssertionError, match="eta"):
        Text2ImagePipeline(_encprop_cfg(eta=0.5))
    with pytest.raises(AssertionError, match="stride"):
        Text2ImagePipeline(_encprop_cfg(stride=0))
    with pytest.raises(AssertionError, match="deepcache"):
        Text2ImagePipeline(_encprop_cfg(kind="euler", deepcache=True,
                                        num_steps=4))


def test_img2img_rejects_encprop(plain_pipe):
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    enc = Text2ImagePipeline(_encprop_cfg(stride=2),
                             share_params_with=plain_pipe)
    imgs = np.zeros((1, 64, 64, 3), dtype=np.uint8)
    with pytest.raises(NotImplementedError, match="encoder propagation"):
        enc.generate_img2img(imgs, ["a sketch"], strength=0.5)


def test_staged_serving_falls_back_with_encprop(plain_pipe):
    """Staged denoise slots cannot replay the key/propagated segment
    structure — an encprop config must keep the monolithic dispatch."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _encprop_cfg(stride=2)
    cfg = cfg.replace(serving=dataclasses.replace(
        cfg.serving, staged_serving=True))
    pipe = Text2ImagePipeline(cfg, share_params_with=plain_pipe)
    assert pipe._staged_enabled() is False


# -- 6. decode-side kernels --------------------------------------------------


def test_fused_vae_resblocks_numeric_parity():
    """VAEConfig.fused_conv routes every GN→SiLU→conv3x3 pair through
    the fused Pallas kernel (interpret mode on CPU — the real kernel)
    with an IDENTICAL param tree; decoder and encoder outputs must
    match the naive path."""
    from cassmantle_tpu.models.vae import VAEDecoder, VAEEncoder

    cfg = _tiny_config().models.vae
    fused_cfg = dataclasses.replace(cfg, fused_conv=True)
    assert fused_cfg.arch() == cfg.arch()

    lat = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))
    dec = VAEDecoder(cfg)
    params = init_params(dec, 3, lat)
    a = dec.apply(params, lat)
    b = VAEDecoder(fused_cfg).apply(params, lat)      # same tree
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=2e-5, rtol=2e-5)

    img = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 3))
    enc = VAEEncoder(cfg)
    eparams = init_params(enc, 4, img, jax.random.PRNGKey(2))
    ea = enc.apply(eparams, img, jax.random.PRNGKey(3))
    eb = VAEEncoder(dataclasses.replace(cfg, fused_conv=True)).apply(
        eparams, img, jax.random.PRNGKey(3))
    np.testing.assert_allclose(np.asarray(ea), np.asarray(eb),
                               atol=2e-5, rtol=2e-5)


def test_fused_vae_kill_switch(monkeypatch):
    """CASSMANTLE_NO_FUSED_CONV covers the VAE sites too (one switch for
    every fused-conv site, UNet and VAE alike)."""
    from cassmantle_tpu.models.vae import VAEDecoder

    cfg = dataclasses.replace(_tiny_config().models.vae, fused_conv=True)
    lat = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 8, 4))
    dec = VAEDecoder(cfg)
    params = init_params(dec, 3, lat)
    monkeypatch.setenv("CASSMANTLE_NO_FUSED_CONV", "1")
    a = dec.apply(params, lat)
    monkeypatch.delenv("CASSMANTLE_NO_FUSED_CONV")
    b = dec.apply(params, lat)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=2e-5, rtol=2e-5)


def test_flash_vae_attention_parity_and_gate():
    """The VAE mid block's single-head, full-channel-width attention
    (D past the main flash kernel's head bound) dispatches the
    wide-head 512-block variant; numeric parity vs the XLA path, and
    the gate must not shadow the main kernel's shapes."""
    from cassmantle_tpu.ops.attention import multi_head_attention
    from cassmantle_tpu.ops.flash_attention import (
        flash_attention_ok,
        flash_wide_ok,
    )

    q = jax.random.normal(jax.random.PRNGKey(1), (1, 512, 1, 320),
                          jnp.float32)
    assert flash_wide_ok(q, q) and not flash_attention_ok(q, q)
    ref = multi_head_attention(q, q, q, use_flash=False)
    out = multi_head_attention(q, q, q, use_flash=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    # narrow heads stay on the main kernel's path; ragged S stays XLA
    q_narrow = jnp.zeros((1, 1024, 1, 64))
    assert not flash_wide_ok(q_narrow, q_narrow)
    q_ragged = jnp.zeros((1, 500, 1, 320))
    assert not flash_wide_ok(q_ragged, q_ragged)

