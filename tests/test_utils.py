import numpy as np

from cassmantle_tpu.utils.codec import decode_jpeg, encode_jpeg, image_to_base64
from cassmantle_tpu.utils.text import (
    detokenize,
    format_clock,
    is_wordlike,
    tokenize_words,
)


def test_tokenize_roundtrip():
    text = "A lone lighthouse, battered by storms, glows faintly."
    tokens = tokenize_words(text)
    assert "lighthouse" in tokens and "," in tokens
    assert detokenize(tokens) == text


def test_tokenize_contractions():
    tokens = tokenize_words("It wasn't the captain's fault.")
    assert "wasn't" in tokens
    assert "captain's" in tokens


def test_token_indices_stable():
    tokens = tokenize_words("red fox, red sky")
    assert tokens == ["red", "fox", ",", "red", "sky"]
    # duplicate words keep distinct indices (fixes reference utils.py:102
    # first-occurrence bug noted in SURVEY.md §2 #9)
    assert tokens.index("red") == 0 and tokens[3] == "red"


def test_format_clock():
    assert format_clock(899) == "14:59"
    assert format_clock(0) == "00:00"
    assert format_clock(-3) == "00:00"


def test_is_wordlike():
    assert is_wordlike("storm")
    assert not is_wordlike(",")
    assert not is_wordlike("")


def test_jpeg_roundtrip():
    # smooth gradient: JPEG should round-trip it nearly losslessly
    y, x = np.mgrid[0:64, 0:64]
    img = np.stack([x * 4, y * 4, (x + y) * 2], axis=-1).astype(np.uint8)
    data = encode_jpeg(img, quality=95)
    back = decode_jpeg(data)
    assert back.shape == (64, 64, 3)
    assert back.dtype == np.uint8
    assert np.abs(back.astype(int) - img.astype(int)).mean() < 8


def test_base64():
    img = np.zeros((8, 8, 3), dtype=np.uint8)
    s = image_to_base64(img)
    assert isinstance(s, str) and len(s) > 0
