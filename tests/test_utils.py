import numpy as np

from cassmantle_tpu.utils.codec import decode_jpeg, encode_jpeg, image_to_base64
from cassmantle_tpu.utils.text import (
    detokenize,
    format_clock,
    is_wordlike,
    tokenize_words,
)


def test_tokenize_roundtrip():
    text = "A lone lighthouse, battered by storms, glows faintly."
    tokens = tokenize_words(text)
    assert "lighthouse" in tokens and "," in tokens
    assert detokenize(tokens) == text


def test_tokenize_contractions():
    tokens = tokenize_words("It wasn't the captain's fault.")
    assert "wasn't" in tokens
    assert "captain's" in tokens


def test_token_indices_stable():
    tokens = tokenize_words("red fox, red sky")
    assert tokens == ["red", "fox", ",", "red", "sky"]
    # duplicate words keep distinct indices (fixes reference utils.py:102
    # first-occurrence bug noted in SURVEY.md §2 #9)
    assert tokens.index("red") == 0 and tokens[3] == "red"


def test_format_clock():
    assert format_clock(899) == "14:59"
    assert format_clock(0) == "00:00"
    assert format_clock(-3) == "00:00"


def test_is_wordlike():
    assert is_wordlike("storm")
    assert not is_wordlike(",")
    assert not is_wordlike("")


def test_jpeg_roundtrip():
    # smooth gradient: JPEG should round-trip it nearly losslessly
    y, x = np.mgrid[0:64, 0:64]
    img = np.stack([x * 4, y * 4, (x + y) * 2], axis=-1).astype(np.uint8)
    data = encode_jpeg(img, quality=95)
    back = decode_jpeg(data)
    assert back.shape == (64, 64, 3)
    assert back.dtype == np.uint8
    assert np.abs(back.astype(int) - img.astype(int)).mean() < 8


def test_base64():
    img = np.zeros((8, 8, 3), dtype=np.uint8)
    s = image_to_base64(img)
    assert isinstance(s, str) and len(s) > 0


def test_breaker_and_watchdog_metrics_names():
    """The supervision subsystem's counters/gauges land in the process
    metrics registry under stable names — what DEPLOY.md's degraded-mode
    runbook tells operators to alert on."""
    from cassmantle_tpu.serving.supervisor import ServingSupervisor
    from cassmantle_tpu.utils.circuit import CircuitBreaker
    from cassmantle_tpu.utils.logging import metrics

    b = CircuitBreaker("mtest", failure_threshold=1, reset_timeout_s=0.0)
    b.record_failure()          # closed -> open
    assert b.state == "half_open"   # reset_timeout 0: immediate probe
    assert b.allow()
    b.record_success()          # half_open -> closed
    b.allow()
    snap = metrics.snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    assert counters["circuit.mtest.failures"] >= 1
    assert counters["circuit.mtest.opened"] >= 1
    assert counters["circuit.mtest.half_open"] >= 1
    assert counters["circuit.mtest.closed"] >= 1
    assert "circuit.mtest.state" in gauges

    sup = ServingSupervisor(degraded_cooldown_s=0.0)
    sup.note_dispatch_overrun("mtest-queue")
    sup.status()
    snap = metrics.snapshot()
    assert snap["counters"]["supervisor.dispatch_overruns"] >= 1
    assert "supervisor.degraded" in snap["gauges"]
    # every transition above also landed in the flight recorder
    # (ISSUE 3), and span/event volume self-reports
    assert snap["counters"]["obs.events"] >= 1


async def test_queue_instrumentation_metric_names():
    """The batch-shape instrumentation lands under stable names —
    what docs/OBSERVABILITY.md's catalog (and tools/check_metrics.py)
    pin for operators."""
    from cassmantle_tpu.serving.queue import BatchingQueue
    from cassmantle_tpu.utils.logging import metrics

    q = BatchingQueue(lambda items: list(items), max_delay_ms=1,
                      name="pinq")
    await q.submit(1)
    await q.stop()
    snap = metrics.snapshot()
    for counter in ("pinq.batches", "pinq.items"):
        assert snap["counters"][counter] >= 1
    for hist in ("pinq.batch_s", "pinq.queue_wait_s", "pinq.batch_size"):
        assert snap["timings"][hist]["count"] >= 1
    for gauge in ("pinq.depth", "pinq.coalesce_wait_s"):
        assert gauge in snap["gauges"]


def test_retry_give_up_on_aborts_immediately():
    """retry_async(give_up_on=...) re-raises without further attempts —
    the breaker fast-fail contract (utils/circuit.py)."""
    import asyncio

    from cassmantle_tpu.utils.retry import retry_async

    calls = []

    class Abort(Exception):
        pass

    async def op():
        calls.append(1)
        raise Abort()

    async def run():
        with np.testing.assert_raises(Abort):
            await retry_async(op, max_retries=5, give_up_on=(Abort,),
                              backoff=lambda a: 0.0)

    asyncio.run(run())
    assert len(calls) == 1
