"""Execute the frontend JavaScript — not just grep it (VERDICT r4 #5).

This container ships no JS runtime (node/bun/deno all absent), so these
tests are node-gated: they skip cleanly here and run as one command on
any provisioned host with node >= 18 (``python -m pytest
tests/test_js_runtime.py``) — part of the provisioned-host drill
(docs/DEPLOY.md). What runs when node exists:

- ``run_spell.js``: the real static/spell.js in a real JS engine over
  golden cases, compared RESULT-FOR-RESULT against the Python mirror
  (utils/spell.py) on the served wordlist — executable lockstep, where
  test_spell_rule_parity only compares rule-set text;
- ``run_app.js``: the real static/app.js against a REAL running --fake
  server through a minimal DOM shim (tests/js/dom_shim.js): boot,
  consent, the per-word spellcheck hold + escape hatch, score
  feedback, the win banner via exact answers (computed here from the
  deterministic fake backend), and the ws-reset refetch.

Reference surface being covered: script.js:362-442 (guess flow),
typo.js:622/755 (check/suggest).
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

NODE = shutil.which("node")
pytestmark = pytest.mark.skipif(
    NODE is None, reason="no JS runtime on this host (node absent)")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JS = os.path.join(REPO, "tests", "js")
WORDLIST = os.path.join(REPO, "data", "wordlist.txt")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def fake_server():
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "cassmantle_tpu.server.app", "--fake",
         "--port", str(port), "--round-seconds", "300"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 60
    while True:
        try:
            urllib.request.urlopen(base + "/healthz", timeout=2)
            break
        except Exception:
            if time.time() > deadline or proc.poll() is not None:
                out = proc.stdout.read().decode("utf-8", "ignore")[-2000:]
                raise RuntimeError(f"fake server failed to boot: {out}")
            time.sleep(0.3)
    yield base
    proc.terminate()
    proc.wait(timeout=10)


def test_spell_js_matches_python_mirror():
    """static/spell.js and utils/spell.py must agree check() AND the
    ranked suggest() list on real-wordlist golden cases — including the
    false-hold regression words."""
    from cassmantle_tpu.server.assets import load_wordlist
    from cassmantle_tpu.utils.spell import Spell

    cases = [
        "stormy", "lighthouse", "lighthosue", "stomry", "zephyr",
        "zephyrs", "unfolded", "happier", "wolves", "brightness",
        "xqzzt", "quickyl", "shimmering", "brambles", "a1bad",
    ]
    proc = subprocess.run(
        [NODE, os.path.join(JS, "run_spell.js"), WORDLIST],
        input=json.dumps(cases), capture_output=True, text=True,
        timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    js = json.loads(proc.stdout)
    py = Spell(load_wordlist())
    for word in cases:
        assert js[word]["check"] == py.check(word), word
        assert js[word]["suggest"] == py.suggest(word, 3), word


def _fake_round_answers(base: str) -> dict:
    """{maskIdx: exact word} for the CURRENT fake round — reconstructed
    from the deterministic template backend: a fresh story's text is
    template_text(title), tokenized the way the engine tokenizes."""
    from cassmantle_tpu.engine.content import template_text
    from cassmantle_tpu.utils.text import tokenize_words

    req = urllib.request.Request(base + "/fetch/contents")
    with urllib.request.urlopen(req, timeout=10) as res:
        data = json.loads(res.read())
    title = data["story"]["title"]
    tokens = tokenize_words(template_text(title))
    served = data["prompt"]["tokens"]
    assert len(tokens) == len(served), (tokens, served)
    return {str(m): tokens[m]
            for m in data["prompt"]["masks"] if m >= 0}


def test_app_js_flows_against_real_server(fake_server):
    answers = _fake_round_answers(fake_server)
    assert answers, "fake round produced no masks"
    proc = subprocess.run(
        [NODE, os.path.join(JS, "run_app.js"), fake_server,
         json.dumps(answers)],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, (proc.stderr[-2000:], proc.stdout[-500:])
    results = json.loads(proc.stdout)
    for label in ("boot: game visible", "consent: dismissed",
                  "hold: flagged once", "hold: resubmit goes through",
                  "score: feedback rendered", "win: banner shown",
                  "reset: banner cleared"):
        assert results.get(label), (label, results)
