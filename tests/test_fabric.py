"""Room fabric unit + acceptance tests (fast tier).

Covers the three legs of ISSUE 8: rooms + routing (directory hashing,
namespaced store isolation, room-scoped HTTP routes, cross-worker 307),
store replication (the leader-kill fault injection: killing the leader
mid-round promotes a follower within the lease TTL and the room's
state — prompt, image, scores — survives bit-for-bit), and membership
(staleness filtering, `/readyz` fabric block). The multi-process load
harness lives in tests/test_fabric_cluster.py (slow tier); a
small-N/M CPU smoke of the same harness runs here.
"""

import asyncio
import dataclasses
import json
import time

import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import MemoryStore, ReplicatedStore
from cassmantle_tpu.fabric.directory import RoomDirectory, stable_hash
from cassmantle_tpu.fabric.membership import ClusterMembership
from cassmantle_tpu.fabric.rooms import NamespacedStore, RoomFabric, room_ids
from cassmantle_tpu.native.client import MantleStore, ensure_built, spawn_server

needs_native = pytest.mark.skipif(
    ensure_built() is None, reason="no C++ toolchain"
)


def make_cfg(num_rooms=2, time_per_prompt=30.0):
    cfg = _tiny_config()
    return cfg.replace(
        game=dataclasses.replace(
            cfg.game, time_per_prompt=time_per_prompt,
            rate_limit_default=1e6, rate_limit_api=1e6),
        fabric=dataclasses.replace(cfg.fabric, num_rooms=num_rooms),
    )


def make_fabric(cfg, store=None, worker_id="worker-0", advertise=""):
    store = store or MemoryStore()

    def factory(room, room_store):
        return Game(cfg, room_store, FakeContentBackend(image_size=32),
                    hash_embed, hash_similarity)

    return RoomFabric(cfg, store, factory, worker_id=worker_id,
                      advertise_addr=advertise, start_timers=False,
                      heartbeat=False)


# -- directory ---------------------------------------------------------------

def test_session_to_room_is_stable_and_process_independent():
    rooms = [f"r{i}" for i in range(8)]
    d1 = RoomDirectory(rooms, workers=["w0"])
    d2 = RoomDirectory(rooms, workers=["w0"])  # a "second process"
    hits = set()
    for i in range(200):
        sid = f"session-{i}"
        room = d1.room_for_session(sid)
        assert room == d1.room_for_session(sid)   # per-request stability
        assert room == d2.room_for_session(sid)   # cross-worker agreement
        hits.add(room)
    assert len(hits) == 8  # 200 sessions spread over all rooms


def test_ring_moves_are_minimal_on_membership_change():
    rooms = [f"r{i}" for i in range(32)]
    d = RoomDirectory(rooms, workers=["a", "b", "c"])
    before = d.placement()
    moves = d.set_workers(["a", "b", "c", "d"])
    # only rooms that moved TO the new worker move; no shuffling among
    # the survivors (the consistent-hash property)
    assert moves
    for room, (old, new) in moves.items():
        assert new == "d"
        assert before[room] == old
    assert len(moves) < len(rooms) // 2
    # removing d sends exactly its rooms back to their previous owners
    moves_back = d.set_workers(["a", "b", "c"])
    assert set(moves_back) == set(moves)
    for room, (old, new) in moves_back.items():
        assert old == "d" and new == before[room]
    assert d.placement() == before


def test_worker_for_room_empty_ring_is_none():
    d = RoomDirectory(["r0"])
    assert d.worker_for_room("r0") is None
    assert d.rooms_owned_by("nobody") == []


# -- namespaced store --------------------------------------------------------

@pytest.mark.asyncio
async def test_namespaced_store_isolates_rooms():
    base = MemoryStore()
    a = NamespacedStore(base, "")             # the default room: legacy keys
    b = NamespacedStore(base, "room:r1:")
    await a.set("prompt", "A")
    await b.set("prompt", "B")
    assert await a.get("prompt") == b"A"
    assert await b.get("prompt") == b"B"
    assert await base.get("prompt") == b"A"   # default == un-prefixed
    assert await base.get("room:r1:prompt") == b"B"
    await a.hset("h", "f", "1")
    await b.hincrby("h", "f", 5)
    assert await a.hget("h", "f") == b"1"
    assert await b.hget("h", "f") == b"5"
    # locks are room-scoped: both rooms hold "startup_lock" at once
    async with a.lock("startup_lock", timeout=5.0, blocking_timeout=0.2):
        async with b.lock("startup_lock", timeout=5.0,
                          blocking_timeout=0.2):
            pass
    # close is a no-op on the view — the shared store stays usable
    await a.close()
    assert await b.get("prompt") == b"B"


# -- room isolation (acceptance) ---------------------------------------------

@pytest.mark.asyncio
async def test_two_rooms_one_worker_hold_independent_state():
    """N-room isolation acceptance: two rooms on one worker hold
    different prompts/images and independent clocks; a session hashes
    to the same room across requests."""
    cfg = make_cfg(num_rooms=2, time_per_prompt=30.0)
    fabric = make_fabric(cfg)
    game_a = await fabric.game_for(fabric.default_room)
    game_b = await fabric.game_for("room-1")
    try:
        prompt_a = await game_a.rounds.fetch_current_prompt()
        prompt_b = await game_b.rounds.fetch_current_prompt()
        assert prompt_a["tokens"] != prompt_b["tokens"]
        image_a = await game_a.rounds.fetch_current_image_bytes()
        image_b = await game_b.rounds.fetch_current_image_bytes()
        assert image_a != image_b
        # independent clocks: restarting room B's countdown leaves room
        # A's remaining time where it was
        await game_a.rounds.start_countdown()
        await asyncio.sleep(0.3)
        await game_b.rounds.start_countdown()
        rem_a = await game_a.rounds.remaining()
        rem_b = await game_b.rounds.remaining()
        assert rem_b > rem_a
        # scores are per (session, room): the same session id wins in
        # room A without touching its room-B state
        session = "both-rooms"
        await game_a.init_client(session)
        await game_b.init_client(session)
        masks_a = prompt_a["masks"]
        answers = {str(m): prompt_a["tokens"][m] for m in masks_a}
        result = await game_a.compute_client_scores(session, answers)
        assert result["won"] == 1
        status_b = await game_b.client_status(session)
        assert status_b["won"] == 0
    finally:
        await fabric.shutdown()


@pytest.mark.asyncio
async def test_http_routes_are_room_scoped():
    from aiohttp.test_utils import TestClient, TestServer

    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg(num_rooms=2)
    fabric = make_fabric(cfg)
    app = create_app(fabric, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        res = await client.get("/init", params={"room": "room-1"})
        data = await res.json()
        assert data["room"] == "room-1"
        res_a = await client.get("/fetch/contents",
                                 params={"room": fabric.default_room,
                                         "session": "s-a"})
        res_b = await client.get("/fetch/contents",
                                 params={"room": "room-1",
                                         "session": "s-b"})
        tokens_a = (await res_a.json())["prompt"]["tokens"]
        tokens_b = (await res_b.json())["prompt"]["tokens"]
        assert tokens_a != tokens_b
        # un-roomed requests resolve deterministically by session hash
        room = fabric.directory.room_for_session("sticky")
        res = await client.get("/init", params={"session": "sticky"})
        assert (await res.json())["room"] == room
        # unknown rooms 404 instead of silently minting state
        res = await client.get("/fetch/contents",
                               params={"room": "no-such-room"})
        assert res.status == 404
        # readyz carries the fabric block
        res = await client.get("/readyz")
        block = (await res.json())["fabric"]
        assert block["worker"] == "worker-0"
        assert set(block["rooms"]) == set(room_ids(cfg))
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_foreign_room_redirects_to_owner():
    from aiohttp.test_utils import TestClient, TestServer

    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg(num_rooms=8)
    fabric = make_fabric(cfg, worker_id="me",
                         advertise="http://127.0.0.1:1")
    app = create_app(fabric, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        # a peer joins: announce it in the membership table, rebuild the
        # ring the way the heartbeat loop would
        await fabric.store.hset(
            "fabric:workers", "peer",
            json.dumps({"addr": "http://127.0.0.1:9999", "rooms": 0,
                        "t": time.time()}))
        await fabric.membership.refresh()
        fabric.directory.set_workers(["me", "peer"])
        foreign = [r for r, w in fabric.directory.placement().items()
                   if w == "peer"]
        assert foreign, "8 rooms over 2 workers: peer must own some"
        res = await client.get(
            "/fetch/contents",
            params={"room": foreign[0], "session": "s1"},
            allow_redirects=False)
        assert res.status == 307
        assert res.headers["Location"].startswith("http://127.0.0.1:9999")
        # the Location pins room AND session: cookies are host-scoped,
        # so a cookie-only client must not re-resolve a different room
        # on the owner (redirect ping-pong)
        assert f"room={foreign[0]}" in res.headers["Location"]
        assert "session=s1" in res.headers["Location"]
        # /init follows the same ownership discipline — it must never
        # quietly start a duplicate room engine on a non-owner worker
        res = await client.get("/init", params={"room": foreign[0]},
                               allow_redirects=False)
        assert res.status == 307
        assert foreign[0] not in fabric._games
        # same room with NO advertised owner address: served locally
        # (resilience beats affinity), never an errored redirect
        await fabric.store.hdel("fabric:workers", "peer")
        await fabric.membership.refresh()
        res = await client.get(
            "/fetch/contents",
            params={"room": foreign[0], "session": "s1"},
            allow_redirects=False)
        assert res.status == 200
    finally:
        await client.close()


# -- membership --------------------------------------------------------------

@pytest.mark.asyncio
async def test_membership_filters_stale_workers():
    store = MemoryStore()
    t = [1000.0]
    m1 = ClusterMembership(store, "w1", addr="http://a", ttl_s=5.0,
                           clock=lambda: t[0])
    m2 = ClusterMembership(store, "w2", addr="http://b", ttl_s=5.0,
                           clock=lambda: t[0])
    await m1.heartbeat(room_count=3)
    await m2.heartbeat(room_count=1)
    live = await m1.refresh()
    assert set(live) == {"w1", "w2"}
    assert live["w1"]["rooms"] == 3
    assert m1.addr_of("w2") == "http://b"
    # w2 goes quiet: after the TTL it drops out of the live view
    t[0] += 6.0
    await m1.heartbeat(room_count=3)
    assert set(await m1.refresh()) == {"w1"}
    # graceful leave removes the row immediately
    await m1.leave()
    assert set(await m2.refresh()) == set()


@pytest.mark.asyncio
async def test_fabric_heartbeat_drains_moved_rooms():
    cfg = make_cfg(num_rooms=8)
    fabric = make_fabric(cfg, worker_id="me")
    try:
        for room in room_ids(cfg):
            await fabric.game_for(room)
        assert len(fabric._games) == 8
        # a peer worker appears in membership: the ring rebuild moves
        # some rooms to it and this worker drains them
        live = {"me": {"addr": "", "rooms": 8},
                "peer": {"addr": "http://p", "rooms": 0}}
        moves = fabric._apply_membership(live)
        await fabric._handle_moves(moves)
        moved = [r for r, (old, new) in moves.items() if new == "peer"]
        assert moved
        for room in moved:
            assert room not in fabric._games
        assert set(fabric.owned_rooms()).isdisjoint(moved)
    finally:
        await fabric.shutdown()


# -- replication (acceptance: leader-kill fault injection) -------------------

@needs_native
@pytest.mark.asyncio
async def test_leader_kill_midround_promotes_follower_and_keeps_state():
    """Kill the store leader mid-round: the follower is promoted within
    the lease TTL and the next /fetch/contents + /compute_score level
    reads see the SAME round (no regeneration) and the session's
    earlier scores."""
    leader = spawn_server(7611, repl=True, repl_id="A", lease_ms=500)
    follower = spawn_server(7612, follower=True, repl_id="B", lease_ms=500)
    store = ReplicatedStore([7611, 7612], poll_interval_s=0.02,
                            lease_timeout_s=0.5)
    try:
        await store.start()
        cfg = make_cfg(num_rooms=1, time_per_prompt=60.0)
        game = Game(cfg, store, FakeContentBackend(image_size=32),
                    hash_embed, hash_similarity)
        await game.startup()
        prompt_before = await game.rounds.fetch_current_prompt()
        image_before = await game.rounds.fetch_current_image_bytes()
        session = "p1"
        await game.init_client(session)
        masks = prompt_before["masks"]
        first = {str(masks[0]): prompt_before["tokens"][masks[0]]}
        res = await game.compute_client_scores(session, first)
        assert float(res[str(masks[0])]) == 1.0
        # replication caught up?
        lc, fc = MantleStore(port=7611), MantleStore(port=7612)
        for _ in range(250):
            _, lend, _ = await lc.repl_offset()
            _, _, fapp = await fc.repl_offset()
            if fapp >= lend:
                break
            await asyncio.sleep(0.02)
        assert fapp >= lend, "follower never caught up"
        await lc.close()
        await fc.close()

        leader.kill()
        leader.wait()
        t0 = time.monotonic()
        prompt_after = await game.rounds.fetch_current_prompt()
        failover_s = time.monotonic() - t0
        # no round regeneration: the surviving replica serves the SAME
        # prompt and image bytes
        assert prompt_after == prompt_before
        assert await game.rounds.fetch_current_image_bytes() == image_before
        # no lost scores: the pre-kill win is still on the session
        scores = await game.sessions.fetch_scores(session)
        assert float(scores[str(masks[0])]) == 1.0
        # and new guesses score against the surviving state
        res = await game.compute_client_scores(
            session, {str(masks[1]): prompt_before["tokens"][masks[1]]})
        assert res["won"] == 1
        st = store.status()
        assert st["leader"] == "127.0.0.1:7612"
        assert st["failovers"] == 1
        # promotion is lease-gated: well inside TTL + grace, not minutes
        assert failover_s < 5.0
    finally:
        await store.close()
        for proc in (leader, follower):
            try:
                proc.kill()
                proc.wait()
            except Exception:
                pass


@needs_native
@pytest.mark.asyncio
async def test_follower_rejects_writes_until_promoted():
    leader = spawn_server(7621, repl=True, repl_id="A", lease_ms=400)
    follower = spawn_server(7622, follower=True, repl_id="B", lease_ms=400)
    try:
        f = MantleStore(port=7622)
        with pytest.raises(RuntimeError, match="READONLY"):
            await f.set("x", "y")
        # promotion is refused while the replicated lease is live
        rs = ReplicatedStore([7621, 7622], poll_interval_s=0.02,
                             lease_timeout_s=0.4)
        await rs.start()
        await rs.set("seed", "1")  # ships the lease + data to B
        await asyncio.sleep(0.1)
        assert await f.repl_promote() is False
        holder, remaining = await f.repl_lease()
        assert holder == "A" and remaining > 0
        await rs.close()
        await f.close()
    finally:
        for proc in (leader, follower):
            proc.kill()
            proc.wait()


@pytest.mark.asyncio
async def test_replicated_store_close_lands_under_cancel_swallow():
    """py3.10's wait_for can swallow a cancellation that races the
    inner future's completion (gh-86296): one cancel() then left the
    pump loop alive and close() awaited it forever (reproduced under
    CPU contention, wedging tier-1). close() now re-delivers the
    cancel until the task actually ends — pinned here with a pump stub
    that swallows the first CancelledError the way the race does."""
    rs = ReplicatedStore([7070], pump=False)
    swallowed = [0]

    async def stubborn_pump():
        while True:
            try:
                await asyncio.sleep(3600)
            except asyncio.CancelledError:
                if swallowed[0] == 0:
                    swallowed[0] += 1
                    continue  # the gh-86296 shape: cancellation eaten
                raise

    rs._pump_task = asyncio.get_running_loop().create_task(stubborn_pump())
    await asyncio.wait_for(rs.close(), timeout=5.0)
    assert swallowed[0] == 1
    assert rs._pump_task is None


# -- rooms_load harness (CPU smoke of the bench entry) -----------------------

@needs_native
def test_rooms_load_smoke():
    """The bench harness at tiny N/M: real worker process, real store,
    real HTTP+WS load — sustained guesses land, the clock fans out,
    nothing errors."""
    import bench

    # minimal N/M and a short window: this is tier-1's proof the
    # harness works end-to-end, not a measurement (the measured runs
    # are tests/test_fabric_cluster.py [slow] and the bench entry)
    raw = bench.rooms_load_run(workers=1, rooms=2, sessions=2,
                               seconds=1.5, ws_conns=1,
                               base_port=8491, store_port=7491)
    assert raw["guesses"] > 0
    assert raw["errors"] == 0
    assert raw["ws_ticks"] >= 1
    assert len(raw["latencies"]) == raw["guesses"]


def test_room_ids_and_prefixes():
    from cassmantle_tpu.fabric.rooms import room_prefix

    cfg = make_cfg(num_rooms=3)
    assert room_ids(cfg) == ["lobby", "room-1", "room-2"]
    assert room_prefix("lobby", "lobby") == ""
    assert room_prefix("room-1", "lobby") == "room:room-1:"
    assert stable_hash("x") == stable_hash("x")
