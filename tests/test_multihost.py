"""Multi-host (jax.distributed) dryrun — the SURVEY §5.8 DCN leg.

Spawns 2 REAL OS processes (subprocesses of this test) that join one
coordinator through the production ``maybe_init_distributed`` env
contract, build a single cross-process mesh over 2x4 virtual CPU
devices, and verify an explicit cross-process psum plus a dp train step
(loss + gradient) against the single-host reference. See
cassmantle_tpu/parallel/multihost_dryrun.py for what the children run.
"""

import pytest

from cassmantle_tpu.parallel.multihost_dryrun import (
    _OK_MARKER,
    run_multihost_dryrun,
)


def test_two_process_distributed_join_and_dp_step():
    try:
        out = run_multihost_dryrun(n_procs=2, local_devices=4)
    except RuntimeError as exc:
        # capability gate, not a code failure: some jaxlib builds ship
        # a CPU backend without cross-process collectives ("Multiprocess
        # computations aren't implemented on the CPU backend"). The join
        # + mesh construction still ran (the children get far enough to
        # log the mesh); only the collective execution leg needs the
        # capable backend — same spirit as the node-gated JS skips.
        if "aren't implemented on the CPU backend" in str(exc):
            pytest.skip("installed jaxlib CPU backend lacks "
                        "cross-process collectives")
        raise
    assert _OK_MARKER in out
    assert "8 global devices" in out
