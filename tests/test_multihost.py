"""Multi-host (jax.distributed) dryrun — the SURVEY §5.8 DCN leg.

Spawns 2 REAL OS processes (subprocesses of this test) that join one
coordinator through the production ``maybe_init_distributed`` env
contract, build a single cross-process mesh over 2x4 virtual CPU
devices, and verify an explicit cross-process psum plus a dp train step
(loss + gradient) against the single-host reference. See
cassmantle_tpu/parallel/multihost_dryrun.py for what the children run.
"""

from cassmantle_tpu.parallel.multihost_dryrun import (
    _OK_MARKER,
    run_multihost_dryrun,
)


def test_two_process_distributed_join_and_dp_step():
    out = run_multihost_dryrun(n_procs=2, local_devices=4)
    assert _OK_MARKER in out
    assert "8 global devices" in out
