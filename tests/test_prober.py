"""Canary prober tests (ISSUE 18, fast tier).

The synthetic probe plays the real HTTP surface (/init → /clock tick →
/fetch/contents → /compute_score) against a known-answer probe room,
so these tests pin the properties the canary's verdicts depend on:

- **determinism**: every worker derives the SAME probe round from the
  fixed sentence (cross-worker probes know remote answers a priori),
  and seeding is idempotent;
- **isolation**: probe traffic leaves ZERO player-visible artifacts —
  no game.guesses, no http.init, no store keys outside the
  ``probe:<worker>:`` prefix, no admission-limiter estimate movement,
  and the probe room answers 404 to non-cluster outsiders;
- **verdicts**: a healthy worker probes ok; a dead one fails within
  the single probe that observed it, counts ``probe.failures``, lands
  a ``probe.fail`` flight-recorder event, and its trace is retained
  and linked from a ``probe.e2e_s`` bucket exemplar;
- **kill switch**: ``CASSMANTLE_NO_PROBER=1`` leaves zero probe
  artifacts — no background task, no SLO objectives, no probe metrics.
"""

import dataclasses

import pytest
from aiohttp.test_utils import TestServer

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import PROBE_ROOM, Game
from cassmantle_tpu.engine.rounds import IMAGE_KEY
from cassmantle_tpu.engine.store import MemoryStore
from cassmantle_tpu.fabric.rooms import RoomFabric
from cassmantle_tpu.obs.prober import (
    CanaryProber,
    ensure_probe_round,
    probe_answers,
    probe_state,
    prober_disabled,
)
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import tracer
from cassmantle_tpu.utils.logging import metrics


def make_cfg(num_rooms=1, **obs_kw):
    cfg = _tiny_config()
    return cfg.replace(
        game=dataclasses.replace(
            cfg.game, rate_limit_default=1e6, rate_limit_api=1e6,
            time_per_prompt=30.0),
        fabric=dataclasses.replace(
            cfg.fabric, num_rooms=num_rooms, heartbeat_s=30.0),
        obs=dataclasses.replace(
            cfg.obs, slo_eval_interval_s=300.0,
            process_sample_interval_s=60.0,
            cluster_fanout_timeout_s=1.0, probe_interval_s=3600.0,
            probe_timeout_s=2.0, **obs_kw),
    )


def make_game(cfg, store=None, room="default"):
    return Game(cfg, store or MemoryStore(),
                FakeContentBackend(image_size=32),
                hash_embed, hash_similarity, room=room)


def counter_base_total(counters, base):
    """Sum one counter across its label sets (flat snapshot keys are
    ``name`` or ``name{k=v}``)."""
    return sum(v for k, v in counters.items()
               if k.split("{", 1)[0] == base)


async def _serve(cfg, game):
    """A legacy single-game app on a real socket (the for_game wrap —
    probe_game() must derive an isolated engine even from this path)."""
    from cassmantle_tpu.server import app as app_mod

    app = app_mod.create_app(game, cfg, start_timer=False)
    server = TestServer(app)
    await server.start_server()
    fabric = app[app_mod._FABRIC]
    url = f"http://127.0.0.1:{server.port}"
    fabric.membership.addr = url
    return server, fabric, url


# -- determinism + seeding -------------------------------------------------

def test_probe_state_identical_across_workers():
    cfg = make_cfg()
    a, b = make_game(cfg), make_game(cfg)
    sa, sb = probe_state(a), probe_state(b)
    assert sa["masks"] == sb["masks"] and sa["tokens"] == sb["tokens"]
    answers = probe_answers(sa)
    assert answers and all(v not in ("", "*") for v in answers.values())
    # memoized: the derivation runs once per game
    assert probe_state(a) is sa


@pytest.mark.asyncio
async def test_ensure_probe_round_seeds_once_and_keeps_clock_alive():
    cfg = make_cfg()
    store = MemoryStore()
    game = make_game(cfg, store, room=PROBE_ROOM)
    state = await ensure_probe_round(game)
    prompt = await game.rounds.fetch_current_prompt()
    assert prompt["masks"] == state["masks"]
    assert await game.rounds.current_image_version() == 1
    assert await game.rounds.remaining() > 60.0
    # idempotent: a second call re-seeds nothing (the stored image is
    # the SAME object — a rewrite would mint fresh bytes)
    img = await store.hget(IMAGE_KEY, "current")
    await ensure_probe_round(game)
    assert await store.hget(IMAGE_KEY, "current") is img


# -- probe room isolation --------------------------------------------------

@pytest.mark.asyncio
async def test_probe_leaves_zero_player_artifacts():
    """The acceptance bar: a full successful probe moves no player
    surface — store keys stay under the probe prefix, game.guesses and
    http.init stay flat, and the probe room never enters the fabric's
    room map."""
    import aiohttp

    cfg = make_cfg()
    store = MemoryStore()
    game = make_game(cfg, store)
    server, fabric, url = await _serve(cfg, game)
    prober = CanaryProber(fabric, cfg, self_addr=url)
    keys_before = set(store._data)
    before = dict(metrics.snapshot()["counters"])
    try:
        verdict = await prober.probe_once()
        assert verdict["ok"], verdict
        counters = dict(metrics.snapshot()["counters"])
        assert counter_base_total(counters, "probe.ok") == \
            counter_base_total(before, "probe.ok") + 1
        for base in ("game.guesses", "http.init"):
            assert counter_base_total(counters, base) == \
                counter_base_total(before, base), base
        new_keys = set(store._data) - keys_before
        assert new_keys, "the probe room must have seeded"
        assert all(k.startswith(f"probe:{fabric.worker_id}:")
                   for k in new_keys), sorted(new_keys)
        # the probe game is NOT in the room directory/placement map
        assert PROBE_ROOM not in fabric._games
        # probes are always tail-retained: the ok trace is queryable
        assert tracer.get_trace(verdict["trace"])
        # /readyz carries the canary block (advisory)
        async with aiohttp.ClientSession() as http:
            body = await (await http.get(url + "/readyz")).json()
        assert "canary" in body
    finally:
        await prober.close()
        await server.close()


@pytest.mark.asyncio
async def test_probe_room_is_cluster_gated(monkeypatch):
    """?room=__probe__ answers 404 "unknown room" to anyone who is not
    loopback/member/token-bearing — outsiders cannot discover or play
    the probe room. The cluster token opens it (the cross-worker path)."""
    import aiohttp

    from cassmantle_tpu.server import app as app_mod

    cfg = make_cfg()
    server, fabric, url = await _serve(cfg, make_game(cfg))
    await fabric._ensure_cluster_key()
    try:
        # the test client connects from loopback, which is ALSO the
        # advertised member host — disable both ambient trust legs so
        # only the explicit token can open the gate
        monkeypatch.setattr(app_mod, "_is_loopback",
                            lambda request: False)
        monkeypatch.setattr(fabric, "peer_hosts", lambda: set())
        params = {"room": PROBE_ROOM, "session": "x"}
        async with aiohttp.ClientSession() as http:
            res = await http.get(url + "/init", params=params)
            assert res.status == 404
            res = await http.get(
                url + "/init", params=params,
                headers={"X-Cluster-Auth": fabric.cluster_token()})
            assert res.status == 200
    finally:
        await server.close()


@pytest.mark.asyncio
async def test_probe_submits_bypass_admission_estimator():
    """A probe-marked request skips admission.admit and never feeds
    observe_batch — the limiter's estimate and the queue-wait histogram
    must not move (probes measure the system; they must not steer it)."""
    from cassmantle_tpu.serving.overload import AdaptiveLimiter
    from cassmantle_tpu.serving.queue import BatchingQueue

    limiter = AdaptiveLimiter("probeq", target_s=0.5)
    q = BatchingQueue(lambda items: [1.0 for _ in items], max_batch=4,
                      max_delay_ms=1.0, name="probeq",
                      admission=limiter)
    try:
        limit_before = limiter._limit
        with tracer.span("probe.run", root=True) as s:
            tracer.mark_retain("probe", s.ctx)
            s.ctx.marks["probe"] = True
            assert await q.submit("canary") == 1.0
        assert limiter._limit == limit_before
        assert metrics.gauge_values("probeq.admit_limit") == []
        assert metrics.hist_totals("probeq.queue_wait_s") is None
        # a PLAYER submit feeds the estimator as before
        assert await q.submit("player") == 1.0
        assert metrics.gauge_values("probeq.admit_limit") != []
        assert metrics.hist_totals("probeq.queue_wait_s") is not None
    finally:
        await q.stop()


# -- verdicts --------------------------------------------------------------

@pytest.mark.asyncio
async def test_failed_probe_counts_and_links_exemplar():
    """A dead target fails the single probe that observed it: the
    verdict names the leg, probe.failures counts, probe.fail lands in
    the flight recorder, the trace is tail-retained, and the
    probe.e2e_s bucket exemplar points at exactly that trace."""
    cfg = make_cfg()
    server, fabric, url = await _serve(cfg, make_game(cfg))
    prober = CanaryProber(fabric, cfg, self_addr=url)
    try:
        ok = await prober.probe_once()
        assert ok["ok"], ok
        await server.close()          # the worker "dies"
        failures = metrics.counter_total("probe.failures")
        watermark = flight_recorder.stats()["total_recorded"]
        verdict = await prober.probe_once()
        assert not verdict["ok"]
        assert verdict["error"]
        assert metrics.counter_total("probe.failures") == failures + 1
        events = [e for e in flight_recorder.tail(kind="probe.fail")
                  if e["seq"] > watermark]
        assert len(events) == 1
        assert events[0]["trace"] == verdict["trace"]
        assert tracer.get_trace(verdict["trace"])
        ex = metrics.snapshot(exemplars=True)["exemplars"]
        linked = {e["trace_id"]
                  for e in ex.get("probe.e2e_s", {}).values()}
        assert verdict["trace"] in linked
        # the streak feeds the /readyz canary block
        block = prober.status_block()
        assert block["consecutive_failures"] == 1
        assert block["ok"] is False
    finally:
        await prober.close()


@pytest.mark.asyncio
async def test_cross_worker_probe_over_membership():
    """Worker A probes worker B through the membership table with the
    cluster token: B's probe room seeds under B's OWN prefix in the
    shared store, and the verdict is recorded per target."""
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    cfg = make_cfg(num_rooms=2)
    store = MemoryStore()

    async def start(worker_id):
        sup = ServingSupervisor()
        backend = FakeContentBackend(image_size=32)

        def factory(room, room_store):
            return Game(cfg, room_store, backend, hash_embed,
                        hash_similarity, supervisor=sup, room=room)

        fabric = RoomFabric(cfg, store, factory, worker_id=worker_id,
                            start_timers=False, heartbeat=False,
                            supervisor=sup)
        server = TestServer(create_app(fabric, cfg, start_timer=False))
        await server.start_server()
        fabric.membership.addr = f"http://127.0.0.1:{server.port}"
        return server, fabric

    server_a, fabric_a = await start("w-a")
    server_b, fabric_b = await start("w-b")
    try:
        for f in (fabric_a, fabric_b):
            await f.membership.heartbeat(len(f._games))
        for f in (fabric_a, fabric_b):
            await f.membership.refresh()
        prober = CanaryProber(fabric_a, cfg,
                              self_addr=fabric_a.membership.addr)
        try:
            targets = dict(prober._targets())
            assert set(targets) == {"w-a", "w-b"}
            await prober.probe_all()
            block = prober.status_block()
            assert set(block["targets"]) == {"w-a", "w-b"}
            assert block["ok"] is True, block
            probe_keys = [k for k in store._data
                          if k.startswith("probe:")]
            owners = {k.split(":", 2)[1] for k in probe_keys}
            assert owners == {"w-a", "w-b"}
        finally:
            await prober.close()
    finally:
        await server_a.close()
        await server_b.close()


# -- kill switch -----------------------------------------------------------

@pytest.mark.asyncio
async def test_no_prober_kill_switch_zero_artifacts(monkeypatch):
    """CASSMANTLE_NO_PROBER=1: no background task, canary disabled in
    /readyz, no probe SLO objectives, and no probe.* series moves."""
    import aiohttp

    from cassmantle_tpu.obs.slo import default_objectives
    from cassmantle_tpu.server import app as app_mod

    monkeypatch.setenv("CASSMANTLE_NO_PROBER", "1")
    assert prober_disabled()
    cfg = make_cfg()
    names = {o.name for o in default_objectives(cfg)}
    assert not any(n.startswith("probe") for n in names)
    app = app_mod.create_app(make_game(cfg), cfg, start_timer=False)
    server = TestServer(app)
    await server.start_server()
    before = dict(metrics.snapshot()["counters"])
    try:
        assert app[app_mod._PROBER]["prober"] is None
        url = f"http://{server.host}:{server.port}"
        async with aiohttp.ClientSession() as http:
            body = await (await http.get(url + "/readyz")).json()
            assert body["canary"] == {"enabled": False}
            # normal player traffic still serves, minting no probe.*
            res = await http.get(url + "/init", params={"session": "p"})
            assert res.status == 200
        counters = dict(metrics.snapshot()["counters"])
        for base in ("probe.ok", "probe.failures"):
            assert counter_base_total(counters, base) == \
                counter_base_total(before, base), base
    finally:
        await server.close()


@pytest.mark.asyncio
async def test_prober_enabled_objectives_and_app_task():
    """The default path: create_app arms the prober and the two
    black-box SLO objectives exist."""
    from cassmantle_tpu.obs.slo import default_objectives
    from cassmantle_tpu.server import app as app_mod

    cfg = make_cfg()
    names = {o.name for o in default_objectives(cfg)}
    assert {"probe_success", "probe_latency"} <= names
    app = app_mod.create_app(make_game(cfg), cfg, start_timer=False)
    server = TestServer(app)
    await server.start_server()
    try:
        prober = app[app_mod._PROBER]["prober"]
        assert prober is not None
        assert prober.interval_s() == 3600.0
    finally:
        await server.close()
