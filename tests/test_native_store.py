"""Native C++ state store (mantlestore) end-to-end tests.

Builds the server with g++, spawns it on a test port, and drives it through
the asyncio RESP client — including the same contract cases MemoryStore
passes, plus cross-connection lock exclusion (the multi-worker property the
engine's double-buffer relies on)."""

import asyncio

import pytest

from cassmantle_tpu.engine.store import LockTimeout
from cassmantle_tpu.native.client import MantleStore, ensure_built, spawn_server

PORT = 7171

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="module")
def server():
    proc = spawn_server(PORT)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture
def store(server):
    # NOTE: each async test runs in its own event loop (conftest runner),
    # so the client must connect inside the test; cleanup uses a fresh
    # client+loop of its own.
    yield MantleStore(port=PORT)

    async def cleanup():
        c = MantleStore(port=PORT)
        await c.flushall()
        await c.close()

    asyncio.run(cleanup())


@pytest.mark.asyncio
async def test_plain_keys_and_ttl(store):
    await store.setex("countdown", 0.2, "active")
    assert await store.exists("countdown")
    ttl = await store.ttl("countdown")
    assert 0.0 < ttl <= 0.2
    await asyncio.sleep(0.25)
    assert not await store.exists("countdown")
    assert await store.ttl("countdown") == -2.0

    await store.set("k", "v")
    assert await store.get("k") == b"v"
    assert await store.ttl("k") == -1.0
    await store.delete("k")
    assert await store.get("k") is None


@pytest.mark.asyncio
async def test_binary_values(store):
    blob = bytes(range(256)) * 3
    await store.hset("image", "current", blob)
    assert await store.hget("image", "current") == blob


@pytest.mark.asyncio
async def test_hash_ops(store):
    await store.hset("sess", mapping={"max": 0.01, "won": 0})
    await store.hset("sess", "attempts", 0)
    assert await store.hget("sess", "max") == b"0.01"
    assert set(await store.hgetall("sess")) == {"max", "won", "attempts"}
    assert await store.hincrby("sess", "attempts") == 1
    assert await store.hincrby("sess", "attempts", 4) == 5
    await store.hdel("sess", "max")
    assert await store.hget("sess", "max") is None


@pytest.mark.asyncio
async def test_set_ops(store):
    await store.sadd("sessions", "a", "b")
    assert await store.sismember("sessions", "a")
    await store.srem("sessions", "a")
    assert await store.smembers("sessions") == {"b"}


@pytest.mark.asyncio
async def test_lock_exclusion_across_connections(store):
    other = MantleStore(port=PORT)
    order = []

    async def holder():
        async with store.lock("l", timeout=5.0, blocking_timeout=1.0):
            order.append("h-in")
            await asyncio.sleep(0.2)
            order.append("h-out")

    async def waiter():
        await asyncio.sleep(0.05)
        async with other.lock("l", timeout=5.0, blocking_timeout=1.0):
            order.append("w-in")

    await asyncio.gather(holder(), waiter())
    assert order == ["h-in", "h-out", "w-in"]
    await other.close()


@pytest.mark.asyncio
async def test_lock_acquire_timeout(store):
    other = MantleStore(port=PORT)

    async def holder():
        async with store.lock("l2", timeout=5.0, blocking_timeout=0.5):
            await asyncio.sleep(0.4)

    async def contender():
        await asyncio.sleep(0.05)
        with pytest.raises(LockTimeout):
            async with other.lock("l2", timeout=5.0,
                                  blocking_timeout=0.1):
                pass

    await asyncio.gather(holder(), contender())
    await other.close()


@pytest.mark.asyncio
async def test_lock_self_expires(store):
    other = MantleStore(port=PORT)
    mgr = store.lock("l3", timeout=0.2, blocking_timeout=0.1)
    await mgr.__aenter__()  # simulated crash: never released
    await asyncio.sleep(0.25)
    async with other.lock("l3", timeout=1.0, blocking_timeout=0.5):
        pass
    await other.close()


@pytest.mark.asyncio
async def test_full_game_on_native_store(store):
    """The whole engine runs against the native store."""
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game

    cfg = test_config()
    game = Game(cfg, store, FakeContentBackend(image_size=16),
                hash_embed, hash_similarity)
    await game.startup()
    await game.init_client("s1")
    prompt = await game.rounds.fetch_current_prompt()
    answers = {str(m): prompt["tokens"][m] for m in prompt["masks"]}
    result = await game.compute_client_scores("s1", answers)
    assert result["won"] == 1
    await game.rounds.buffer_contents()
    await game.rounds.promote_buffer()
    assert int((await game.fetch_story())["episode"]) == 2


@pytest.mark.asyncio
async def test_snapshot_durability(tmp_path):
    """State survives a SIGTERM + restart via the snapshot file — the
    worker-restart-resumes-round semantics the reference gets from Redis
    durability (SURVEY.md §5.4)."""
    import signal

    snap = str(tmp_path / "store.snap")
    port = PORT + 1
    proc = spawn_server(port, snapshot_path=snap)
    try:
        c = MantleStore(port=port)
        await c.set("prompt:current", "the stormy lighthouse")
        await c.hset("story", mapping={"title": "Salt Roads", "episode": "3"})
        await c.sadd("sessions", "s1", "s2")
        await c.setex("countdown", 30.0, "active")
        await c.setex("gone", 0.05, "x")
        await c.close()
        import asyncio as aio

        await aio.sleep(0.1)  # 'gone' expires before the snapshot
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=5)

        proc = spawn_server(port, snapshot_path=snap)
        c = MantleStore(port=port)
        assert await c.get("prompt:current") == b"the stormy lighthouse"
        story = await c.hgetall("story")
        assert story["title"] == b"Salt Roads" and story["episode"] == b"3"
        assert await c.smembers("sessions") == {"s1", "s2"}
        ttl = await c.ttl("countdown")
        assert 0.0 < ttl <= 30.0  # TTL persisted as REMAINING time
        assert not await c.exists("gone")
        await c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


@pytest.mark.asyncio
async def test_snapshot_chunks_large_collections(tmp_path):
    """Sets/hashes beyond the RESP 1024-arg parse cap replay losslessly
    (the snapshot writer chunks multi-member commands)."""
    import signal

    snap = str(tmp_path / "big.snap")
    port = PORT + 2
    proc = spawn_server(port, snapshot_path=snap)
    try:
        c = MantleStore(port=port)
        members = [f"player-{i}" for i in range(1500)]
        await c.sadd("sessions", *members)
        await c.hset("scores",
                     mapping={f"f{i}": str(i) for i in range(700)})
        await c.set("after", "still-here")  # key serialized after the big ones
        await c.close()
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=5)

        proc = spawn_server(port, snapshot_path=snap)
        c = MantleStore(port=port)
        assert await c.smembers("sessions") == set(members)
        scores = await c.hgetall("scores")
        assert len(scores) == 700 and scores["f699"] == b"699"
        assert await c.get("after") == b"still-here"
        await c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


@pytest.mark.asyncio
async def test_lock_expired_in_hold_detected(store):
    """The native client reports the same hazard taxonomy MemoryStore
    detects: a hold past its TTL that nobody reclaimed is an 'overrun'
    (UNLOCK :2); one another worker reacquired is 'expired_in_hold'
    (UNLOCK :0)."""
    from cassmantle_tpu.utils.logging import metrics

    key = "store.lock_overrun"
    before = metrics.snapshot()["counters"].get(key, 0)
    async with store.lock("l4", timeout=0.2, blocking_timeout=0.1):
        await asyncio.sleep(0.3)   # hold past the TTL, unclaimed
    after = metrics.snapshot()["counters"].get(key, 0)
    assert after == before + 1

    other = MantleStore(port=PORT)
    key = "store.lock_expired_in_hold"
    before = metrics.snapshot()["counters"].get(key, 0)
    async with store.lock("l5", timeout=0.2, blocking_timeout=1.0):
        await asyncio.sleep(0.3)
        # generous blocking_timeout: the lock frees after its 0.2 s TTL,
        # but on a saturated host pure event-loop scheduling delay can
        # exceed a tight window and fail the ACQUISITION, which this
        # test is not about (observed flaking at 0.5 s under a full
        # parallel suite run)
        async with other.lock("l5", timeout=1.0, blocking_timeout=5.0):
            pass      # another worker reacquired the expired lock
    after = metrics.snapshot()["counters"].get(key, 0)
    assert after == before + 1
    await other.close()
