"""Normalization layers vs the flax reference implementations.

GroupNorm32/LayerNorm32 restructure the statistics computation for TPU
layout/bandwidth (channels-last reductions, affine folded to one FMA) —
these tests pin them to nn.GroupNorm/nn.LayerNorm numerics so layout
optimizations can never drift the math.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.models.layers import GroupNorm32, LayerNorm32


def test_groupnorm_matches_flax_fp32():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 64),
                          jnp.float32) * 3.0 + 1.5
    ours = GroupNorm32(num_groups=16)
    ref = nn.GroupNorm(num_groups=16, epsilon=1e-5)
    # non-trivial affine params, mapped into each layout
    scale = jax.random.normal(jax.random.PRNGKey(2), (64,)) + 1.0
    bias = jax.random.normal(jax.random.PRNGKey(3), (64,))
    p_ours = {"params": {"norm": {"scale": scale, "bias": bias}}}
    p_ref = {"params": {"scale": scale, "bias": bias}}
    np.testing.assert_allclose(
        np.asarray(ours.apply(p_ours, x)),
        np.asarray(ref.apply(p_ref, x)),
        rtol=2e-5, atol=2e-5,
    )


def test_groupnorm_bf16_activation_close_to_fp32_ref():
    x32 = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 16, 32),
                            jnp.float32)
    gn = GroupNorm32(num_groups=8)
    params = gn.init(jax.random.PRNGKey(5), x32)
    out32 = gn.apply(params, x32)
    out16 = gn.apply(params, x32.astype(jnp.bfloat16))
    assert out16.dtype == jnp.bfloat16
    # fp32 statistics keep bf16 activations within bf16 rounding error
    np.testing.assert_allclose(np.asarray(out16, dtype=np.float32),
                               np.asarray(out32), atol=0.06)


def test_groupnorm_constant_input_is_bias():
    # zero variance: output must be exactly the bias (rsqrt(eps) * 0)
    x = jnp.full((1, 4, 4, 16), 7.0, jnp.float32)
    gn = GroupNorm32(num_groups=4)
    params = gn.init(jax.random.PRNGKey(6), x)
    out = gn.apply(params, x)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-3)


def test_layernorm_matches_flax_fp32():
    x = jax.random.normal(jax.random.PRNGKey(7), (3, 17, 96),
                          jnp.float32) * 2.0 - 0.5
    ours = LayerNorm32()
    ref = nn.LayerNorm(epsilon=1e-5)
    scale = jax.random.normal(jax.random.PRNGKey(8), (96,)) + 1.0
    bias = jax.random.normal(jax.random.PRNGKey(9), (96,))
    p = {"params": {"scale": scale, "bias": bias}}
    np.testing.assert_allclose(
        np.asarray(ours.apply(p, x)),
        np.asarray(ref.apply(p, x)),
        rtol=2e-5, atol=2e-5,
    )
