"""Multi-process room-fabric cluster tests (slow tier).

The heavyweight end of ISSUE 8's acceptance: real worker PROCESSES over
a shared (and then replicated) mantlestore, real HTTP + WS load through
the bench harness, and the full failover drill — the store leader dies
under live multi-worker traffic and the fleet keeps serving guesses
from the promoted follower. The per-component versions of these
behaviors run in the fast tier (tests/test_fabric.py); this module
buys the cross-process integration at multi-second cost, which is why
it lives in ``slow`` (tests/conftest.py).
"""

import asyncio
import time

import pytest

import bench
from cassmantle_tpu.native.client import MantleStore, ensure_built, spawn_server

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="no C++ toolchain"
)


def test_multiworker_rooms_load():
    """2 workers × 4 rooms under sustained load: guesses flow on every
    worker (cross-worker 307s followed transparently), the WS clock
    fans out, and the room spread is real."""
    raw = bench.rooms_load_run(workers=2, rooms=4, sessions=6,
                               seconds=4.0, ws_conns=4,
                               base_port=8501, store_port=7501)
    assert raw["guesses"] > 20
    assert raw["ws_ticks"] >= 4
    # the flood is allowed a handful of stragglers (connection churn at
    # the deadline) but not systematic failure
    assert raw["errors"] <= raw["guesses"] * 0.05


def test_cluster_survives_store_leader_kill_under_load():
    """The failover drill end-to-end: two fabric workers over a
    replicated store pair; the leader dies mid-load; the workers'
    ReplicatedStores promote the follower and the SECOND load phase
    still lands guesses."""
    leader = spawn_server(7671, repl=True, repl_id="A", lease_ms=600)
    follower = spawn_server(7672, follower=True, repl_id="B", lease_ms=600)
    procs = []
    try:
        procs, base_urls = bench.rooms_load_spawn_workers(
            workers=2, rooms=3, base_port=8511,
            store_addr="repl:127.0.0.1:7671,127.0.0.1:7672")
        phase1 = asyncio.run(bench._rooms_load_drive(
            base_urls, sessions=4, seconds=2.0, ws_conns=0))
        assert phase1["guesses"] > 0
        leader.kill()
        leader.wait()
        # the workers' next store op fails over once the 600 ms lease
        # lapses on the follower; give the drill a fresh load phase
        phase2 = asyncio.run(bench._rooms_load_drive(
            base_urls, sessions=4, seconds=4.0, ws_conns=0))
        assert phase2["guesses"] > 0, (
            f"no guesses landed after leader kill ({phase2['errors']} "
            f"errors)")

        async def check_promoted():
            c = MantleStore(port=7672)
            role = await c.repl_role()
            await c.close()
            return role

        deadline = time.monotonic() + 5.0
        role = asyncio.run(check_promoted())
        while role != "leader" and time.monotonic() < deadline:
            time.sleep(0.2)
            role = asyncio.run(check_promoted())
        assert role == "leader"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.join(timeout=5.0)
        for proc in (leader, follower):
            try:
                proc.kill()
                proc.wait()
            except Exception:
                pass
