"""Deep-feature reuse (DeepCache-style serving acceleration).

The key invariants that make the approximation trustworthy:
1. the UNet's full/shallow split is EXACT when the cache comes from the
   same step (shallow(x, deep_of(x)) == full(x));
2. the paired DDIM loop is EXACT when the shallow denoiser ignores its
   cache (pairing math == plain eta-0 DDIM);
3. the whole pipeline runs with the deepcache config.
The only approximation in production is reusing step t's deep features
at step t+1 — everything structural is pinned here.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.ops.ddim import (
    DDIMSchedule,
    ddim_sample,
    ddim_sample_deepcache,
)


def _tiny_unet():
    cfg = _tiny_config().models.unet
    model = UNet(cfg)
    lat = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))
    t = jnp.array([5, 9], jnp.int32)
    ctx = jax.random.normal(jax.random.PRNGKey(1), (2, 6, cfg.context_dim))
    params = init_params(model, 0, lat, t, ctx)
    return model, params, lat, t, ctx


def test_shallow_pass_exact_with_same_step_cache():
    model, params, lat, t, ctx = _tiny_unet()
    eps_full, deep = model.apply(params, lat, t, ctx, None, None, True)
    eps_shallow = model.apply(params, lat, t, ctx, None, deep)
    np.testing.assert_allclose(
        np.asarray(eps_shallow), np.asarray(eps_full), atol=1e-5, rtol=1e-5
    )


def test_deep_cache_actually_skips_deep_levels():
    """The shallow pass must not depend on deeper-level parameters:
    zeroing the mid block changes the full pass but not the shallow one."""
    model, params, lat, t, ctx = _tiny_unet()
    _, deep = model.apply(params, lat, t, ctx, None, None, True)

    broken = jax.tree_util.tree_map(lambda x: x, params)  # copy refs
    import flax

    broken = flax.core.unfreeze(broken) if hasattr(flax.core, "unfreeze") \
        else broken
    mid = broken["params"]["mid_res_0"]["conv1"]["kernel"]
    broken["params"]["mid_res_0"]["conv1"]["kernel"] = jnp.zeros_like(mid)

    shallow_ok = model.apply(params, lat, t, ctx, None, deep)
    shallow_broken = model.apply(broken, lat, t, ctx, None, deep)
    np.testing.assert_array_equal(np.asarray(shallow_ok),
                                  np.asarray(shallow_broken))
    full_ok = model.apply(params, lat, t, ctx)
    full_broken = model.apply(broken, lat, t, ctx)
    assert not np.allclose(np.asarray(full_ok), np.asarray(full_broken))


def test_paired_loop_matches_plain_ddim_when_cache_ignored():
    schedule = DDIMSchedule.create(8)
    lat = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 8, 4))

    def denoise(x, t):
        return 0.1 * x + 0.01 * t.astype(jnp.float32)

    ref = ddim_sample(denoise, lat, schedule, eta=0.0)
    out = ddim_sample_deepcache(
        lambda x, t: (denoise(x, t), None),
        lambda x, t, deep: denoise(x, t),
        lat, schedule,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_with_deepcache_config():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _tiny_config()
    cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="ddim", deepcache=True, num_steps=4))
    pipe = Text2ImagePipeline(cfg)
    imgs = pipe.generate(["a quiet harbor at dawn"], seed=1)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8


def test_deepcache_rejects_odd_steps_or_wrong_sampler():
    import pytest

    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _tiny_config()
    bad = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="ddim", deepcache=True, num_steps=5))
    with pytest.raises(AssertionError, match="even"):
        Text2ImagePipeline(bad)
    bad = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="euler", deepcache=True, num_steps=4))
    with pytest.raises(AssertionError, match="ddim"):
        Text2ImagePipeline(bad)
    bad = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="ddim", deepcache=True, num_steps=4, eta=0.5))
    with pytest.raises(AssertionError, match="eta"):
        Text2ImagePipeline(bad)


def test_sdxl_pipeline_with_deepcache_config():
    from cassmantle_tpu.config import (
        test_sdxl_config as _tiny_sdxl_config,
    )
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    cfg = _tiny_sdxl_config()
    cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="ddim", deepcache=True, num_steps=4))
    pipe = SDXLPipeline(cfg)
    imgs = pipe.generate(["a glass orchard"], seed=2)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8


def test_img2img_rejects_deepcache():
    import pytest

    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _tiny_config()
    cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="ddim", deepcache=True, num_steps=4))
    pipe = Text2ImagePipeline(cfg)
    with pytest.raises(NotImplementedError, match="img2img"):
        pipe.generate_img2img(
            np.zeros((1, cfg.sampler.image_size, cfg.sampler.image_size, 3),
                     np.uint8),
            ["x"],
        )


def test_dpmpp_paired_loop_matches_plain_when_cache_ignored():
    """dpmpp_2m + deepcache pairing is EXACTLY dpmpp_2m when the shallow
    denoiser ignores its cache — for even AND odd step counts (odd runs
    its final step as an unpaired full pass)."""
    from cassmantle_tpu.ops.samplers import (
        DPMppSchedule,
        dpmpp_2m_sample,
        dpmpp_2m_sample_deepcache,
    )

    lat = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 8, 4))

    def denoise(x, t):
        return 0.1 * x + 0.01 * t.astype(jnp.float32)

    for steps in (8, 5):
        schedule = DPMppSchedule.create(steps)
        ref = dpmpp_2m_sample(denoise, lat, schedule)
        out = dpmpp_2m_sample_deepcache(
            lambda x, t: (denoise(x, t), None),
            lambda x, t, deep: denoise(x, t),
            lat, schedule,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6, rtol=1e-6, err_msg=f"{steps=}")


def test_pipeline_with_dpmpp_deepcache_config():
    """The composed turbo path (dpmpp_2m + deepcache) runs end to end,
    including an odd step count."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    for steps in (4, 5):
        cfg = _tiny_config()
        cfg = cfg.replace(sampler=dataclasses.replace(
            cfg.sampler, kind="dpmpp_2m", deepcache=True, num_steps=steps))
        pipe = Text2ImagePipeline(cfg)
        imgs = pipe.generate(["a copper kite over cliffs"], seed=3)
        assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8
