"""Stage-disaggregated serving (serving/stages.py): parity + scheduling.

The acceptance bars from the stage-graph refactor (ISSUE 6), in test
form:

- **solo bit-parity** — a request through the staged encode/denoise/
  decode graph produces BYTE-identical images to the monolithic
  dispatch for the same seed/prompt, on both the SD1.5 and SDXL-shaped
  test configs (the kill switch flips the SAME pipeline object between
  paths, so params/tokenizer/jit inputs are held constant);
- **continuous batching is real** — a request submitted mid-denoise of
  another is admitted into a free slot at a step boundary BEFORE that
  denoise finishes (slot-step accounting proves overlap), both outputs
  stay bit-correct, and the denoise step function compiles exactly once
  for the whole mixed admission/retirement history;
- **step-granular deadlines** — an expired request frees its slot at
  the next boundary (DeadlineExceeded) without perturbing a neighbor's
  trajectory;
- **containment** — a step failure fails the waiting callers instead of
  hanging them, and stop() fails pending work with QueueStopped; both
  leave the server restartable.

The module deliberately stays OUT of the ``fast`` tier (it compiles
three pipeline-sized jits); it runs in the default tier-1 sweep like
test_spec_decode (tests/conftest.py tier map).
"""

import dataclasses
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.config import test_sdxl_config as _tiny_sdxl_config
from cassmantle_tpu.ops.samplers import make_sampler, make_slot_sampler
from cassmantle_tpu.serving.queue import DeadlineExceeded, QueueStopped
from cassmantle_tpu.serving.supervisor import ServingSupervisor

KILL = "CASSMANTLE_NO_STAGED_SERVING"


def staged_test_config():
    base = _tiny_config()
    return base.replace(serving=dataclasses.replace(
        base.serving, staged_serving=True, denoise_slots=3))


@pytest.fixture(scope="module")
def sd_pipe():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe = Text2ImagePipeline(staged_test_config())
    pipe.supervisor = ServingSupervisor()
    yield pipe
    if pipe._staged is not None:
        pipe._staged.stop()


@pytest.fixture(autouse=True)
def _clear_hook(sd_pipe):
    yield
    if sd_pipe._staged is not None:
        sd_pipe._staged._on_step = None


# -- slot sampler unit parity (no UNet: cheap, covers every kind) ------------

def _toy_denoise(x, t):
    tt = jnp.asarray(t, jnp.float32)
    if tt.ndim:
        tt = tt.reshape((-1,) + (1,) * (x.ndim - 1))
    return 0.003 * x * (tt + 1.0) - 0.01 * x


@pytest.mark.parametrize("kind", ["ddim", "euler", "dpmpp_2m"])
def test_slot_sampler_matches_scan_bitwise(kind):
    """make_slot_sampler replays make_sampler's scan body verbatim: a
    solo trajectory stepped one JITTED slot-step at a time is
    bit-identical to the monolithic lax.scan, for every stageable
    sampler kind. The step must run under jit exactly as the server
    dispatches it (StagedImageServer._step): XLA then fuses the step
    body the same way it fuses the scan body — eager per-op dispatch
    would skip those fusions and drift in the last ulp."""
    num_steps = 5
    lat = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, 4, 4, 2)),
        jnp.float32)
    ref = make_sampler(kind, num_steps)(_toy_denoise, lat)
    prepare, slot_step, n = make_slot_sampler(kind, num_steps)
    assert n == num_steps
    step = jax.jit(
        lambda x, aux, idx: slot_step(_toy_denoise, x, aux, idx))
    x, aux = prepare(lat)
    for i in range(num_steps):
        x, aux = step(x, aux, jnp.full((1,), i, jnp.int32))
    assert np.array_equal(np.asarray(ref), np.asarray(x)), kind


def test_slot_sampler_rejects_stochastic_eta():
    with pytest.raises(ValueError, match="eta"):
        make_slot_sampler("ddim", 4, eta=0.3)


# -- routing decision --------------------------------------------------------

def test_staged_enabled_gating(monkeypatch):
    """The per-call routing decision: on for the supported configs, off
    for everything the slot stepper cannot replay exactly, off under
    the kill switch."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    monkeypatch.delenv(KILL, raising=False)
    cfg = staged_test_config()

    def ns(cfg, mesh=None):
        return SimpleNamespace(cfg=cfg, mesh=mesh)

    enabled = Text2ImagePipeline._staged_enabled
    assert enabled(ns(cfg))
    assert not enabled(ns(_tiny_config()))          # knob off
    assert not enabled(ns(cfg, mesh=object()))     # meshed serving
    for sampler in (
        dataclasses.replace(cfg.sampler, deepcache=True),
        dataclasses.replace(cfg.sampler, eta=0.5),
        dataclasses.replace(cfg.sampler, kind="nonexistent"),
    ):
        assert not enabled(ns(cfg.replace(sampler=sampler)))
    monkeypatch.setenv(KILL, "1")
    assert not enabled(ns(cfg))                    # kill switch


# -- solo bit-parity ---------------------------------------------------------

def _mono_ref(monkeypatch, pipe, prompts, seed):
    """The monolithic output of the SAME pipeline object (kill switch
    routes generate() through the proven whole-jit dispatch)."""
    monkeypatch.setenv(KILL, "1")
    try:
        return pipe.generate(prompts, seed=seed)
    finally:
        monkeypatch.delenv(KILL, raising=False)


def test_solo_bit_parity_sd15(sd_pipe, monkeypatch):
    prompt = ["a lighthouse over a stormy sea"]
    ref = _mono_ref(monkeypatch, sd_pipe, prompt, seed=7)
    out = sd_pipe.generate(prompt, seed=7)
    assert out.dtype == np.uint8 and out.shape == ref.shape
    assert np.array_equal(ref, out), "staged SD1.5 output diverged"
    # a second seed exercises a fresh latent draw through the SAME
    # compiled step function
    ref2 = _mono_ref(monkeypatch, sd_pipe, prompt, seed=8)
    out2 = sd_pipe.generate(prompt, seed=8)
    assert np.array_equal(ref2, out2)
    assert not np.array_equal(ref, ref2)  # the seed actually matters


def test_multi_prompt_request_bit_parity(sd_pipe, monkeypatch):
    """A B=2 request splits into two denoise slots but draws its
    latents as ONE (2, ...) normal draw, exactly like the monolithic
    batch — rows must come back identical and in order."""
    prompts = ["a caravan crossing silver dunes", "an orchard at night"]
    ref = _mono_ref(monkeypatch, sd_pipe, prompts, seed=11)
    out = sd_pipe.generate(prompts, seed=11)
    assert np.array_equal(ref, out)


def test_solo_bit_parity_sdxl(monkeypatch):
    """Same parity bar for the SDXL shape: dual-tower conditioning +
    micro-conds ride the cond dict as add/uadd rows."""
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    base = _tiny_sdxl_config()
    cfg = base.replace(serving=dataclasses.replace(
        base.serving, staged_serving=True, denoise_slots=2))
    pipe = SDXLPipeline(cfg)
    try:
        prompt = ["a stained glass window of two moons"]
        ref = _mono_ref(monkeypatch, pipe, prompt, seed=5)
        out = pipe.generate(prompt, seed=5)
        assert np.array_equal(ref, out), "staged SDXL output diverged"
    finally:
        if pipe._staged is not None:
            pipe._staged.stop()


# -- continuous batching: mid-flight admission -------------------------------

def test_mid_flight_admission_and_compile_once(sd_pipe, monkeypatch):
    """The tentpole property: request B, submitted while request A is
    mid-denoise, joins at a step boundary BEFORE A finishes. The
    step-loop hook holds the boundary after A's second step until B's
    encoded conditioning reaches the admission queue, so the overlap is
    deterministic, then slot-step accounting proves both requests
    actually shared step dispatches. Both outputs stay bit-identical to
    their monolithic references, and the jitted step function has
    compiled exactly ONCE across the whole admission/retirement
    history."""
    prompt_a = ["a night train between cities"]
    prompt_b = ["a watercolor harbor at dawn"]
    ref_a = _mono_ref(monkeypatch, sd_pipe, prompt_a, seed=21)
    ref_b = _mono_ref(monkeypatch, sd_pipe, prompt_b, seed=22)

    srv = sd_pipe._staged_server()
    base = dict(srv.stats)
    num_steps = srv.num_steps
    snaps = []

    def hook(s):
        snaps.append((s.stats["steps"] - base["steps"],
                      s.stats["admissions"] - base["admissions"]))
        if (s.stats["admissions"] - base["admissions"] == 1
                and s.stats["steps"] - base["steps"] >= 2):
            deadline = time.monotonic() + 30.0
            while (s._admit_q.empty() and not s._pend
                    and time.monotonic() < deadline
                    and not s._stop_evt.is_set()):
                time.sleep(0.002)

    srv._on_step = hook
    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(sd_pipe.generate, prompt_a, 21)
        # B arrives only once A is admitted (denoise in flight)
        deadline = time.monotonic() + 30.0
        while (srv.stats["admissions"] - base["admissions"] < 1
                and time.monotonic() < deadline):
            time.sleep(0.002)
        fb = ex.submit(sd_pipe.generate, prompt_b, 22)
        out_a = fa.result(timeout=120)
        out_b = fb.result(timeout=120)
    srv._on_step = None

    assert np.array_equal(ref_a, out_a), "neighbor admission perturbed A"
    assert np.array_equal(ref_b, out_b), "mid-flight admission broke B"
    # B was admitted mid-denoise of A: at some observed boundary the
    # second admission had happened while A (admitted at step 0) still
    # had steps to run
    b_admit_steps = [s for s, adm in snaps if adm == 2]
    assert b_admit_steps, "B was never admitted while observable"
    assert min(b_admit_steps) < num_steps, (
        "B only joined after A's denoise completed — that is a rename, "
        "not continuous batching")
    # overlap in the slot tensor: some steps advanced BOTH slots
    d_steps = srv.stats["steps"] - base["steps"]
    d_slot_steps = srv.stats["slot_steps"] - base["slot_steps"]
    assert d_slot_steps > d_steps, "no step ever ran two live slots"
    assert d_slot_steps == 2 * num_steps  # every request got its steps
    # the step function compiles once per occupancy-width bucket, never
    # per admission/retirement: this module has only ever driven widths
    # 1 and 2, across MANY admissions
    cache_after = srv._step._cache_size()
    assert cache_after <= 2, "step recompiled beyond the width buckets"
    # ...and another full request (width 1, already compiled) plus the
    # admissions it implies grow the cache by nothing
    sd_pipe.generate(prompt_a, seed=23)
    assert srv._step._cache_size() == cache_after
    # the jit compile-count sentinel pins the same steady-state claim
    # across the WHOLE stage graph (encode/init/admit/step/take/
    # decode), not just the step cache: admissions in warmed width
    # buckets compile nothing anywhere
    from cassmantle_tpu.utils import jit_sentinel

    with jit_sentinel.no_new_compiles():
        sd_pipe.generate(prompt_b, seed=24)
    assert srv._step._cache_size() == cache_after


# -- deadlines at step granularity -------------------------------------------

def test_deadline_expiry_frees_slot_without_corrupting_neighbor(
        sd_pipe, monkeypatch):
    prompt_a = ["an art deco skyline"]
    prompt_b = ["a vaporwave fountain"]
    ref_a = _mono_ref(monkeypatch, sd_pipe, prompt_a, seed=31)

    srv = sd_pipe._staged_server()
    base = dict(srv.stats)
    state = {}

    def hook(s):
        # once both requests occupy slots, stall ONE boundary long
        # enough to blow B's deadline; the next tick preempts it
        if (s.stats["admissions"] - base["admissions"] >= 2
                and "slept" not in state):
            state["slept"] = True
            time.sleep(0.7)

    srv._on_step = hook
    with ThreadPoolExecutor(max_workers=2) as ex:
        fa = ex.submit(sd_pipe.generate, prompt_a, 31)
        fb = ex.submit(lambda: sd_pipe.generate(prompt_b, 32,
                                                deadline_s=0.5))
        out_a = fa.result(timeout=120)
        with pytest.raises(DeadlineExceeded):
            fb.result(timeout=120)
    srv._on_step = None

    assert srv.stats["preemptions"] - base["preemptions"] >= 1
    assert np.array_equal(ref_a, out_a), (
        "preempting a neighbor's slot perturbed a live trajectory")
    # the freed slot is reusable: a follow-up request completes
    assert sd_pipe.generate(prompt_b, seed=33).shape == out_a.shape


# -- kill switch & fallback --------------------------------------------------

def test_kill_switch_routes_monolithic(sd_pipe, monkeypatch):
    srv = sd_pipe._staged_server()
    before = dict(srv.stats)
    monkeypatch.setenv(KILL, "1")
    out = sd_pipe.generate(["a quiet glass valley"], seed=41)
    assert out.dtype == np.uint8
    # no staged admission happened: the monolithic jit served it
    assert srv.stats == before


# -- observability -----------------------------------------------------------

def test_stage_metrics_events_and_supervisor_health(sd_pipe, monkeypatch):
    from cassmantle_tpu.obs.recorder import flight_recorder
    from cassmantle_tpu.utils.logging import metrics

    sd_pipe.generate(["a velvet comet"], seed=51)
    snap = metrics.snapshot()
    assert snap["counters"].get("stage.denoise.admissions", 0) >= 1
    assert "stage.denoise.queue_wait_s" in snap["timings"]
    assert "stage.denoise.service_s" in snap["timings"]
    # the per-stage BatchingQueues report under their stage names
    assert "stage.encode.batch_size" in snap["timings"]
    assert "stage.decode.queue_wait_s" in snap["timings"]
    assert snap["gauges"]["stage.denoise.slot_occupancy"] <= 1.0
    kinds = {e["kind"] for e in flight_recorder.tail(200)}
    assert {"stage.admit", "stage.retire"} <= kinds
    # per-stage progress fused into the one supervisor /readyz feeds
    health = sd_pipe.supervisor.stage_health()
    assert {"encode", "denoise", "decode"} <= set(health)
    status = sd_pipe.supervisor.status()
    assert set(status["stages"]) >= {"encode", "denoise", "decode"}


# -- containment & lifecycle -------------------------------------------------

def test_step_failure_fails_caller_not_hangs(sd_pipe):
    srv = sd_pipe._staged_server()
    orig = srv._step

    def boom(*a, **k):
        raise RuntimeError("injected step failure")

    srv._step = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            sd_pipe.generate(["a broken loom"], seed=61)
    finally:
        srv._step = orig
    # the loop survived and the slot state reset: next request is clean
    out = sd_pipe.generate(["a mended loom"], seed=62)
    assert out.dtype == np.uint8


def test_stop_fails_pending_and_server_restarts(sd_pipe):
    srv = sd_pipe._staged_server()
    hold = threading.Event()

    def hook(s):
        while not hold.is_set() and not s._stop_evt.is_set():
            time.sleep(0.002)

    srv._on_step = hook
    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(sd_pipe.generate, ["an unfinished bridge"], 71)
        deadline = time.monotonic() + 30.0
        while (not srv._pend and srv._admit_q.empty()
                and not srv._alive.any()
                and time.monotonic() < deadline):
            time.sleep(0.002)
        srv.stop()
        hold.set()
        with pytest.raises(QueueStopped):
            fut.result(timeout=60)
    srv._on_step = None
    # stopped is not wedged: the next generate restarts the stage graph
    out = sd_pipe.generate(["a rebuilt bridge"], seed=72)
    assert out.dtype == np.uint8
