"""LM training stack tests: packing, prefetch loader, and the distributed
LM train step on the 8-device CPU mesh for BOTH prompt-LM families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import (
    MeshConfig,
    MistralConfig,
    test_config as _tiny_config,
)
from cassmantle_tpu.models.gpt2 import GPT2LM
from cassmantle_tpu.models.mistral import MistralLM
from cassmantle_tpu.parallel.lm_train import LMTrainer, next_token_loss
from cassmantle_tpu.parallel.mesh import make_mesh
from cassmantle_tpu.utils.data import (
    PrefetchLoader,
    batches_from,
    pack_tokens,
)

ENC = lambda s: [ord(c) % 250 for c in s]  # noqa: E731


def test_pack_tokens_dense_rows():
    packed = pack_tokens(["abc", "defg"], ENC, seq_len=4, eos_id=255)
    ids, mask = packed["input_ids"], packed["loss_mask"]
    # stream: a b c EOS d e f g EOS -> 9 tokens -> 3 rows of 4, 3 pad
    assert ids.shape == (3, 4) and mask.shape == (3, 4)
    assert ids[0].tolist() == [ord("a") % 250, ord("b") % 250,
                               ord("c") % 250, 255]
    assert mask[:2].min() == 1           # full rows all real
    assert mask[2].tolist() == [1, 0, 0, 0]
    assert ids[2, 1:].tolist() == [255, 255, 255]


def test_pack_tokens_empty():
    packed = pack_tokens([], ENC, seq_len=8, eos_id=1)
    assert packed["input_ids"].shape == (0, 8)


def test_batches_from_epochs_and_shapes():
    packed = pack_tokens(["hello world"] * 10, ENC, seq_len=4, eos_id=255)
    batches = list(batches_from(packed, 8, epochs=2, seed=1))
    n = packed["input_ids"].shape[0]
    assert len(batches) == 2 * (n // 8)
    assert all(b["input_ids"].shape == (8, 4) for b in batches)
    # shuffling: two epochs see different row orders; rows must use
    # distinguishable content for the assertion to mean anything
    packed2 = {
        "input_ids": np.arange(64, dtype=np.int32).reshape(16, 4),
        "loss_mask": np.ones((16, 4), np.int32),
    }
    two = list(batches_from(packed2, 8, epochs=2, seed=3))
    e1 = np.concatenate([b["input_ids"] for b in two[:2]])
    e2 = np.concatenate([b["input_ids"] for b in two[2:]])
    assert e1.shape == e2.shape
    assert not np.array_equal(e1, e2)
    # and unshuffled epochs repeat exactly
    two_ns = list(batches_from(packed2, 8, epochs=2, shuffle=False))
    np.testing.assert_array_equal(two_ns[0]["input_ids"],
                                  two_ns[2]["input_ids"])


def test_prefetch_loader_order_and_error():
    batches = [{"x": np.full((2,), i)} for i in range(5)]
    out = list(PrefetchLoader(batches, depth=2))
    assert [int(b["x"][0]) for b in out] == [0, 1, 2, 3, 4]

    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("boom")

    loader = PrefetchLoader(bad())
    next(loader)
    with pytest.raises(RuntimeError, match="boom"):
        next(loader)


def test_batches_from_rejects_undersized_corpus():
    packed = pack_tokens(["ab"], ENC, seq_len=4, eos_id=255)
    with pytest.raises(ValueError, match="batch_size"):
        next(batches_from(packed, 8))


def test_prefetch_loader_exhaustion_is_sticky():
    loader = PrefetchLoader([{"x": np.zeros(1)}])
    assert len(list(loader)) == 1
    with pytest.raises(StopIteration):
        next(loader)  # second next() raises again instead of deadlocking
    with pytest.raises(StopIteration):
        next(loader)


def test_next_token_loss_masks_padding():
    v = 16
    logits = jnp.zeros((1, 4, v))
    ids = jnp.asarray([[1, 2, 3, 0]], dtype=jnp.int32)
    full = next_token_loss(logits, ids, jnp.ones((1, 4), jnp.int32))
    # uniform logits -> loss log(v) regardless of targets
    np.testing.assert_allclose(float(full), np.log(v), rtol=1e-5)
    # masking the pad tail must not change the uniform value but must
    # change the denominator; make one target "right" to see the effect
    peaked = logits.at[0, 2, 0].set(10.0)  # predicts target at pos 3
    m_all = next_token_loss(peaked, ids, jnp.ones((1, 4), jnp.int32))
    m_pad = next_token_loss(
        peaked, ids, jnp.asarray([[1, 1, 1, 0]], jnp.int32)
    )
    assert float(m_pad) > float(m_all)  # the easy (peaked) position at
    # the masked tail no longer pulls the mean down


@pytest.mark.parametrize("family", ["gpt2", "mistral"])
def test_lm_trainer_step_runs_and_learns(family):
    cfg = _tiny_config()
    if family == "gpt2":
        model = GPT2LM(cfg.models.gpt2)
        vocab = cfg.models.gpt2.vocab_size
    else:
        model = MistralLM(MistralConfig.tiny())
        vocab = MistralConfig.tiny().vocab_size
    mesh = make_mesh(MeshConfig(dp=-1, tp=2))
    trainer = LMTrainer(model, mesh, lr=1e-2)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (8, 12)).astype(np.int32)
    batch = trainer.shard_batch({
        "input_ids": ids,
        "loss_mask": np.ones_like(ids),
    })
    params, opt_state = trainer.init_state(jnp.asarray(ids[:1]))
    losses = []
    for i in range(5):
        params, opt_state, loss = trainer.step(
            params, opt_state, batch, jax.random.PRNGKey(i)
        )
        losses.append(float(jax.block_until_ready(loss)))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizes the fixed batch


def test_lm_trainer_remat_matches():
    cfg = _tiny_config()
    model = GPT2LM(cfg.models.gpt2)
    mesh = make_mesh(MeshConfig(dp=-1))
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.models.gpt2.vocab_size, (8, 8)).astype(
        np.int32)
    batch = {"input_ids": ids, "loss_mask": np.ones_like(ids)}

    losses = {}
    for remat in (False, True):
        tr = LMTrainer(model, mesh, lr=1e-3, remat=remat)
        b = tr.shard_batch(batch)
        params, opt = tr.init_state(jnp.asarray(ids[:1]))
        _, _, loss = tr.step(params, opt, b, jax.random.PRNGKey(0))
        losses[remat] = float(jax.block_until_ready(loss))
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)


def test_end_to_end_data_to_train():
    """Corpus -> pack -> batches -> prefetch(place=shard) -> train steps."""
    cfg = _tiny_config()
    model = GPT2LM(cfg.models.gpt2)
    mesh = make_mesh(MeshConfig(dp=-1))
    trainer = LMTrainer(model, mesh, lr=1e-3)
    texts = [f"the {w} ship sailed at dawn" for w in
             ("red", "old", "last", "lost", "great")] * 16
    packed = pack_tokens(texts, ENC, seq_len=16, eos_id=255)
    loader = PrefetchLoader(
        batches_from(packed, 8, epochs=1, seed=2),
        place=trainer.shard_batch,
    )
    first = next(loader)
    params, opt = trainer.init_state(first["input_ids"][:1])
    n = 0
    for batch in [first] + list(loader):
        params, opt, loss = trainer.step(params, opt, batch,
                                         jax.random.PRNGKey(n))
        n += 1
    assert n >= 2
    assert np.isfinite(float(jax.block_until_ready(loss)))


def test_context_parallel_forward_matches_plain(cfg):
    """GPT-2 forward with sequence-sharded zigzag attention == plain
    forward (logits compared after undoing the zigzag permutation)."""
    from cassmantle_tpu.ops.attention import context_parallel
    from cassmantle_tpu.parallel.ring import (
        zigzag_permute,
        zigzag_unpermute,
    )

    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    model = GPT2LM(cfg.models.gpt2)
    b, s = 2, 32                      # S % 2*sp == 0
    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, s), 0, cfg.models.gpt2.vocab_size)
    params = model.init(jax.random.PRNGKey(1), ids)

    ref = model.apply(params, ids)    # plain causal forward

    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    ids_z = zigzag_permute(ids, 4, axis=1)
    pos_z = zigzag_permute(positions, 4, axis=1)
    with context_parallel(mesh, "sp", batch_axis="dp"):
        out_z = jax.jit(
            lambda p, i, pos: model.apply(p, i, None, pos)
        )(params, ids_z, pos_z)
    out = zigzag_unpermute(out_z, 4, axis=1)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_context_parallel_train_step_loss_matches_plain(cfg):
    """One optimizer step in context-parallel mode produces the same
    loss as the plain dp trainer on the same (fully valid) data."""
    mesh_cp = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    mesh_dp = make_mesh(MeshConfig(dp=8))
    model = GPT2LM(cfg.models.gpt2)

    b, s = 8, 32
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.models.gpt2.vocab_size, size=(b, s),
                       dtype=np.int32)
    mask = np.ones((b, s), np.int32)

    plain = LMTrainer(model, mesh_dp)
    cp = LMTrainer(model, mesh_cp, context_parallel=True)

    pb = plain.prepare_batch(ids, mask)
    cb = cp.prepare_batch(ids, mask)
    assert cb["input_ids"].shape == (b, s)

    p0, o0 = plain.init_state(pb["input_ids"], seed=3)
    p1, o1 = cp.init_state(cb["input_ids"], seed=3)
    _, _, l_plain = plain.step(p0, o0, pb, jax.random.PRNGKey(0))
    _, _, l_cp = cp.step(p1, o1, cb, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(l_cp), float(l_plain), rtol=2e-4)


def test_prepare_long_context_batch_shift_before_permute():
    """Targets must be the NATURAL-order next token, not the permuted
    neighbor."""
    from cassmantle_tpu.parallel.lm_train import (
        prepare_long_context_batch,
    )

    ids = np.arange(16, dtype=np.int32)[None, :]          # 0..15
    mask = np.ones((1, 16), np.int32)
    batch = prepare_long_context_batch(ids, mask, n_sp=2)
    ids_z = np.asarray(batch["input_ids"])[0]
    tgt_z = np.asarray(batch["targets"])[0]
    pos_z = np.asarray(batch["positions"])[0]
    # wherever token t sits after permutation, its target is t+1
    for i in range(16):
        tok = ids_z[i]
        assert pos_z[i] == tok                    # position rides along
        if tok < 15:
            assert tgt_z[i] == tok + 1
        else:
            assert np.asarray(batch["loss_mask"])[0, i] == 0


def test_context_parallel_rejects_interior_zero_mask():
    from cassmantle_tpu.parallel.lm_train import (
        prepare_long_context_batch,
    )

    ids = np.zeros((1, 16), np.int32)
    mask = np.ones((1, 16), np.int32)
    mask[0, 5:8] = 0                      # interior zeros -> reject
    with pytest.raises(ValueError, match="tail-pad"):
        prepare_long_context_batch(ids, mask, n_sp=2)
    mask = np.ones((1, 16), np.int32)
    mask[0, 12:] = 0                      # tail pad -> fine
    prepare_long_context_batch(ids, mask, n_sp=2)


def test_context_parallel_mistral_forward_matches_plain():
    """Mistral cp mode (RoPE from explicit positions, zigzag causal
    attention) == plain forward, for sequences within the window (where
    the band mask degenerates to causal)."""
    from cassmantle_tpu.ops.attention import context_parallel
    from cassmantle_tpu.parallel.ring import (
        zigzag_permute,
        zigzag_unpermute,
    )

    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    mcfg = MistralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_positions=64,
        sliding_window=64, dtype="float32",
    )
    model = MistralLM(mcfg)
    b, s = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(0), (b, s), 0, 64)
    params = model.init(jax.random.PRNGKey(1), ids)
    ref = model.apply(params, ids)

    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    ids_z = zigzag_permute(ids, 4, axis=1)
    pos_z = zigzag_permute(positions, 4, axis=1)
    with context_parallel(mesh, "sp", batch_axis="dp"):
        out_z = jax.jit(
            lambda p, i, pos: model.apply(p, i, None, pos)
        )(params, ids_z, pos_z)
    out = zigzag_unpermute(out_z, 4, axis=1)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_context_parallel_mistral_rejects_overlong_sequence():
    mcfg = MistralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=1, num_heads=4, num_kv_heads=2, max_positions=64,
        sliding_window=16, dtype="float32",
    )
    model = MistralLM(mcfg)
    ids = jnp.zeros((1, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    pos = jnp.broadcast_to(jnp.arange(32)[None, :], (1, 32))
    with pytest.raises(AssertionError, match="sliding_window"):
        model.apply(params, ids, None, pos)


def test_context_parallel_rejects_positionless_model():
    """The constructor guard: a model whose __call__ lacks `positions`
    fails fast with a clear TypeError, not at trace time."""

    class NoPositionsLM(GPT2LM):
        def __call__(self, input_ids, valid=None):  # noqa: D401
            return super().__call__(input_ids, valid)

    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    cfg = _tiny_config()
    with pytest.raises(TypeError, match="positions"):
        LMTrainer(NoPositionsLM(cfg.models.gpt2), mesh,
                  context_parallel=True)
