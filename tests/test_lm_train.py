"""LM training stack tests: packing, prefetch loader, and the distributed
LM train step on the 8-device CPU mesh for BOTH prompt-LM families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import MeshConfig, MistralConfig, test_config
from cassmantle_tpu.models.gpt2 import GPT2LM
from cassmantle_tpu.models.mistral import MistralLM
from cassmantle_tpu.parallel.lm_train import LMTrainer, next_token_loss
from cassmantle_tpu.parallel.mesh import make_mesh
from cassmantle_tpu.utils.data import (
    PrefetchLoader,
    batches_from,
    pack_tokens,
)

ENC = lambda s: [ord(c) % 250 for c in s]  # noqa: E731


def test_pack_tokens_dense_rows():
    packed = pack_tokens(["abc", "defg"], ENC, seq_len=4, eos_id=255)
    ids, mask = packed["input_ids"], packed["loss_mask"]
    # stream: a b c EOS d e f g EOS -> 9 tokens -> 3 rows of 4, 3 pad
    assert ids.shape == (3, 4) and mask.shape == (3, 4)
    assert ids[0].tolist() == [ord("a") % 250, ord("b") % 250,
                               ord("c") % 250, 255]
    assert mask[:2].min() == 1           # full rows all real
    assert mask[2].tolist() == [1, 0, 0, 0]
    assert ids[2, 1:].tolist() == [255, 255, 255]


def test_pack_tokens_empty():
    packed = pack_tokens([], ENC, seq_len=8, eos_id=1)
    assert packed["input_ids"].shape == (0, 8)


def test_batches_from_epochs_and_shapes():
    packed = pack_tokens(["hello world"] * 10, ENC, seq_len=4, eos_id=255)
    batches = list(batches_from(packed, 8, epochs=2, seed=1))
    n = packed["input_ids"].shape[0]
    assert len(batches) == 2 * (n // 8)
    assert all(b["input_ids"].shape == (8, 4) for b in batches)
    # shuffling: two epochs see different row orders; rows must use
    # distinguishable content for the assertion to mean anything
    packed2 = {
        "input_ids": np.arange(64, dtype=np.int32).reshape(16, 4),
        "loss_mask": np.ones((16, 4), np.int32),
    }
    two = list(batches_from(packed2, 8, epochs=2, seed=3))
    e1 = np.concatenate([b["input_ids"] for b in two[:2]])
    e2 = np.concatenate([b["input_ids"] for b in two[2:]])
    assert e1.shape == e2.shape
    assert not np.array_equal(e1, e2)
    # and unshuffled epochs repeat exactly
    two_ns = list(batches_from(packed2, 8, epochs=2, shuffle=False))
    np.testing.assert_array_equal(two_ns[0]["input_ids"],
                                  two_ns[2]["input_ids"])


def test_prefetch_loader_order_and_error():
    batches = [{"x": np.full((2,), i)} for i in range(5)]
    out = list(PrefetchLoader(batches, depth=2))
    assert [int(b["x"][0]) for b in out] == [0, 1, 2, 3, 4]

    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("boom")

    loader = PrefetchLoader(bad())
    next(loader)
    with pytest.raises(RuntimeError, match="boom"):
        next(loader)


def test_batches_from_rejects_undersized_corpus():
    packed = pack_tokens(["ab"], ENC, seq_len=4, eos_id=255)
    with pytest.raises(ValueError, match="batch_size"):
        next(batches_from(packed, 8))


def test_prefetch_loader_exhaustion_is_sticky():
    loader = PrefetchLoader([{"x": np.zeros(1)}])
    assert len(list(loader)) == 1
    with pytest.raises(StopIteration):
        next(loader)  # second next() raises again instead of deadlocking
    with pytest.raises(StopIteration):
        next(loader)


def test_next_token_loss_masks_padding():
    v = 16
    logits = jnp.zeros((1, 4, v))
    ids = jnp.asarray([[1, 2, 3, 0]], dtype=jnp.int32)
    full = next_token_loss(logits, ids, jnp.ones((1, 4), jnp.int32))
    # uniform logits -> loss log(v) regardless of targets
    np.testing.assert_allclose(float(full), np.log(v), rtol=1e-5)
    # masking the pad tail must not change the uniform value but must
    # change the denominator; make one target "right" to see the effect
    peaked = logits.at[0, 2, 0].set(10.0)  # predicts target at pos 3
    m_all = next_token_loss(peaked, ids, jnp.ones((1, 4), jnp.int32))
    m_pad = next_token_loss(
        peaked, ids, jnp.asarray([[1, 1, 1, 0]], jnp.int32)
    )
    assert float(m_pad) > float(m_all)  # the easy (peaked) position at
    # the masked tail no longer pulls the mean down


@pytest.mark.parametrize("family", ["gpt2", "mistral"])
def test_lm_trainer_step_runs_and_learns(family):
    cfg = test_config()
    if family == "gpt2":
        model = GPT2LM(cfg.models.gpt2)
        vocab = cfg.models.gpt2.vocab_size
    else:
        model = MistralLM(MistralConfig.tiny())
        vocab = MistralConfig.tiny().vocab_size
    mesh = make_mesh(MeshConfig(dp=-1, tp=2))
    trainer = LMTrainer(model, mesh, lr=1e-2)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (8, 12)).astype(np.int32)
    batch = trainer.shard_batch({
        "input_ids": ids,
        "loss_mask": np.ones_like(ids),
    })
    params, opt_state = trainer.init_state(jnp.asarray(ids[:1]))
    losses = []
    for i in range(5):
        params, opt_state, loss = trainer.step(
            params, opt_state, batch, jax.random.PRNGKey(i)
        )
        losses.append(float(jax.block_until_ready(loss)))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizes the fixed batch


def test_lm_trainer_remat_matches():
    cfg = test_config()
    model = GPT2LM(cfg.models.gpt2)
    mesh = make_mesh(MeshConfig(dp=-1))
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.models.gpt2.vocab_size, (8, 8)).astype(
        np.int32)
    batch = {"input_ids": ids, "loss_mask": np.ones_like(ids)}

    losses = {}
    for remat in (False, True):
        tr = LMTrainer(model, mesh, lr=1e-3, remat=remat)
        b = tr.shard_batch(batch)
        params, opt = tr.init_state(jnp.asarray(ids[:1]))
        _, _, loss = tr.step(params, opt, b, jax.random.PRNGKey(0))
        losses[remat] = float(jax.block_until_ready(loss))
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)


def test_end_to_end_data_to_train():
    """Corpus -> pack -> batches -> prefetch(place=shard) -> train steps."""
    cfg = test_config()
    model = GPT2LM(cfg.models.gpt2)
    mesh = make_mesh(MeshConfig(dp=-1))
    trainer = LMTrainer(model, mesh, lr=1e-3)
    texts = [f"the {w} ship sailed at dawn" for w in
             ("red", "old", "last", "lost", "great")] * 16
    packed = pack_tokens(texts, ENC, seq_len=16, eos_id=255)
    loader = PrefetchLoader(
        batches_from(packed, 8, epochs=1, seed=2),
        place=trainer.shard_batch,
    )
    first = next(loader)
    params, opt = trainer.init_state(first["input_ids"][:1])
    n = 0
    for batch in [first] + list(loader):
        params, opt, loss = trainer.step(params, opt, batch,
                                         jax.random.PRNGKey(n))
        n += 1
    assert n >= 2
    assert np.isfinite(float(jax.block_until_ready(loss)))
