"""Exception-flow & resource-lifecycle lint + leak sentinel gate
(fast tier).

Golden fixture snippets pin each rule of the three
``cassmantle_tpu/analysis`` lifecycle passes (known violations must
fail; suppressed / fixed variants must pass). Two repo-history shapes
are pinned as golden violating/fixed pairs the way PR 4 pinned the
PR 1 dispatch deadlock for ``lock-order-cycle``:

- the **PR 6 stop-stranding** shape (``future-discipline``): a class
  that enqueues futures its ``stop()`` only cancels — the queued
  futures stay pending forever;
- the **PR 8 cancel-swallow** shape (``swallowed-error``): a loop
  handler in an async pump that eats ``CancelledError``, making the
  task uncancellable (gh-86296) so ``close()`` awaits it forever.

The repo itself must lint clean through the real entry point
(``tools/check_lifecycle.py``), ``tools/lint_all.py`` must actually
run the lifecycle passes in its one walk, and the
``utils/leak_sentinel`` runtime counterpart must fail seeded
thread/task/fd leaks with the leaker's creation site while staying
vacuous when disarmed and log-only in prod ``scan()`` mode.
"""

import os
import textwrap
import threading

import pytest

from cassmantle_tpu.analysis.core import parse_source, run_passes
from cassmantle_tpu.analysis.exceptionflow import ExceptionFlowPass
from cassmantle_tpu.analysis.futuredisc import FutureDisciplinePass
from cassmantle_tpu.analysis.lifecycle import LifecyclePass
from cassmantle_tpu.utils import leak_sentinel
from cassmantle_tpu.utils.leak_sentinel import LeakError


def lint(src, *passes, rel="<fixture>"):
    return run_passes([parse_source(textwrap.dedent(src), rel)],
                      list(passes))


def rules(findings):
    return [f.rule for f in findings]


# -- swallowed-error ---------------------------------------------------------

def test_log_only_broad_except_fails_and_suppression_passes():
    src = """
        def handle(self, req):
            try:
                return self.dispatch(req)
            except Exception:{sup}
                log.warning("dispatch failed")
    """
    findings = lint(src.format(sup=""), ExceptionFlowPass())
    assert rules(findings) == ["swallowed-error"]
    assert "unobservable" in findings[0].message
    sup = "  # lint: ignore[swallowed-error] — fixture reason"
    assert lint(src.format(sup=sup), ExceptionFlowPass()) == []


def test_metric_record_reraise_and_narrow_catches_are_clean():
    assert lint("""
        def a(self, req):
            try:
                return self.dispatch(req)
            except Exception:
                metrics.inc("dispatch.failures")

        def b(self, req):
            try:
                return self.dispatch(req)
            except Exception as exc:
                flight_recorder.record("dispatch.error", err=str(exc))

        def c(self, req):
            try:
                return self.dispatch(req)
            except Exception:
                log.warning("context for the re-raise")
                raise

        def d(self, req):
            try:
                return self.table[req]
            except KeyError:
                return None
    """, ExceptionFlowPass()) == []


def test_pr8_cancel_swallow_pump_fails_and_reraise_fixes_it():
    """The golden PR 8 pair: the replication pump whose loop handler
    ate CancelledError left close() awaiting an uncancellable task
    (gh-86296). The violating shape fails; ``raise`` fixes it."""
    violating = """
        async def _pump(self):
            while True:
                try:
                    await self._ship_once()
                except asyncio.CancelledError:
                    pass
                except Exception:
                    metrics.inc("repl.pump_errors")
    """
    findings = lint(violating, ExceptionFlowPass())
    assert rules(findings) == ["swallowed-error"]
    assert "gh-86296" in findings[0].message
    fixed = violating.replace("pass", "raise")
    assert lint(fixed, ExceptionFlowPass()) == []


def test_cancelled_task_reap_idiom_is_exempt():
    # awaiting a task you just cancelled raises its CancelledError at
    # you — suppressing that is teardown, not swallowing
    assert lint("""
        async def reap(self):
            self._task.cancel()
            try:
                await self._task
            except Exception:
                pass
    """, ExceptionFlowPass()) == []


# -- overbroad-except --------------------------------------------------------

def test_bare_except_on_hot_path_fails_and_suppression_passes():
    src = """
        def fetch(self):
            try:
                return self._get()
            except BaseException:{sup}
                return None
    """
    findings = lint(src.format(sup=""), ExceptionFlowPass())
    assert rules(findings) == ["overbroad-except"]
    sup = "  # lint: ignore[overbroad-except] — fixture reason"
    assert lint(src.format(sup=sup), ExceptionFlowPass()) == []


def test_shutdown_path_exempts_overbroad_but_not_swallow():
    # stop() may catch broadest, but a silent pass is still a swallow:
    # the stronger overbroad claim is waived, the visibility one is not
    findings = lint("""
        def stop(self):
            try:
                self._sock.close()
            except BaseException:
                pass
    """, ExceptionFlowPass())
    assert rules(findings) == ["swallowed-error"]


def test_carrier_that_set_exceptions_a_future_is_clean():
    # the dispatch-thread carrier shape: broadest catch whose whole job
    # is handing the error to the waiter
    assert lint("""
        def _worker(self, fut):
            try:
                fut.set_result(self._run())
            except BaseException as exc:
                fut.set_exception(exc)
    """, ExceptionFlowPass()) == []


def test_exceptionflow_scoped_to_containment_layers():
    src = """
        def handle(self, req):
            try:
                return self.dispatch(req)
            except Exception:
                log.warning("boom")
    """
    p = ExceptionFlowPass.for_repo()
    assert lint(src, p, rel="cassmantle_tpu/ops/attn.py") == []
    assert rules(lint(src, p, rel="cassmantle_tpu/serving/x.py")) == \
        ["swallowed-error"]


# -- future-discipline: error-path stranding ---------------------------------

def test_error_path_stranding_fails_and_set_exception_fixes_it():
    violating = """
        def _complete(self, payload):
            fut = loop.create_future()
            try:
                fut.set_result(self._decode(payload))
            except Exception:
                log.warning("decode failed")
            return fut
    """
    findings = lint(violating, FutureDisciplinePass())
    assert rules(findings) == ["future-discipline"]
    assert "strands waiter" in findings[0].message
    fixed = violating.replace(
        'log.warning("decode failed")',
        "fut.set_exception(exc)").replace(
        "except Exception:", "except Exception as exc:")
    assert lint(fixed, FutureDisciplinePass()) == []


def test_error_path_that_reraises_is_clean():
    assert lint("""
        def _complete(self, payload):
            fut = loop.create_future()
            try:
                fut.set_result(self._decode(payload))
            except Exception:
                raise
            return fut
    """, FutureDisciplinePass()) == []


# -- future-discipline: unguarded set ----------------------------------------

def test_unguarded_set_on_foreign_future_fails_and_guard_fixes_it():
    src = """
        def finish(self, fut, value):
            {body}
    """
    findings = lint(src.format(body="fut.set_result(value)"),
                    FutureDisciplinePass())
    assert rules(findings) == ["future-discipline"]
    assert "InvalidStateError" in findings[0].message
    guarded = "if not fut.done():\n                fut.set_result(value)"
    assert lint(src.format(body=guarded), FutureDisciplinePass()) == []


def test_suppress_invalidstate_and_creator_sets_are_clean():
    assert lint("""
        def finish(self, fut, value):
            with contextlib.suppress(asyncio.InvalidStateError):
                fut.set_result(value)

        def mint(self):
            fut = loop.create_future()
            fut.set_result(None)   # creator is the sole resolver
            return fut
    """, FutureDisciplinePass()) == []


# -- future-discipline: the PR 6 stop-strand pair ----------------------------

PR6_VIOLATING = """
    class BatchQueue:
        def submit(self, item):
            fut = concurrent.futures.Future()
            self._jobs.put((item, fut))
            return fut

        def stop(self):{sup}
            self._task.cancel()
"""

PR6_FIXED = """
    class BatchQueue:
        def submit(self, item):
            fut = concurrent.futures.Future()
            self._jobs.put((item, fut))
            return fut

        def stop(self):
            self._task.cancel()
            self._drain_pending()

        def _drain_pending(self):
            while not self._jobs.empty():
                _, fut = self._jobs.get_nowait()
                if not fut.done():   # a racing completer may have won
                    fut.set_exception(RuntimeError("queue stopped"))
"""


def test_pr6_stop_strand_fails_and_drain_fixes_it():
    """The golden PR 6 pair: stop() that only cancels the consumer
    strands every queued future (callers block in cf.result()
    forever); the drain + set_exception fix is clean."""
    findings = lint(PR6_VIOLATING.format(sup=""), FutureDisciplinePass())
    assert rules(findings) == ["future-discipline"]
    assert "PR 6" in findings[0].message
    assert "cancelling the consumer task is not enough" in \
        findings[0].message
    assert lint(PR6_FIXED, FutureDisciplinePass()) == []


def test_pr6_shape_suppression_passes():
    sup = "  # lint: ignore[future-discipline] — fixture reason"
    assert lint(PR6_VIOLATING.format(sup=sup),
                FutureDisciplinePass()) == []


# -- task-leak ---------------------------------------------------------------

def test_fire_and_forget_create_task_fails_and_suppression_passes():
    src = """
        async def kick(self):
            asyncio.create_task(self._refresh()){sup}
    """
    findings = lint(src.format(sup=""), LifecyclePass())
    assert rules(findings) == ["task-leak"]
    assert "GC'd mid-flight" in findings[0].message
    sup = "  # lint: ignore[task-leak] — fixture reason"
    assert lint(src.format(sup=sup), LifecyclePass()) == []


def test_stored_and_callback_retained_tasks_are_clean():
    assert lint("""
        async def kick(self):
            self._refresher = asyncio.create_task(self._refresh())
            asyncio.create_task(self._probe()).add_done_callback(_log)
    """, LifecyclePass()) == []


# -- thread-leak -------------------------------------------------------------

def test_stop_without_join_fails_and_bounded_join_fixes_it():
    src = """
        class Worker:
            def start(self):
                self._thread = threading.Thread(
                    target=self._run, daemon=True)
                self._thread.start(){sup}

            def stop(self):
                {body}
    """
    findings = lint(src.format(sup="", body="self._stopping = True"),
                    LifecyclePass())
    assert rules(findings) == ["thread-leak"]
    assert "never joins" in findings[0].message
    assert lint(src.format(
        sup="", body="self._thread.join(timeout=5.0)"),
        LifecyclePass()) == []
    sup = "  # lint: ignore[thread-leak] — fixture reason"
    assert lint(src.format(sup=sup, body="self._stopping = True"),
                LifecyclePass()) == []


def test_grab_under_lock_alias_join_counts():
    # the serving/queue.py _DispatchWorker.stop() idiom: snapshot the
    # attrs under the lock, join the local alias outside it
    assert lint("""
        class Worker:
            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def stop(self):
                jobs, thread = self._jobs, self._thread
                jobs.put(None)
                thread.join(timeout=5.0)
    """, LifecyclePass()) == []


def test_nondaemon_thread_with_no_stop_path_fails():
    findings = lint("""
        class Prober:
            def boot(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
    """, LifecyclePass())
    assert rules(findings) == ["thread-leak"]
    assert "no stop()/close() at all" in findings[0].message


def test_anonymous_thread_fails_unless_daemon():
    findings = lint("""
        def fire(work):
            threading.Thread(target=work).start()
    """, LifecyclePass())
    assert rules(findings) == ["thread-leak"]
    assert "anonymous non-daemon" in findings[0].message
    # deliberate fire-and-forget daemons are the documented blind spot
    # the runtime sentinel's allowlist mirrors
    assert lint("""
        def fire(work):
            threading.Thread(target=work, daemon=True).start()
    """, LifecyclePass()) == []


def test_local_thread_joined_or_handed_off_is_clean():
    findings = lint("""
        def probe_once(target):
            t = threading.Thread(target=target)
            t.start()
    """, LifecyclePass())
    assert rules(findings) == ["thread-leak"]
    assert lint("""
        def probe_once(target):
            t = threading.Thread(target=target)
            t.start()
            t.join(timeout=2.0)

        def spawn(target, registry):
            t = threading.Thread(target=target)
            t.start()
            registry.adopt(t)   # ownership transfer
            return t
    """, LifecyclePass()) == []


# -- resource-leak -----------------------------------------------------------

def test_class_resource_without_close_path_fails_and_close_fixes_it():
    src = """
        class Sink:
            def open_log(self):
                self._fh = open("/tmp/x.log", "a"){sup}

            def stop(self):
                {body}
    """
    findings = lint(src.format(sup="", body="self._stopping = True"),
                    LifecyclePass())
    assert rules(findings) == ["resource-leak"]
    assert "EMFILE" in findings[0].message
    assert lint(src.format(sup="", body="self._fh.close()"),
                LifecyclePass()) == []
    sup = "  # lint: ignore[resource-leak] — fixture reason"
    assert lint(src.format(sup=sup, body="self._stopping = True"),
                LifecyclePass()) == []


def test_local_resource_leak_fails_with_and_transfer_clean():
    findings = lint("""
        def slurp(path):
            fh = open(path)
            data = fh.read()
            return data
    """, LifecyclePass())
    assert rules(findings) == ["resource-leak"]
    assert lint("""
        def slurp(path):
            with open(path) as fh:
                return fh.read()

        def closed(path):
            fh = open(path)
            data = fh.read()
            fh.close()
            return data

        def handoff(path):
            fh = open(path)
            return fh   # caller owns it now
    """, LifecyclePass()) == []


# -- the repo itself lints clean ---------------------------------------------

def test_repo_is_lifecycle_clean():
    from tools.check_lifecycle import check

    assert check() == []


def test_check_lifecycle_cli_exits_zero():
    from tools.check_lifecycle import main

    assert main([]) == 0


def test_lint_all_includes_lifecycle_passes():
    """lint_all's pass set must actually run the lifecycle family in
    its one walk — a task-leak fixture under a serving/ rel path goes
    red through all_passes (non-package root, so registry orphan
    directions stay out of the way)."""
    import pathlib

    from tools.lint_all import REPO, all_passes

    module = parse_source(textwrap.dedent("""
        import asyncio

        async def kick(refresh):
            asyncio.create_task(refresh())
    """), "cassmantle_tpu/serving/bad_fixture.py")
    findings = run_passes([module],
                          all_passes(pathlib.Path(REPO) / "tools"))
    assert rules(findings) == ["task-leak"]


def test_new_rules_documented():
    import pathlib

    doc = pathlib.Path(__file__).resolve().parents[1] / "docs" / \
        "STATIC_ANALYSIS.md"
    text = doc.read_text()
    for rule in ("swallowed-error", "overbroad-except",
                 "future-discipline", "task-leak", "thread-leak",
                 "resource-leak"):
        assert rule in text, f"rule {rule} missing from catalog"
    assert "leak_sentinel" in text
    assert "CASSMANTLE_LEAK_SENTINEL" in text


# -- leak sentinel (runtime counterpart) -------------------------------------
# (the autouse conftest fixture arms the sentinel + resets per test)

def test_seeded_thread_leak_fails_with_creation_site():
    release = threading.Event()
    snap = leak_sentinel.snapshot()
    t = threading.Thread(target=release.wait, name="seeded-leaker")
    t.start()
    try:
        with pytest.raises(LeakError) as exc:
            leak_sentinel.verify(snap)
        msg = str(exc.value)
        assert "seeded-leaker" in msg
        # the failure names WHO leaked: this file, the t.start() site
        assert "test_check_lifecycle.py" in msg
        assert "test_seeded_thread_leak_fails_with_creation_site" in msg
    finally:
        release.set()
        t.join(timeout=5.0)


async def test_seeded_task_leak_fails_with_creation_site():
    import asyncio

    snap = leak_sentinel.snapshot()
    task = asyncio.get_running_loop().create_task(
        asyncio.sleep(60), name="seeded-task-leaker")
    try:
        with pytest.raises(LeakError) as exc:
            leak_sentinel.verify(snap)
        msg = str(exc.value)
        assert "seeded-task-leaker" in msg
        assert "test_check_lifecycle.py" in msg
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)


def test_seeded_fd_leak_logs_by_default_and_raises_on_request():
    snap = leak_sentinel.snapshot()
    if snap["fds"] is None:
        pytest.skip("no /proc/self/fd on this platform")
    r, w = os.pipe()
    try:
        # default policy: reported, counted, never raised (lazy
        # process-lifetime caches open fds mid-suite legitimately)
        leaks = leak_sentinel.verify(snap)
        assert leaks and "fd(s) opened" in leaks[0]
        with pytest.raises(LeakError):
            leak_sentinel.verify(snap, fd_policy="raise")
    finally:
        os.close(r)
        os.close(w)


def test_disarmed_sentinel_is_vacuous():
    leak_sentinel.disable_sentinel()
    assert not leak_sentinel.sentinel_active()
    release = threading.Event()
    snap = leak_sentinel.snapshot()
    t = threading.Thread(target=release.wait)
    t.start()
    try:
        # not tracked → not reported: disarmed costs nothing and
        # claims nothing (prod default)
        assert leak_sentinel.verify(snap) == []
    finally:
        release.set()
        t.join(timeout=5.0)


def test_tasks_of_allowlisted_worker_loops_are_not_leaks():
    """Tasks created ON an allowlisted process/module-lifetime
    worker's loop (the staged server's queue getters between batches)
    are its working set, not the test's leak."""
    import asyncio

    snap = leak_sentinel.snapshot()
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, name="device-probe")
    t.start()
    made = threading.Event()
    box = {}

    def _mk():
        box["task"] = loop.create_task(asyncio.sleep(60))
        made.set()

    loop.call_soon_threadsafe(_mk)
    assert made.wait(5.0)
    try:
        # fd_policy off: the loop's own epoll/self-pipe fds are the
        # subject of teardown below, not of this assertion
        assert leak_sentinel.verify(snap, fd_policy="off") == []
    finally:
        def _fin():
            box["task"].add_done_callback(lambda _: loop.stop())
            box["task"].cancel()

        loop.call_soon_threadsafe(_fin)
        t.join(timeout=5.0)
        loop.close()


def test_dispatch_worker_stop_retires_its_thread():
    """The stop-retires-the-thread contract the `cassmantle-stage*`
    allowlist entry could otherwise mask: a DEDICATED dispatch
    worker's thread must be dead after stop() (bounded join), so a
    staged-server stop cycle abandons nothing."""
    from cassmantle_tpu.serving.queue import _DispatchWorker

    worker = _DispatchWorker("stage.test_retire", rank=21)
    fut, started = worker.submit(lambda: 42)
    assert fut.result(timeout=5.0) == 42
    thread = worker._thread
    assert thread is not None and thread.is_alive()
    worker.stop()
    assert not thread.is_alive()
    assert worker._thread is None


def test_allowlisted_singletons_are_not_leaks():
    release = threading.Event()
    snap = leak_sentinel.snapshot()
    t = threading.Thread(target=release.wait, name="device-probe")
    t.start()
    try:
        assert leak_sentinel.verify(snap) == []
    finally:
        release.set()
        t.join(timeout=5.0)


def test_prod_scan_counts_growth_log_only():
    from cassmantle_tpu.utils.logging import metrics

    before = metrics.snapshot()["counters"].get("leaks.threads", 0)
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="prod-leaker")
    t.start()
    try:
        census = leak_sentinel.scan()   # growth vs high-water: counts
        assert census["threads"] >= 1
        after = metrics.snapshot()["counters"].get("leaks.threads", 0)
        assert after >= before + 1
        # census unchanged → no new growth, no double count
        leak_sentinel.scan()
        assert metrics.snapshot()["counters"].get(
            "leaks.threads", 0) == after
    finally:
        release.set()
        t.join(timeout=5.0)
