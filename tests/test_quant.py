"""Weights-only int8 quantization (ops/quant.py): reconstruction error,
tree transforms, jit/pytree compatibility, and the quantized LM serving
path end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.quant import (
    QTensor,
    default_predicate,
    dequantize_tree,
    quantization_error,
    quantize_tensor,
    quantize_tree,
    quantized_apply,
    tree_nbytes,
)


def test_quantize_tensor_roundtrip_error():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 512), jnp.float32)
    q = quantize_tensor(w)
    assert q.data.dtype == jnp.int8
    assert q.scale.shape == (1, 512)          # per-out-channel
    # int8 symmetric quantization of a gaussian: ~0.2-0.7% relative L2
    assert quantization_error(w) < 0.01


def test_quantize_exact_for_scaled_ints():
    # values that are exact multiples of absmax/127 reconstruct exactly
    base = jnp.asarray(np.arange(-127, 128, dtype=np.float32))[:, None]
    w = jnp.tile(base, (1, 4)) * 0.037
    q = quantize_tensor(w)
    np.testing.assert_allclose(np.asarray(q.dequantize(jnp.float32)),
                               np.asarray(w), rtol=1e-6)


def test_matmul_semantics_per_channel():
    # x @ dequant(W) must equal (x @ W8) * s: per-output-channel scales
    rng = jax.random.PRNGKey(1)
    w = jax.random.normal(rng, (64, 32), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 64), jnp.float32)
    q = quantize_tensor(w)
    lhs = x @ q.dequantize(jnp.float32)
    rhs = (x @ q.data.astype(jnp.float32)) * q.scale[0][None, :]
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs),
                               rtol=1e-5, atol=1e-5)


def test_zero_channel_safe():
    w = jnp.zeros((16, 8), jnp.float32)
    q = quantize_tensor(w)
    assert np.all(np.isfinite(np.asarray(q.scale)))
    np.testing.assert_array_equal(np.asarray(q.dequantize(jnp.float32)), 0)


def test_tree_transform_selects_kernels_only():
    tree = {
        "dense": {"kernel": jnp.ones((512, 512)), "bias": jnp.ones((512,))},
        "emb": {"embedding": jnp.ones((1000, 512))},
        "tiny": {"kernel": jnp.ones((4, 4))},
        "ln": {"scale": jnp.ones((512,))},
    }
    qt = quantize_tree(tree)
    assert isinstance(qt["dense"]["kernel"], QTensor)
    assert not isinstance(qt["emb"]["embedding"], QTensor)   # embeddings stay
    assert not isinstance(qt["tiny"]["kernel"], QTensor)     # too small
    assert not isinstance(qt["ln"]["scale"], QTensor)
    # footprint: the big kernel shrinks ~4x (fp32 -> int8 + scales);
    # untouched leaves (embedding here) keep their bytes
    assert tree_nbytes(qt["dense"]) < 0.3 * tree_nbytes(tree["dense"])
    assert tree_nbytes(qt["emb"]) == tree_nbytes(tree["emb"])
    back = dequantize_tree(qt, jnp.float32)
    assert back["dense"]["kernel"].dtype == jnp.float32
    assert back["dense"]["kernel"].shape == (512, 512)


def test_default_predicate_paths():
    big = jnp.ones((512, 512))
    assert default_predicate(("layer", "kernel"), big)
    assert not default_predicate(("layer", "bias"), jnp.ones((512,)))
    assert default_predicate((), big) is False  # empty path: no name


def test_qtensor_through_jit():
    # QTensor trees cross the jit boundary as pytrees; dequant inside
    w = jax.random.normal(jax.random.PRNGKey(3), (128, 512), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 128), jnp.float32)
    tree = quantize_tree({"m": {"kernel": w}})

    @jax.jit
    def f(qt, x):
        d = dequantize_tree(qt, jnp.float32)
        return x @ d["m"]["kernel"]

    out = f(tree, x)
    ref = x @ quantize_tensor(w).dequantize(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_quantized_apply_wrapper():
    w = jax.random.normal(jax.random.PRNGKey(5), (300, 300), jnp.float32)
    tree = {"m": {"kernel": w}}

    def apply_fn(params, x):
        return x @ params["m"]["kernel"]

    x = jax.random.normal(jax.random.PRNGKey(6), (4, 300), jnp.float32)
    qout = quantized_apply(apply_fn, jnp.float32)(quantize_tree(tree), x)
    ref = apply_fn(tree, x)
    # w8a16 noise on a 300-dim contraction stays ~1%
    err = float(jnp.linalg.norm(qout - ref) / jnp.linalg.norm(ref))
    assert err < 0.02


def test_quantized_lm_decode_end_to_end(cfg, monkeypatch):
    """The serving path with lm_int8: quantized GPT-2 decodes sane tokens
    with int8 kernels in the tree. The test config's kernels sit below
    the production size threshold, so drop it for this test."""
    import dataclasses

    import cassmantle_tpu.ops.quant as quant
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    monkeypatch.setattr(
        quant, "default_predicate",
        lambda path, leaf: "kernel" in str(path[-1] if path else "")
        and getattr(leaf, "ndim", 0) >= 2)

    qcfg = cfg.replace(
        models=dataclasses.replace(cfg.models, lm_int8=True))
    gen_fp = PromptGenerator(cfg)
    gen_q = PromptGenerator(qcfg)

    toks_fp, len_fp = gen_fp.decode_ids("the storm rose", max_new_tokens=8)
    toks_q, len_q = gen_q.decode_ids("the storm rose", max_new_tokens=8)
    assert toks_q.shape == toks_fp.shape
    assert int(len_q[0]) >= 1
    # tiny random-init model: quantization noise may flip argmaxes, so
    # assert the mechanism (int8 storage) rather than token equality
    from cassmantle_tpu.ops.quant import QTensor as QT

    leaves = jax.tree_util.tree_leaves(
        gen_q.params, is_leaf=lambda x: isinstance(x, QT))
    assert any(isinstance(leaf, QT) for leaf in leaves)


def test_save_load_quantized_roundtrip(tmp_path):
    from cassmantle_tpu.ops.quant import load_quantized, save_quantized

    w = jax.random.normal(jax.random.PRNGKey(7), (300, 300))
    tree = quantize_tree({"a": {"kernel": w, "bias": jnp.ones((300,))}})
    path = str(tmp_path / "q.safetensors")
    save_quantized(tree, path)
    back = load_quantized(path)
    q0, q1 = tree["a"]["kernel"], back["a"]["kernel"]
    assert isinstance(q1, QTensor)
    np.testing.assert_array_equal(np.asarray(q0.data), np.asarray(q1.data))
    np.testing.assert_allclose(np.asarray(q0.scale), np.asarray(q1.scale))
    np.testing.assert_array_equal(np.asarray(back["a"]["bias"]),
                                  np.ones((300,)))


def test_prompt_generator_int8_checkpoint_boot(cfg, tmp_path, monkeypatch):
    """Quantize once, save, boot again from the int8 file: identical
    quantized params, no fp load."""
    import dataclasses

    import cassmantle_tpu.ops.quant as quant
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    monkeypatch.setattr(
        quant, "default_predicate",
        lambda path, leaf: "kernel" in str(path[-1] if path else "")
        and getattr(leaf, "ndim", 0) >= 2)
    qcfg = cfg.replace(models=dataclasses.replace(cfg.models, lm_int8=True))

    gen1 = PromptGenerator(qcfg, weights_dir=str(tmp_path))
    path = gen1.save_quantized()
    assert path.endswith("gpt2.int8.safetensors")

    gen2 = PromptGenerator(qcfg, weights_dir=str(tmp_path))
    l1 = jax.tree_util.tree_leaves(
        gen1.params, is_leaf=lambda x: isinstance(x, QTensor))
    l2 = jax.tree_util.tree_leaves(
        gen2.params, is_leaf=lambda x: isinstance(x, QTensor))
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        if isinstance(a, QTensor):
            assert isinstance(b, QTensor)
            np.testing.assert_array_equal(np.asarray(a.data),
                                          np.asarray(b.data))
    # and the loaded generator still decodes
    toks, n = gen2.decode_ids("the storm", max_new_tokens=4)
    assert toks.shape[1] == 4


def test_unet_int8_pipeline_generates():
    """unet_int8 config: the pipeline quantizes UNet kernels to int8
    QTensors (footprint shrinks), dequantizes inside the jit, and still
    generates images — including through the deepcache turbo path and
    img2img."""
    import dataclasses

    import numpy as np

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.ops.quant import QTensor, tree_nbytes
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    base = test_config()
    cfg = base.replace(models=dataclasses.replace(
        base.models, unet_int8=True))
    pipe = Text2ImagePipeline(cfg)
    q_leaves = [leaf for leaf in jax.tree_util.tree_leaves(
        pipe.unet_params,
        is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(leaf, QTensor)]
    assert q_leaves, "expected quantized kernels in the int8 UNet tree"
    fp = Text2ImagePipeline(base)
    assert tree_nbytes(pipe.unet_params) < tree_nbytes(fp.unet_params)
    imgs = pipe.generate(["a tin lantern in fog"], seed=5)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8

    turbo = base.replace(
        models=dataclasses.replace(base.models, unet_int8=True),
        sampler=dataclasses.replace(
            base.sampler, kind="dpmpp_2m", num_steps=4, deepcache=True))
    imgs = Text2ImagePipeline(turbo).generate(["a paper boat"], seed=6)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8

    # img2img consumes the same quantized unet_apply via its own
    # denoiser construction — exercise that path too
    size = cfg.sampler.image_size
    src = np.zeros((1, size, size, 3), dtype=np.uint8)
    out = pipe.generate_img2img(src, ["a tin lantern"], strength=0.5,
                                seed=7)
    assert out.shape[-1] == 3 and out.dtype == np.uint8


def test_fp_arm_joining_int8_donor_reports_honest_weights_flag():
    """The fp-joins-int8-donor path re-loads its own UNet (dequant is
    lossy); the donor's loaded_real_weights flag must not vouch for a
    load the donor never did — if the checkpoint is gone by then, the
    fp arm is random-init and must report False (ADVICE r2)."""
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    base = test_config()
    int8_cfg = base.replace(models=dataclasses.replace(
        base.models, unet_int8=True))
    donor = Text2ImagePipeline(int8_cfg)
    donor.loaded_real_weights = True  # simulate a weights-provisioned donor
    fp = Text2ImagePipeline(base, share_params_with=donor)
    assert fp.loaded_real_weights is False

    # same-arch arm taking every tensor from the donor keeps its word
    clone = Text2ImagePipeline(int8_cfg, share_params_with=donor)
    assert clone.loaded_real_weights is True


def test_lm_int8_ab_tool_smoke(tmp_path):
    """tools/lm_int8_ab.py runs both arms end to end at tiny dims on
    CPU and emits one comparable JSON report (the on-hardware A/B the
    int8 claims are gated on uses the same code path)."""
    import json
    import subprocess
    import sys

    import os

    tool = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "lm_int8_ab.py")
    out = tmp_path / "ab.json"
    proc = subprocess.run(
        [sys.executable, tool, "--tiny",
         "--platform", "cpu", "--tokens", "8", "--reps", "1",
         "--out", str(out)],
        capture_output=True, text=True, timeout=480,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out.read_text())
    assert report["fp"]["tokens_per_sec"] > 0
    assert report["int8"]["tokens_per_sec"] > 0
    assert "speedup" in report and "param_shrink" in report
    # tiny dims: nothing meets the quantization size predicate, and the
    # report must SAY so rather than look like a measurement
    assert report["int8"]["quantized_leaves"] == 0
    assert report["tiny"] is True


def test_lm_int8_ab_quantizes_at_real_predicate(monkeypatch):
    """With the size predicate lowered to tiny dims, the int8 arm
    actually quantizes and the tree shrinks — the property the real
    GPT-2/Mistral run exercises at full size."""
    import dataclasses

    import cassmantle_tpu.ops.quant as quant

    orig = quant.default_predicate
    monkeypatch.setattr(
        quant, "default_predicate",
        lambda path, leaf: orig(path, leaf) or (
            "kernel" in str(path[-1]) and leaf.ndim >= 2
            and leaf.size >= 1024))

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.ops.quant import QTensor, tree_nbytes
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    base = test_config()
    fp_cfg = base
    q_cfg = base.replace(models=dataclasses.replace(
        base.models, lm_int8=True))
    fp = PromptGenerator(fp_cfg)
    q = PromptGenerator(q_cfg)
    q_leaves = [leaf for leaf in jax.tree_util.tree_leaves(
        q.params, is_leaf=lambda x: isinstance(x, QTensor))
        if isinstance(leaf, QTensor)]
    assert q_leaves
    assert tree_nbytes(q.params) < tree_nbytes(fp.params)
    text = q.generate("The storm", max_new_tokens=8)
    assert isinstance(text, str) and text
