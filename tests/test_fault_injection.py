"""Fault injection for the round lifecycle (SURVEY.md §5.3).

The reference's failure story is skip-don't-crash: failed generation
leaves the buffer empty and the old round silently replays (reference
backend.py:211-215), retries wrap each API call (utils.py:43-61), and
lock contention skips rather than errors (backend.py:123-125). These
tests inject faults — failing backends, flaky stores, contended locks —
and assert the game keeps serving through all of them.
"""

import asyncio
import dataclasses
import json
import random

import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.rounds import ContentBackend
from cassmantle_tpu.engine.store import MemoryStore


class FlakyBackend(ContentBackend):
    """Fails the first ``failures`` generate calls, then delegates."""

    def __init__(self, failures: int, image_size: int = 32) -> None:
        self.remaining_failures = failures
        self.inner = FakeContentBackend(image_size=image_size)
        self.calls = 0

    async def generate(self, seed, is_seed):
        self.calls += 1
        if self.remaining_failures > 0:
            self.remaining_failures -= 1
            raise RuntimeError("injected generation failure")
        return await self.inner.generate(seed, is_seed)


class DeadBackend(ContentBackend):
    async def generate(self, seed, is_seed):
        raise RuntimeError("device lost")


class FlakyStore(MemoryStore):
    """MemoryStore that raises on a seeded fraction of mutating ops
    AFTER startup completes (``arm()``)."""

    def __init__(self, fail_rate: float, seed: int = 0) -> None:
        super().__init__()
        self.fail_rate = fail_rate
        self.rng = random.Random(seed)
        self.armed = False

    def _maybe_fail(self):
        if self.armed and self.rng.random() < self.fail_rate:
            raise ConnectionError("injected store failure")

    async def hset(self, key, field=None, value=None, mapping=None):
        self._maybe_fail()
        return await super().hset(key, field, value, mapping)

    async def hdel(self, key, *fields):
        self._maybe_fail()
        return await super().hdel(key, *fields)

    async def setex(self, key, ttl, value):
        self._maybe_fail()
        return await super().setex(key, ttl, value)


def make_game(backend, store=None, time_per_prompt=2.0, retries=2):
    cfg = _tiny_config()
    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, time_per_prompt=time_per_prompt,
    ))
    store = store if store is not None else MemoryStore()
    game = Game(cfg, store, backend, hash_embed, hash_similarity)
    game.rounds.max_retries = retries
    game.rounds.retry_backoff_s = 0.0
    return game


@pytest.mark.asyncio
async def test_transient_generation_failure_recovers_via_retry():
    """A backend that fails once per call site still produces a round:
    the regeneration retry (reference ≤5 API retries) absorbs it."""
    backend = FlakyBackend(failures=1)
    game = make_game(backend)
    await game.rounds.startup()
    assert await game.rounds.fetch_current_prompt() is not None
    assert backend.calls >= 2              # one failure + one success


@pytest.mark.asyncio
async def test_buffer_failure_replays_old_round():
    """Generation dead at buffer time -> promote is a no-op and the
    current round replays unchanged (skip-don't-crash)."""
    backend = FlakyBackend(failures=0)
    game = make_game(backend)
    await game.rounds.startup()
    before = await game.rounds.fetch_current_prompt()

    game.rounds.backend = DeadBackend()
    await game.rounds.buffer_contents()     # swallows the failure
    await game.rounds.promote_buffer()      # no buffer -> replay
    after = await game.rounds.fetch_current_prompt()
    assert after["tokens"] == before["tokens"]


@pytest.mark.asyncio
async def test_rollover_with_dead_backend_keeps_game_playable():
    """Full rollover with a dead backend: clock restarts, reset flag
    fires, sessions reset, content still served."""
    game = make_game(FlakyBackend(failures=0))
    await game.rounds.startup()
    game.rounds.backend = DeadBackend()

    await game.rounds.buffer_contents()
    await game.rounds.rollover()
    assert await game.rounds.remaining() > 0          # clock restarted
    assert await game.rounds.fetch_current_prompt() is not None
    img = await game.rounds.fetch_current_image()
    assert img.shape[-1] == 3


@pytest.mark.asyncio
async def test_lock_contention_skips_not_errors():
    """While another worker holds buffer/promotion locks, this worker's
    buffer + promote SKIP silently (reference LockError -> skip,
    backend.py:123-125) and leave state untouched."""
    store = MemoryStore()
    backend = FakeContentBackend(image_size=32)
    game = make_game(backend, store=store)
    await game.rounds.startup()
    calls_before = backend.calls

    async with store.lock("buffer_lock", timeout=30.0,
                          blocking_timeout=0.05):
        await game.rounds.buffer_contents()           # lock held: skip
    assert backend.calls == calls_before

    await game.rounds.buffer_contents()               # lock free: works
    async with store.lock("promotion_lock", timeout=30.0,
                          blocking_timeout=0.05):
        before = await game.rounds.fetch_current_prompt()
        await game.rounds.promote_buffer()            # lock held: skip
        assert (await game.rounds.fetch_current_prompt())["tokens"] \
            == before["tokens"]
    await game.rounds.promote_buffer()                # lock free: promotes
    assert (await game.rounds.fetch_current_prompt())["tokens"] \
        != before["tokens"]


class FailOnWrite(MemoryStore):
    """Raises on the Nth hset call (counting from 1)."""

    def __init__(self, fail_on_call: int) -> None:
        super().__init__()
        self.fail_on_call = fail_on_call
        self.count = 0
        self.armed = False

    async def hset(self, key, field=None, value=None, mapping=None):
        if self.armed:
            self.count += 1
            if self.count == self.fail_on_call:
                raise ConnectionError("injected write failure")
        return await super().hset(key, field, value, mapping)


@pytest.mark.asyncio
async def test_half_promotion_rolls_back_to_consistent_pair():
    """Store dies between the prompt and image current-slot writes: the
    prompt write must roll back so the served (prompt, image) pair stays
    consistent, and the buffer survives for the next attempt."""
    store = FailOnWrite(fail_on_call=2)   # 1st armed hset = prompt.current
    game = make_game(FakeContentBackend(image_size=32), store=store)
    await game.rounds.startup()
    before = await game.rounds.fetch_current_prompt()
    await game.rounds.buffer_contents()

    store.armed = True                     # fail on the image write
    await game.rounds.promote_buffer()     # swallowed by the broad except
    store.armed = False

    after = await game.rounds.fetch_current_prompt()
    assert after["tokens"] == before["tokens"]          # rolled back
    assert await store.hget("prompt", "next") is not None  # buffer intact
    await game.rounds.promote_buffer()     # healthy store: promotes now
    final = await game.rounds.fetch_current_prompt()
    assert final["tokens"] != before["tokens"]


@pytest.mark.asyncio
async def test_retry_deadline_bounds_lock_hold_time():
    """_generate's retry loop gives up before 0.8x lock_timeout so the
    lock can't expire mid-retry (multi-worker write interleaving)."""
    import time

    backend = DeadBackend()
    game = make_game(backend, retries=50)
    # this test pins the retry DEADLINE, not the breaker (which has its
    # own arm_fast_breaker tests below): with the breaker armed, the
    # jittered backoff stream decides whether 5 attempts fit inside the
    # 0.8 s deadline — when they do, the breaker opens first and
    # CircuitOpen beats the expected deadline RuntimeError (observed
    # flake under load). Disarm it so the deadline path is what runs.
    game.rounds.breaker = None
    game.rounds.retry_backoff_s = 0.2
    game.rounds.lock_timeout = 1.0         # deadline = 0.8 s
    t0 = time.monotonic()
    with pytest.raises(RuntimeError):
        await game.rounds._generate("seed", True)
    assert time.monotonic() - t0 < 2.0     # not 50 x backoff


class CountingDeadBackend(ContentBackend):
    """DeadBackend that counts how often it is actually dialed."""

    def __init__(self) -> None:
        self.calls = 0

    async def generate(self, seed, is_seed):
        self.calls += 1
        raise RuntimeError("device lost")


def arm_fast_breaker(game, threshold=2, reset_s=0.1):
    """Swap in a breaker that trips after ``threshold`` failures and
    half-opens after ``reset_s`` — wired into BOTH the supervisor (the
    /readyz signal) and the round manager (the generation guard), like
    production wiring in Game.__init__."""
    from cassmantle_tpu.utils.circuit import CircuitBreaker

    breaker = CircuitBreaker("content", failure_threshold=threshold,
                             window_s=60.0, reset_timeout_s=reset_s)
    game.supervisor.content_breaker = breaker
    game.rounds.breaker = breaker
    return breaker


@pytest.mark.asyncio
async def test_breaker_trips_reserve_rotates_then_recovers():
    """The ISSUE 2 acceptance path end to end: backend dies after N good
    rounds -> breaker trips within one window -> consecutive degraded
    promotions serve DIFFERENT reserve rounds on the normal clock (no
    identical back-to-back prompts, no backend dials) -> backend heals ->
    one half-open probe restores fresh generation and readiness."""
    backend = FlakyBackend(failures=0)
    game = make_game(backend, retries=2)
    breaker = arm_fast_breaker(game, threshold=2, reset_s=0.1)
    game.rounds.rng = random.Random(42)   # deterministic seed/story line

    await game.rounds.startup()           # archives round 1
    for _ in range(2):                    # archive rounds 2 and 3
        await game.rounds.buffer_contents()
        await game.rounds.rollover()
    assert await game.reserve.size() == 3
    assert not game.supervisor.degraded

    # -- backend goes dark: one buffer attempt (2 retried failures) trips
    dead = CountingDeadBackend()
    game.rounds.backend = dead
    await game.rounds.buffer_contents()   # swallowed; breaker trips
    assert breaker.state == "open"
    assert game.supervisor.degraded      # what /readyz surfaces as 503
    dials_after_trip = dead.calls

    # -- degraded rounds: reserve rotation, not replay, not backend dials
    served = []
    for _ in range(3):
        await game.rounds.buffer_contents()     # fast-fail (breaker open)
        await game.rounds.rollover()            # promotes from reserve
        prompt = await game.rounds.fetch_current_prompt()
        served.append(tuple(prompt["tokens"]))
        assert await game.rounds.remaining() > 0    # clock keeps running
    assert dead.calls == dials_after_trip    # open breaker = no dials
    for a, b in zip(served, served[1:]):
        assert a != b, "degraded promotions must rotate, not replay"

    # -- backend heals: one half-open probe restores full service
    game.rounds.backend = FlakyBackend(failures=0)
    await asyncio.sleep(0.15)             # past reset_timeout_s
    assert breaker.state == "half_open"
    await game.rounds.buffer_contents()   # the probe: succeeds, closes
    assert breaker.state == "closed"
    assert not game.supervisor.degraded   # /readyz OK again
    before = await game.rounds.fetch_current_prompt()
    await game.rounds.rollover()          # freshly generated round serves
    after = await game.rounds.fetch_current_prompt()
    assert after["tokens"] != before["tokens"]


@pytest.mark.asyncio
async def test_reserve_empty_falls_back_to_reference_replay():
    """Dead backend from the very first buffer + nothing archived beyond
    the current round: degradation bottoms out at the reference's replay
    semantics (same round again), never a crash."""
    game = make_game(FlakyBackend(failures=0), retries=1)
    await game.rounds.startup()           # only round ever generated
    game.rounds.backend = DeadBackend()
    before = await game.rounds.fetch_current_prompt()
    await game.rounds.buffer_contents()
    await game.rounds.rollover()          # reserve only holds the current
    after = await game.rounds.fetch_current_prompt()
    assert after["tokens"] == before["tokens"]


@pytest.mark.asyncio
async def test_open_breaker_skips_retry_backoff():
    """With the breaker open, _generate fails fast (CircuitOpen aborts
    the retry loop) instead of burning max_retries x backoff inside the
    buffer lock."""
    import time as _time

    from cassmantle_tpu.utils.circuit import CircuitOpen

    game = make_game(FlakyBackend(failures=0), retries=50)
    game.rounds.retry_backoff_s = 0.2
    breaker = arm_fast_breaker(game, threshold=1, reset_s=60.0)
    game.rounds.backend = DeadBackend()
    breaker.record_failure()                        # trip it
    assert breaker.state == "open"
    t0 = _time.monotonic()
    with pytest.raises(CircuitOpen):
        await game.rounds._generate("seed", True)
    assert _time.monotonic() - t0 < 0.1             # not 50 x 0.2 s backoff


@pytest.mark.asyncio
async def test_hung_scorer_dispatch_fails_at_deadline_not_forever():
    """Inject a wedged scorer handler (the hang-not-raise failure
    utils/health.py documents): pending submits fail at their deadline,
    the watchdog degrades the supervisor, and a fresh dispatch thread
    serves the next batch."""
    import threading

    from cassmantle_tpu.serving.queue import BatchingQueue, DeadlineExceeded
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    release = threading.Event()

    def wedged_scorer(items):
        if "wedge" in items:
            release.wait(timeout=10.0)
        return [0.0 for _ in items]

    sup = ServingSupervisor(degraded_cooldown_s=30.0)
    q = BatchingQueue(wedged_scorer, max_batch=4, max_delay_ms=1,
                      default_deadline_s=0.2, hang_timeout_s=2.0,
                      supervisor=sup, name="faultscore")
    with pytest.raises(DeadlineExceeded):
        await q.submit("wedge")
    release.set()                       # unwedge the disowned call
    await q.stop()


# -- the same drills on REAL chaos fault points (ISSUE 12) -----------------
# The monkeypatch setups above predate the chaos subsystem; these ports
# drive the identical degradation paths through the armed plan instead
# of swapping backends — what `CASSMANTLE_CHAOS` does to a live worker.

@pytest.fixture()
def _chaos():
    from cassmantle_tpu import chaos

    chaos.disarm()
    yield chaos
    chaos.disarm()


@pytest.mark.asyncio
async def test_chaos_point_transient_failure_recovers_via_retry(_chaos):
    """The FlakyBackend(failures=1) drill via the ``round.generate``
    fault point: one injected failure, the retry absorbs it, the round
    generates — and the backend itself was only dialed once (the
    injection fires BEFORE the device dial)."""
    backend = FakeContentBackend(image_size=32)
    game = make_game(backend)
    _chaos.configure("seed=1;round.generate=raise:times=1")
    await game.rounds.startup()
    assert await game.rounds.fetch_current_prompt() is not None
    assert backend.calls == 1
    assert [f["point"] for f in _chaos.plan().schedule()] \
        == ["round.generate"]


@pytest.mark.asyncio
async def test_chaos_dead_generation_trips_breaker_then_recovers(_chaos):
    """The DeadBackend drill via chaos: a p=1 flake trips the breaker
    (no backend dials while open), degraded promotions rotate the
    reserve, and DISARMING the plan is the 'device heals' lever — the
    half-open probe restores fresh rounds."""
    backend = FakeContentBackend(image_size=32)
    game = make_game(backend, retries=2)
    breaker = arm_fast_breaker(game, threshold=2, reset_s=0.05)
    game.rounds.rng = random.Random(42)
    await game.rounds.startup()
    for _ in range(2):
        await game.rounds.buffer_contents()
        await game.rounds.rollover()
    assert await game.reserve.size() == 3
    dials_before = backend.calls

    _chaos.configure("seed=1;round.generate=raise")
    await game.rounds.buffer_contents()      # both retries injected
    assert breaker.state == "open"
    assert backend.calls == dials_before     # injection precedes dials
    before = await game.rounds.fetch_current_prompt()
    await game.rounds.rollover()             # reserve rotation
    after = await game.rounds.fetch_current_prompt()
    assert after["tokens"] != before["tokens"]

    _chaos.disarm()                          # the device heals
    await asyncio.sleep(0.1)
    assert breaker.state == "half_open"
    await game.rounds.buffer_contents()      # probe succeeds, closes
    assert breaker.state == "closed"
    assert backend.calls > dials_before


@pytest.mark.asyncio
async def test_chaos_wedged_dispatch_deadline_then_watchdog(_chaos):
    """The wedged-scorer drill via the ``queue.dispatch`` fault point:
    the wedge holds the REAL dispatch thread, the pending submit fails
    at its deadline, the watchdog declares the wedge (supervisor
    overrun + thread replacement), and a released plan serves the next
    batch on the fresh thread."""
    from cassmantle_tpu.serving.queue import (
        BatchingQueue,
        DeadlineExceeded,
        _DispatchWorker,
    )
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    _chaos.configure("seed=1;queue.dispatch=wedge:times=1,wedge_s=10")
    sup = ServingSupervisor(degraded_cooldown_s=30.0)
    q = BatchingQueue(lambda items: [0.0 for _ in items], max_batch=4,
                      max_delay_ms=1, default_deadline_s=0.2,
                      hang_timeout_s=0.3, supervisor=sup,
                      name="chaosscore",
                      dispatcher=_DispatchWorker(
                          name="chaos.dispatch_worker"))
    with pytest.raises(DeadlineExceeded):
        await q.submit("wedge-me")
    deadline = asyncio.get_running_loop().time() + 5.0
    while not sup.status()["watchdog"]["overruns"] and \
            asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.05)
    assert sup.status()["watchdog"]["overruns"] >= 1
    assert sup.degraded
    _chaos.release("queue.dispatch")
    assert await q.submit("after") == 0.0    # fresh thread dispatches
    await q.stop()


@pytest.mark.asyncio
async def test_interrupted_promotion_retry_finishes_without_double_promote():
    """Idempotent promotion (ISSUE 12): a worker killed after the
    current-slot writes + promoted_gen marker but before the cleanup
    leaves 'next' in place. The retrying promote must FINISH the tail —
    image version bumped (clients refetch), episode advanced ONCE,
    buffer cleaned — and never re-run the promotion."""
    store = MemoryStore()
    game = make_game(FakeContentBackend(image_size=32), store=store)
    await game.rounds.startup()
    await game.rounds.buffer_contents()

    # simulate the crash window: current slots + marker written, then
    # death before version bump / buffer cleanup
    prompt_next = await store.hget("prompt", "next")
    image_next = await store.hget("image", "next")
    next_gen = await store.hget("prompt", "next_gen")
    assert next_gen is not None
    await store.hset("prompt", "current", prompt_next)
    await store.hset("image", "current", image_next)
    await store.hset("prompt", "promoted_gen", next_gen)
    episode = int(await store.hget("story", "episode"))
    version = await game.rounds.current_image_version()

    await game.rounds.promote_buffer()       # the retry
    assert await store.hget("prompt", "next") is None
    assert await store.hget("prompt", "next_gen") is None
    assert await game.rounds.current_image_version() > version
    assert int(await store.hget("story", "episode")) == episode + 1
    served = await game.rounds.fetch_current_prompt()
    assert json.loads(prompt_next.decode())["tokens"] \
        == served["tokens"]

    # a FURTHER promote with no buffer replays; the episode counter
    # must not creep
    await game.rounds.promote_buffer()
    assert int(await store.hget("story", "episode")) == episode + 1


@pytest.mark.asyncio
async def test_chaos_rounds_with_random_faults():
    """Chaos drive: several fast rounds with a backend failing ~40% of
    calls and a store failing ~10% of mutations (content writes AND the
    clock's setex). The invariant through every round: current content
    exists — some rounds replay, some ticks skip, none crash, the timer
    survives."""
    store = FlakyStore(fail_rate=0.10, seed=7)
    backend = FlakyBackend(failures=0)
    game = make_game(backend, store=store, time_per_prompt=0.4, retries=1)
    await game.rounds.startup()
    store.armed = True
    rng = random.Random(3)

    task = game.rounds.start(tick=0.05)
    try:
        for _ in range(10):
            # re-arm random failures on the generation path
            if rng.random() < 0.4:
                game.rounds.backend = DeadBackend()
            else:
                game.rounds.backend = backend
            await asyncio.sleep(0.15)
            prompt = await game.rounds.fetch_current_prompt()
            assert prompt["tokens"]
            img = await game.rounds.fetch_current_image()
            assert img.size > 0
        assert not task.done()                         # timer never died
    finally:
        await game.rounds.stop()
