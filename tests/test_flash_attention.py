"""Flash-attention kernel parity vs the XLA reference path (interpret mode
on CPU; the same kernel compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.attention import xla_attention
from cassmantle_tpu.ops.flash_attention import (
    BLOCK_K,
    BLOCK_Q,
    flash_attention,
    flash_attention_ok,
)


def _rand_qkv(key, batch, seq, heads, dim, dtype=jnp.float32, seq_k=None):
    ks = jax.random.split(key, 3)
    seq_k = seq_k or seq
    q = jax.random.normal(ks[0], (batch, seq, heads, dim), dtype)
    k = jax.random.normal(ks[1], (batch, seq_k, heads, dim), dtype)
    v = jax.random.normal(ks[2], (batch, seq_k, heads, dim), dtype)
    return q, k, v


def test_ok_predicate():
    q, k, _ = _rand_qkv(jax.random.PRNGKey(0), 1, BLOCK_Q, 2, 64)
    assert flash_attention_ok(q, k)
    q2, k2, _ = _rand_qkv(jax.random.PRNGKey(0), 1, 77, 2, 64)
    assert not flash_attention_ok(q2, k2)  # not block-divisible
    q3 = q[0]
    assert not flash_attention_ok(q3, k[0])  # needs batch dim


@pytest.mark.parametrize("seq,heads,dim", [
    (BLOCK_Q, 2, 64),          # single block
    (2 * BLOCK_Q, 1, 40),      # SD1.5 head_dim at level 0, 2 k-blocks
    (4 * BLOCK_Q, 2, 80),      # multi-block, SD1.5 level-1 head_dim
])
def test_flash_matches_xla(seq, heads, dim):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 2, seq, heads, dim)
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_cross_lengths():
    """Sq != Sk (both block-divisible)."""
    q, k, v = _rand_qkv(
        jax.random.PRNGKey(2), 1, BLOCK_Q, 2, 64, seq_k=2 * BLOCK_K
    )
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_bf16():
    q, k, v = _rand_qkv(
        jax.random.PRNGKey(3), 1, BLOCK_Q, 2, 64, dtype=jnp.bfloat16
    )
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_flash_extreme_logits_stable():
    """Online softmax must survive large logit magnitudes."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), 1, BLOCK_Q, 1, 64)
    q = q * 30.0
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
    )


def test_causal_decode_alignment():
    """causal=True with s_q != s_k (cached decode: queries are the LAST
    s_q positions) must use a bottom-right-aligned band — the single last
    query sees every key, and the general case matches a full-sequence
    causal run restricted to its last rows."""
    from cassmantle_tpu.ops.attention import multi_head_attention as attention

    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 2, 6, 2, 8)
    full = attention(q, k, v, causal=True, use_flash=False)
    # decode step: last query only, full KV — equals last row of full run
    one = attention(q[:, -1:], k, v, causal=True, use_flash=False)
    np.testing.assert_allclose(
        np.asarray(one), np.asarray(full[:, -1:]), atol=1e-6, rtol=1e-6)
    # chunked decode: last 3 queries vs full KV
    tail = attention(q[:, -3:], k, v, causal=True, use_flash=False)
    np.testing.assert_allclose(
        np.asarray(tail), np.asarray(full[:, -3:]), atol=1e-6, rtol=1e-6)


def test_flash_cross_ragged_kv_matches_xla():
    """Ragged-S_k cross-attention (the UNet's text context, S_k=77):
    K/V pad into one block and the kernel's kv_len mask makes the
    result EXACT vs the XLA reference — pad columns contribute
    nothing to the softmax."""
    from cassmantle_tpu.ops.flash_attention import (
        flash_cross_attention,
        flash_cross_ok,
    )

    for sk in (77, 7, 130):
        q, k, v = _rand_qkv(jax.random.PRNGKey(5), 2, BLOCK_Q, 2, 40,
                            seq_k=sk)
        assert flash_cross_ok(q, k), sk
        out = flash_cross_attention(q, k, v, interpret=True)
        ref = xla_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5,
            err_msg=f"{sk=}")


def test_flash_cross_ok_predicate():
    from cassmantle_tpu.ops.flash_attention import (
        CROSS_BLOCK_K,
        flash_cross_ok,
    )

    q, k, _ = _rand_qkv(jax.random.PRNGKey(6), 1, BLOCK_Q, 2, 64,
                        seq_k=77)
    assert flash_cross_ok(q, k)
    # short ALIGNED S_k (128..896) also belongs here: too small for the
    # plain kernel's 1024-blocks, still worth keeping out of HBM
    q2, k2, _ = _rand_qkv(jax.random.PRNGKey(6), 1, BLOCK_Q, 2, 64,
                          seq_k=CROSS_BLOCK_K)
    assert flash_cross_ok(q2, k2)
    from cassmantle_tpu.ops.flash_attention import flash_cross_attention

    out = flash_cross_attention(q2, k2, k2, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(xla_attention(q2, k2, k2)),
        atol=2e-5, rtol=2e-5)
    # full-block K/V stays with the plain kernel
    q4, k4, _ = _rand_qkv(jax.random.PRNGKey(6), 1, BLOCK_Q, 2, 64)
    assert not flash_cross_ok(q4, k4)
    # short query axis -> XLA path
    q3, k3, _ = _rand_qkv(jax.random.PRNGKey(6), 1, 64, 2, 64, seq_k=77)
    assert not flash_cross_ok(q3, k3)


def test_dispatcher_routes_ragged_cross_attention():
    """multi_head_attention with use_flash=True and ragged K/V must hit
    the cross kernel (numerics equal XLA) rather than falling back."""
    from cassmantle_tpu.ops.attention import multi_head_attention

    q, k, v = _rand_qkv(jax.random.PRNGKey(7), 1, BLOCK_Q, 2, 40,
                        seq_k=77)
    out = multi_head_attention(q, k, v, use_flash=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_cross_kill_switch(monkeypatch):
    """CASSMANTLE_NO_FLASH_CROSS reverts ragged cross-attention to the
    XLA path (operator insurance for a misbehaving kernel). Routing is
    asserted directly: the cross kernel must not be INVOKED when the
    switch is set ('0' and unset mean on), since the two paths are
    parity-equal by design and output comparison can't see routing."""
    import cassmantle_tpu.ops.flash_attention as fa_mod
    from cassmantle_tpu.ops.attention import multi_head_attention

    calls = []
    real = fa_mod.flash_cross_attention
    monkeypatch.setattr(
        fa_mod, "flash_cross_attention",
        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    q, k, v = _rand_qkv(jax.random.PRNGKey(8), 1, BLOCK_Q, 2, 40,
                        seq_k=77)
    monkeypatch.setenv("CASSMANTLE_NO_FLASH_CROSS", "1")
    off = multi_head_attention(q, k, v, use_flash=True)
    assert not calls, "kill switch set but cross kernel was invoked"
    monkeypatch.setenv("CASSMANTLE_NO_FLASH_CROSS", "0")  # conventional re-enable
    on = multi_head_attention(q, k, v, use_flash=True)
    assert calls, "switch '0' must mean enabled"
    np.testing.assert_allclose(np.asarray(on), np.asarray(off),
                               atol=2e-5, rtol=2e-5)
